"""CLI for the program-contract analyzer (analysis/programs.py;
docs/ANALYSIS.md "Layer 2").

    python -m distributed_ddpg_tpu.tools.proganalyze                # check
    python -m distributed_ddpg_tpu.tools.proganalyze --update-golden
    python -m distributed_ddpg_tpu.tools.proganalyze --programs 'learner.*'
    python -m distributed_ddpg_tpu.tools.proganalyze --changed-only HEAD

Exit codes mirror tools.lint: 0 = clean, 2 = findings, 1 = usage error.
Unlike tools.lint this DOES import jax (it traces the real programs) —
but it never compiles or executes one: `jax.make_jaxpr` + `.lower()`
only, so a full live-tree run stays inside a 30 s CPU budget.

On the default registry the CLI also runs the static `recompile-hazard`
rule (analysis/progrules.py) over the package, so one command covers all
four program-contract checks; `scripts/proganalyze_gate.sh` wraps this
as the CI gate and `tools.runs programs` renders the JSON artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import List, Optional, Sequence

_PACKAGE_ROOT = Path(__file__).resolve().parent.parent
_REPO_ROOT = _PACKAGE_ROOT.parent
_DEFAULT_GOLDEN = _REPO_ROOT / "tests" / "golden_programs"

# What --changed-only watches WITHOUT importing jax: the spec-owner
# modules (kept in sync with programs.SPEC_MODULES — test_programs.py
# pins the correspondence) plus the analyzer itself and the goldens.
_OWNER_FILES = (
    "distributed_ddpg_tpu/parallel/learner.py",
    "distributed_ddpg_tpu/parallel/megastep.py",
    "distributed_ddpg_tpu/parallel/superstep.py",
    "distributed_ddpg_tpu/replay/device.py",
    "distributed_ddpg_tpu/actors/device_pool.py",
    "distributed_ddpg_tpu/serve/server.py",
    "distributed_ddpg_tpu/ondevice.py",
)
_WATCH_PREFIXES = (
    "distributed_ddpg_tpu/analysis/",
    "distributed_ddpg_tpu/tools/proganalyze.py",
    "tests/golden_programs/",
)


def _prepare_jax(devices: int) -> None:
    """Force a multi-device CPU platform BEFORE the jax backend
    initializes. Two steps (the tests/conftest.py discipline): XLA_FLAGS
    for the fake device count, then jax.config.update AFTER import —
    this image's site customization registers a remote 'axon' TPU
    platform that overrides the JAX_PLATFORMS env var."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def _load_specs(spec_ref: str):
    """Resolve `module:callable` or `path/to/file.py:callable` to a spec
    list — the hook the broken-fixture tests use to point the CLI at a
    registry other than the live tree's."""
    mod_part, _, attr = spec_ref.partition(":")
    attr = attr or "default_specs"
    if mod_part.endswith(".py"):
        import importlib.util

        p = Path(mod_part)
        spec = importlib.util.spec_from_file_location(p.stem, p)
        if spec is None or spec.loader is None:
            raise ImportError(f"cannot load {mod_part}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        import importlib

        mod = importlib.import_module(mod_part)
    return getattr(mod, attr)()


def _changed_scope(ref: str) -> Optional[List[str]]:
    """Owner files (package-relative) touched vs `ref`, or None meaning
    'everything' (an analyzer/golden/tooling change invalidates every
    fingerprint). Empty list = nothing relevant changed. Runs BEFORE any
    jax import so the no-op pre-commit path stays sub-second."""
    from distributed_ddpg_tpu.analysis.engine import git_changed_files

    changed = git_changed_files(_REPO_ROOT, ref)
    if changed is None:
        raise RuntimeError(
            f"--changed-only needs a git checkout and a valid ref "
            f"(git diff --name-only {ref} failed)"
        )
    rel = []
    for c in changed:
        try:
            rel.append(Path(c).resolve().relative_to(_REPO_ROOT).as_posix())
        except ValueError:
            continue
    if any(r.startswith(_WATCH_PREFIXES) for r in rel):
        return None
    return [r for r in rel if r in _OWNER_FILES]


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_ddpg_tpu.tools.proganalyze",
        description=__doc__.split("\n\n")[0],
    )
    parser.add_argument(
        "--golden", type=Path, default=_DEFAULT_GOLDEN, metavar="DIR",
        help="golden fingerprint directory "
             "(default: <repo>/tests/golden_programs)",
    )
    parser.add_argument(
        "--update-golden", action="store_true",
        help="rewrite the golden fingerprints from the current trace and "
             "prune stale ones — review/commit the diff",
    )
    parser.add_argument(
        "--json", type=Path, default=None, metavar="FILE",
        help="also write the machine-readable report JSON here",
    )
    parser.add_argument(
        "--programs", default=None, metavar="NAMES",
        help="comma-separated program names (exact or glob, e.g. "
             "'learner.*'); scoped runs skip the stale-golden sweep",
    )
    parser.add_argument(
        "--specs", default=None, metavar="MODULE:CALLABLE",
        help="alternate spec registry (module path or .py file); default: "
             "the live default_specs() registry",
    )
    parser.add_argument(
        "--changed-only", nargs="?", const="HEAD", default=None,
        metavar="REF",
        help="scope to programs whose owner module changed vs the git ref "
             "(default HEAD); exits 0 without importing jax when nothing "
             "relevant changed",
    )
    parser.add_argument(
        "--list", action="store_true",
        help="print the registered program specs and exit",
    )
    parser.add_argument(
        "--devices", type=int, default=8,
        help="virtual CPU device count to force (default 8, matching "
             "tests/conftest.py)",
    )
    parser.add_argument(
        "--quiet", action="store_true",
        help="suppress per-program detail (summary + exit code only)",
    )
    args = parser.parse_args(argv)

    only: Optional[List[str]] = None
    if args.programs:
        only = [p.strip() for p in args.programs.split(",") if p.strip()]

    if args.changed_only is not None:
        try:
            scope = _changed_scope(args.changed_only)
        except RuntimeError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if scope == []:
            print(
                f"proganalyze: no program-owning module changed vs "
                f"{args.changed_only} — nothing to analyze"
            )
            return 0
        changed_owners = None if scope is None else set(scope)
    else:
        changed_owners = False  # sentinel: no scoping requested

    _prepare_jax(args.devices)
    from distributed_ddpg_tpu.analysis import programs as prog_lib

    try:
        specs = _load_specs(args.specs) if args.specs else (
            prog_lib.default_specs()
        )
    except Exception as e:
        print(f"error: loading specs failed: {e!r}", file=sys.stderr)
        return 1

    if changed_owners not in (False, None):
        # Scope to the changed owners' programs via names, so analyze()
        # knows the run is partial (skips the stale-golden sweep).
        scoped_names = [
            s.name for s in specs
            if "distributed_ddpg_tpu/" + s.owner in changed_owners
        ]
        if not scoped_names:
            print("proganalyze: changed modules own no registered "
                  "programs — nothing to analyze")
            return 0
        if only is None:
            only = scoped_names
        else:
            # --programs composes as a filter WITHIN the changed scope —
            # fnmatch like everywhere else, and say so when the
            # intersection is empty rather than green-lighting a run
            # that analyzed nothing.
            import fnmatch

            only = [
                n for n in scoped_names
                if any(fnmatch.fnmatch(n, pat) for pat in only)
            ]
            if not only:
                print("proganalyze: no program of the changed modules "
                      "matches --programs — nothing to analyze")
                return 0

    if args.list:
        for s in specs:
            group = f"  [beat:{s.beat_group}]" if s.beat_group else ""
            print(f"{s.name:42s} {s.owner}{group}")
        return 0

    if only is not None:
        import fnmatch

        matched = {
            pat for pat in only
            if any(fnmatch.fnmatch(s.name, pat) for s in specs)
        }
        unmatched = [pat for pat in only if pat not in matched]
        if unmatched:
            print(
                f"error: --programs pattern(s) {', '.join(unmatched)} "
                "match no registered program (see --list)",
                file=sys.stderr,
            )
            return 1

    report = prog_lib.analyze(
        specs, args.golden, update_golden=args.update_golden, only=only,
        # An alternate --specs registry knows nothing about the live
        # programs: sweeping (or pruning, under --update-golden) the
        # default golden dir against it would flag/delete every
        # committed golden.
        sweep_stale=args.specs is None,
    )

    if args.specs is None:
        # Static jit-key hazards (analysis/progrules.py) over the live
        # package: the fourth program-contract check, stdlib-fast. Only
        # meaningful for the default registry — fixture registries check
        # the analyzer, not the package.
        from distributed_ddpg_tpu.analysis import run_lint

        lint = run_lint(_PACKAGE_ROOT, rule_names=["recompile-hazard"])
        for f in lint.unsuppressed:
            if f.rule != "recompile-hazard":
                continue
            report.findings.append(prog_lib.ProgramFinding(
                f"{f.path}:{f.line}", "recompile-hazard", f.message,
            ))

    if args.json is not None:
        prog_lib.write_report(report, args.json)
    text = prog_lib.render_human(report)
    if args.quiet:
        text = text.splitlines()[-1]
    print(text)
    if report.findings:
        print(
            "proganalyze: FAIL — fix the findings, or re-run with "
            "--update-golden and review the golden diff if a collective "
            "reorder is intentional (docs/ANALYSIS.md)",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
