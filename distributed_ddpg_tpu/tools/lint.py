"""CLI for the invariant lint engine (distributed_ddpg_tpu/analysis/;
docs/ANALYSIS.md).

    python -m distributed_ddpg_tpu.tools.lint                  # lint the package
    python -m distributed_ddpg_tpu.tools.lint --json out.json  # + findings file
    python -m distributed_ddpg_tpu.tools.lint --rules timeout-discipline path/

Exit codes: 0 = clean (suppressed findings allowed), 2 = unsuppressed
findings, 1 = usage error. Pure stdlib — never imports jax; the whole
run must finish in < 5 s (tests/test_lint.py pins both).

`scripts/lint_gate.sh` wraps this as the CI gate and `tools.runs lint`
pretty-prints the emitted JSON on gate boxes.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path
from typing import Optional, Sequence

from distributed_ddpg_tpu.analysis import RULES, run_lint
from distributed_ddpg_tpu.analysis.engine import render_human, write_json

_PACKAGE_ROOT = Path(__file__).resolve().parent.parent


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_ddpg_tpu.tools.lint",
        description=__doc__.split("\n\n")[0],
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to lint (default: the installed "
             "distributed_ddpg_tpu package)",
    )
    parser.add_argument(
        "--root", type=Path, default=None,
        help="root that rule path-scoping is relative to (default: the "
             "package dir, or the common parent of explicit paths)",
    )
    parser.add_argument(
        "--docs", type=Path, default=None,
        help="docs directory for the cross-file doc rules (default: "
             "<root>/../docs when it exists)",
    )
    parser.add_argument(
        "--json", type=Path, default=None, metavar="FILE",
        help="also write the machine-readable findings JSON here",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule subset (default: all); "
             f"known: {', '.join(r.name for r in RULES)}",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--changed-only", nargs="?", const="HEAD", default=None,
        metavar="REF",
        help="lint only files changed vs the git ref (default HEAD) — "
             "the sub-second pre-commit mode; note the cross-file doc "
             "rules see only the changed subset (docs/ANALYSIS.md)",
    )
    parser.add_argument(
        "--quiet", action="store_true",
        help="suppress per-finding lines (summary + exit code only)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(f"{r.name:24s} {r.doc}")
        return 0

    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",") if r.strip()]
        known = {r.name for r in RULES}
        bad = [r for r in rule_names if r not in known]
        if bad:
            print(f"error: unknown rule(s) {', '.join(bad)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 1

    if args.paths:
        paths = args.paths
        if args.root is not None:
            root = args.root
        else:
            # Paths inside the package anchor to the PACKAGE root — the
            # path-scoped rules (typed-error's serve/ prefix, the
            # parallel/multihost.py exemption) key on package-relative
            # paths, so `lint parallel/multihost.py` must not re-anchor
            # to parallel/. Arbitrary external trees fall back to their
            # common parent.
            resolved = [p.resolve() for p in paths]
            if all(r == _PACKAGE_ROOT or r.is_relative_to(_PACKAGE_ROOT)
                   for r in resolved):
                root = _PACKAGE_ROOT
            else:
                root = Path(os.path.commonpath([str(r) for r in resolved]))
        if root.is_file():
            root = root.parent
    else:
        root = args.root or _PACKAGE_ROOT
        paths = [root]
    for p in paths:
        if not p.exists():
            print(f"error: {p} does not exist", file=sys.stderr)
            return 1
        if not p.resolve().is_relative_to(root.resolve()):
            print(f"error: {p} is outside the lint root {root} — pass "
                  "--root (rule path-scoping is root-relative)",
                  file=sys.stderr)
            return 1

    if args.changed_only is not None:
        from distributed_ddpg_tpu.analysis.engine import (
            _is_test_file,
            git_changed_files,
        )

        changed = git_changed_files(root, args.changed_only)
        if changed is None:
            print(
                f"error: --changed-only needs a git checkout and a valid "
                f"ref (git diff --name-only {args.changed_only} failed)",
                file=sys.stderr,
            )
            return 1
        rootr = root.resolve()
        # Explicit path args compose as a FILTER within the changed set
        # (same semantics as proganalyze --programs + --changed-only): a
        # pre-commit hook scoped to one subsystem must not fail on
        # unrelated changed files elsewhere in the tree.
        explicit = [p.resolve() for p in args.paths] if args.paths else None
        selected = []
        for c in changed:
            p = Path(c)
            if p.suffix != ".py" or not p.is_file():
                continue
            r = p.resolve()
            if not r.is_relative_to(rootr) or _is_test_file(rootr, r):
                continue
            if explicit is not None and not any(
                    r == e or r.is_relative_to(e) for e in explicit):
                continue
            selected.append(p)
        if not selected:
            scope = root if explicit is None else ", ".join(
                str(p) for p in args.paths)
            print(
                f"lint: no changed non-test Python files under {scope} vs "
                f"{args.changed_only} — nothing to lint"
            )
            return 0
        paths = selected

    docs = args.docs
    if docs is None:
        # Repo-anchored roots find docs/ directly under themselves;
        # package-anchored roots (no docs/ inside the package) fall back
        # to <repo>/docs via parent. Self-first, so a stray sibling docs
        # dir can never shadow the tree being linted.
        for cand in (root.resolve() / "docs", root.resolve().parent / "docs"):
            if cand.is_dir():
                docs = cand
                break

    result = run_lint(root, paths, docs_root=docs, rule_names=rule_names)
    if result.files == 0:
        # A gate that lints nothing must not read as green.
        print("error: no Python files found under the given paths",
              file=sys.stderr)
        return 1
    if args.json is not None:
        write_json(result, args.json)
    if args.quiet:
        text = render_human(result).splitlines()[-1]
    else:
        text = render_human(result)
    print(text)
    if result.unsuppressed:
        print(
            "lint: FAIL — fix the findings or suppress each with "
            "`# lint: ok(<rule>): <reason>` (docs/ANALYSIS.md)",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
