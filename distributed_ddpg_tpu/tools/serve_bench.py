"""Serve-path traffic generator (docs/SERVING.md): drive an
InferenceServer with synthetic closed-loop clients — no Gym, no learner,
no replay. The local in-process RPC front for load-testing the serving
stack by itself:

    python -m distributed_ddpg_tpu.tools.serve_bench \
        --clients=8 --duration_s=3 --max_batch=32 --max_latency_ms=5

Prints ONE JSON line: the serve_* digest (metrics.ServeStats) plus the
client-side view (served requests/sec, sheds) and an A/B against the
per-worker local act() path at the same thread count — the "what does
dynamic batching buy/cost on this box" number bench.py's BENCH_SERVE=1
mode embeds in its scaling curves.

numpy + stdlib only on the default backend (--backend=jax jits the padded
batch apply instead — the device-serving path).

--transport socket drives the NETWORK front instead (serve/front/;
docs/SERVING.md 'Network front'): each client thread opens its own
framed-TCP FrontClient connection against a local FrontServer and the
digest gains the front_*/tenant_* families plus wire_p50_ms/wire_p95_ms
— client-measured round-trip tails over the real socket, the
BENCH_SERVE row that covers the external ingress path.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
from typing import Dict, Optional, Sequence

import numpy as np

from distributed_ddpg_tpu.actors.policy import (
    NumpyPolicy,
    layout_size,
    param_layout,
)
from distributed_ddpg_tpu.serve import (
    InferenceServer,
    ServeDispatchError,
    ServeOverload,
    ServeTimeout,
)


# Reap bound for bench client threads after stop is set: generous next to
# serve_fallback_s (the longest a client blocks per request), so a join
# miss means a wedged client, not a slow one — the threads are daemons and
# the measurement is already taken either way.
_CLIENT_JOIN_S = 10.0


def _random_flat(layout, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(layout_size(layout)) * 0.1).astype(np.float32)


def run_socket_bench(
    clients: int = 8,
    duration_s: float = 3.0,
    obs_dim: int = 17,
    act_dim: int = 6,
    hidden: Sequence[int] = (256, 256),
    max_batch: int = 32,
    max_latency_ms: float = 5.0,
    queue: int = 1024,
    backend: str = "numpy",
    seed: int = 0,
    tenants: str = "",
) -> Dict[str, float]:
    """Closed-loop load over the REAL TCP front: `clients` threads, one
    persistent framed connection each, tenant ids bench-0..N-1 (or the
    names from `tenants`, round-robin). Returns the front_*/tenant_*
    digest plus client-measured wire round-trip tails."""
    from distributed_ddpg_tpu.serve.front import FrontClient, FrontError
    from distributed_ddpg_tpu.serve.front.qos import parse_tenants

    layout = param_layout(obs_dim, act_dim, tuple(hidden))
    flat = _random_flat(layout, seed)

    def make_engine():
        return InferenceServer(
            layout,
            1.0,
            max_batch=max_batch,
            max_latency_s=max_latency_ms / 1000.0,
            max_queue=queue,
            backend=backend,
            seed=seed,
        )

    from distributed_ddpg_tpu.serve.front import FrontServer

    front = FrontServer(make_engine, tenants=tenants, seed=seed)
    front.publish("bench-0", flat)
    front.start()

    names = list(parse_tenants(tenants)) if tenants else []
    stop = threading.Event()
    served = [0] * clients
    sheds = [0] * clients
    # Client-side wire latency samples (bounded: the tail computation is
    # exact over the run, not reservoir-thinned — a bench run is short).
    lats: list = [[] for _ in range(clients)]

    def client_loop(i: int) -> None:
        tenant = names[i % len(names)] if names else f"bench-{i}"
        rng = np.random.default_rng(seed + 1 + i)
        obs = rng.standard_normal((64, obs_dim)).astype(np.float32)
        try:
            cli = FrontClient(front.port, tenant=tenant, timeout_s=5.0)
        except OSError:
            return
        j = 0
        with cli:
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    cli.act(obs[j % 64])
                    served[i] += 1
                    lats[i].append(time.perf_counter() - t0)
                except FrontError:
                    sheds[i] += 1
                except (ConnectionError, OSError):
                    return
                j += 1

    threads = [
        threading.Thread(target=client_loop, args=(i,), daemon=True)
        for i in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=_CLIENT_JOIN_S)
    elapsed = time.perf_counter() - t0
    snap = front.snapshot()
    front.stop()

    all_lats = sorted(x for per in lats for x in per)

    def pct(q: float) -> float:
        if not all_lats:
            return 0.0
        return round(
            1000.0 * all_lats[min(len(all_lats) - 1, int(q * len(all_lats)))],
            3,
        )

    return {
        "clients": clients,
        "backend": backend,
        "transport": "socket",
        "served_rps": round(sum(served) / elapsed, 1),
        "client_sheds": int(sum(sheds)),
        "wire_p50_ms": pct(0.50),
        "wire_p95_ms": pct(0.95),
        **snap,
    }


def run_serve_bench(
    clients: int = 8,
    duration_s: float = 3.0,
    obs_dim: int = 17,
    act_dim: int = 6,
    hidden: Sequence[int] = (256, 256),
    max_batch: int = 32,
    max_latency_ms: float = 5.0,
    queue: int = 1024,
    backend: str = "numpy",
    seed: int = 0,
    scheduler=None,
    measure_local: bool = True,
) -> Dict[str, float]:
    """One measurement: `clients` closed-loop threads hammer the server
    for `duration_s`; returns the serve_* digest + client-side rates and
    (measure_local) the same-thread-count local-act A/B."""
    layout = param_layout(obs_dim, act_dim, tuple(hidden))
    flat = _random_flat(layout, seed)
    server = InferenceServer(
        layout,
        1.0,
        max_batch=max_batch,
        max_latency_s=max_latency_ms / 1000.0,
        max_queue=queue,
        backend=backend,
        scheduler=scheduler,
        seed=seed,
    ).start()
    server.refresh(flat)

    stop = threading.Event()
    served = [0] * clients
    sheds = [0] * clients

    def client_loop(i: int) -> None:
        cli = server.client(timeout_s=5.0)
        rng = np.random.default_rng(seed + 1 + i)
        obs = rng.standard_normal((64, obs_dim)).astype(np.float32)
        j = 0
        while not stop.is_set():
            try:
                cli.act(obs[j % 64])
                served[i] += 1
            except (ServeOverload, ServeTimeout, ServeDispatchError):
                sheds[i] += 1
            j += 1

    threads = [
        threading.Thread(target=client_loop, args=(i,), daemon=True)
        for i in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=_CLIENT_JOIN_S)
    elapsed = time.perf_counter() - t0
    snap = server.snapshot()
    server.close()

    result: Dict[str, float] = {
        "clients": clients,
        "backend": backend,
        "served_rps": round(sum(served) / elapsed, 1),
        "client_sheds": int(sum(sheds)),
        **snap,
    }
    if measure_local:
        result["local_act_rps"] = round(
            _measure_local_act(layout, flat, clients, min(duration_s, 1.0),
                               obs_dim, seed),
            1,
        )
        if result["local_act_rps"]:
            result["served_vs_local"] = round(
                result["served_rps"] / result["local_act_rps"], 3
            )
    return result


def _measure_local_act(layout, flat, threads_n: int, duration_s: float,
                       obs_dim: int, seed: int) -> float:
    """The A/B denominator: per-worker act() — each thread owns its own
    NumpyPolicy mirror (exactly the worker topology) and acts closed-loop."""
    stop = threading.Event()
    counts = [0] * threads_n

    def local_loop(i: int) -> None:
        policy = NumpyPolicy(layout, 1.0)
        policy.load_flat(flat)
        rng = np.random.default_rng(seed + 101 + i)
        obs = rng.standard_normal((64, obs_dim)).astype(np.float32)
        j = 0
        while not stop.is_set():
            policy(obs[j % 64])
            counts[i] += 1
            j += 1

    threads = [
        threading.Thread(target=local_loop, args=(i,), daemon=True)
        for i in range(threads_n)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=_CLIENT_JOIN_S)
    return sum(counts) / (time.perf_counter() - t0)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_ddpg_tpu.tools.serve_bench",
        description=__doc__.split("\n\n")[0],
    )
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--duration_s", type=float, default=3.0)
    parser.add_argument("--obs_dim", type=int, default=17)
    parser.add_argument("--act_dim", type=int, default=6)
    parser.add_argument("--hidden", default="256,256",
                        help="comma-separated hidden sizes")
    parser.add_argument("--max_batch", type=int, default=32)
    parser.add_argument("--max_latency_ms", type=float, default=5.0)
    parser.add_argument("--queue", type=int, default=1024)
    parser.add_argument("--backend", choices=("numpy", "jax"),
                        default="numpy")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--transport", choices=("local", "socket"), default="local",
        help="local = in-process ServeClient; socket = framed TCP "
             "through a FrontServer (the network-front path)",
    )
    parser.add_argument(
        "--tenants", default="",
        help="front tenant table (socket transport): "
             "name:priority[:rate[:burst]];...",
    )
    args = parser.parse_args(argv)
    kwargs = dict(
        clients=args.clients,
        duration_s=args.duration_s,
        obs_dim=args.obs_dim,
        act_dim=args.act_dim,
        hidden=tuple(int(x) for x in args.hidden.split(",")),
        max_batch=args.max_batch,
        max_latency_ms=args.max_latency_ms,
        queue=args.queue,
        backend=args.backend,
        seed=args.seed,
    )
    if args.transport == "socket":
        result = run_socket_bench(tenants=args.tenants, **kwargs)
    else:
        result = run_serve_bench(**kwargs)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
