"""`python -m distributed_ddpg_tpu.tools.supervise` — run a pod under
the autonomous shrink/grow supervisor (supervisor/core.py;
docs/OPERATIONS.md supervisor runbook).

    python -m distributed_ddpg_tpu.tools.supervise \\
        --procs 2 --event-log runs/supervisor.jsonl \\
        --probe-port-base 9400 --child-logs runs/children \\
        --env POD_CKPT_DIR=/ckpts/run1 \\
        --env-first POD_FAULTS='pod:1:kill@12' \\
        -- python tests/multihost_child.py {proc} {nprocs} {port} podtrain

Everything after `--` is the per-child command template; `{proc}`,
`{nprocs}`, `{port}` and `{gen}` are substituted per spawn (same
placeholders work inside --env VALUES — e.g. a per-generation log dir
`POD_LOG_DIR=/logs/gen{gen}`). `--env-first` entries apply to
generation 1 ONLY: that is where fault injection belongs, so a scripted
kill does not re-fire in every relaunched generation.

Exit codes (exits.py): 0 when the supervised run completes its budget,
75 when the supervisor itself is SIGTERMed (the running generation is
drained first), 79 with a JSON report on disk when it gives up
(crash-loop breaker or numeric budget).
"""

from __future__ import annotations

import argparse
import signal
import sys
from typing import Dict, List, Tuple

from distributed_ddpg_tpu import exits
from distributed_ddpg_tpu.supervisor import (
    PodSupervisor,
    SupervisorConfig,
    SupervisorGaveUp,
)


def _parse_env(pairs: List[str], flag: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for pair in pairs:
        key, sep, val = pair.partition("=")
        if not sep or not key:
            raise SystemExit(f"{flag} wants KEY=VALUE, got {pair!r}")
        out[key] = val
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m distributed_ddpg_tpu.tools.supervise",
        description=__doc__.split("\n\n")[0],
    )
    p.add_argument("--procs", type=int, required=True,
                   help="full-strength pod size N")
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="first relaunch backoff, seconds (doubles)")
    p.add_argument("--backoff-max", type=float, default=60.0)
    p.add_argument("--breaker-failures", type=int, default=5,
                   help="failing generations within --breaker-window "
                        "that trip the crash-loop breaker (0=off)")
    p.add_argument("--breaker-window", type=float, default=300.0)
    p.add_argument("--healthy-run", type=float, default=60.0,
                   help="generations older than this reset the "
                        "consecutive-failure count")
    p.add_argument("--max-numeric", type=int, default=0,
                   help="exit-77 relaunch budget (default: refuse)")
    p.add_argument("--max-generations", type=int, default=0,
                   help="hard generation cap, 0=unbounded")
    p.add_argument("--drain-grace", type=float, default=60.0,
                   help="after the first child exit, peers get this "
                        "long to take their own typed exits")
    p.add_argument("--kill-grace", type=float, default=10.0,
                   help="SIGTERM -> SIGKILL escalation")
    p.add_argument("--probe-host", default="127.0.0.1")
    p.add_argument("--probe-port-base", type=int, default=0,
                   help="slot i's /healthz probed at base+i "
                        "(0 disables rejoin probing — the pod can "
                        "shrink but never grows back)")
    p.add_argument("--probe-interval", type=float, default=2.0)
    p.add_argument("--probe-healthy-k", type=int, default=3,
                   help="consecutive healthy probes before rejoin")
    p.add_argument("--probe-hysteresis", type=float, default=10.0,
                   help="min continuous-healthy seconds before rejoin")
    p.add_argument("--grow-defer", type=float, default=30.0,
                   help="min running-generation age before a "
                        "stop-the-world grow resize")
    p.add_argument("--event-log", default="",
                   help="supervision JSONL (tools.runs summarize "
                        "renders it)")
    p.add_argument("--report", default="",
                   help="gave-up report path (default: alongside "
                        "--event-log)")
    p.add_argument("--child-logs", default="",
                   help="directory for per-child gen<G>_proc<P>.log")
    p.add_argument("--env", action="append", default=[],
                   metavar="KEY=VAL",
                   help="child environment override, every generation")
    p.add_argument("--env-first", action="append", default=[],
                   metavar="KEY=VAL",
                   help="child environment override, generation 1 ONLY "
                        "(fault injection lives here)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- child command template "
                        "({proc} {nprocs} {port} {gen})")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("supervise: no child command given (after --)",
              file=sys.stderr)
        return 2
    env_all = _parse_env(args.env, "--env")
    env_first = _parse_env(args.env_first, "--env-first")

    def command_builder(
        proc: int, nprocs: int, port: int, gen: int
    ) -> Tuple[List[str], Dict[str, str]]:
        subs = {"proc": proc, "nprocs": nprocs, "port": port, "gen": gen}
        argv_out = [part.format(**subs) for part in command]
        env = {k: v.format(**subs) for k, v in env_all.items()}
        if gen == 1:
            env.update(
                {k: v.format(**subs) for k, v in env_first.items()}
            )
        return argv_out, env

    cfg = SupervisorConfig(
        procs=args.procs,
        backoff_base_s=args.backoff_base,
        backoff_max_s=args.backoff_max,
        breaker_failures=args.breaker_failures,
        breaker_window_s=args.breaker_window,
        healthy_run_s=args.healthy_run,
        max_numeric=args.max_numeric,
        max_generations=args.max_generations,
        drain_grace_s=args.drain_grace,
        kill_grace_s=args.kill_grace,
        probe_host=args.probe_host,
        probe_port_base=args.probe_port_base,
        probe_interval_s=args.probe_interval,
        probe_healthy_k=args.probe_healthy_k,
        probe_hysteresis_s=args.probe_hysteresis,
        grow_defer_s=args.grow_defer,
        event_log=args.event_log,
        report_path=args.report,
        child_log_dir=args.child_logs,
    )
    sup = PodSupervisor(cfg, command_builder)

    def _on_signal(*_):
        sup.request_stop()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass  # not on the main thread (embedded callers)

    try:
        return sup.run()
    except SupervisorGaveUp as e:
        print(
            f"supervise: gave up ({e.reason}) — report: "
            f"{e.report_path or '(unwritable)'}",
            file=sys.stderr,
        )
        return exits.EXIT_SUPERVISOR_GAVE_UP


if __name__ == "__main__":
    sys.exit(main())
