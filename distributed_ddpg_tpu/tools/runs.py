"""Run-analysis CLI for the repo's JSONL/JSON artifacts.

`runs/` holds ~100 train/eval/bench files and until this module the only
tooling was hand-diffing them (how the 8-device ingest regression in
BENCH_r05 was found). Four subcommands over the schemas the repo already
produces (metrics.MetricsLogger records; bench.py result JSON — both
documented in docs/OBSERVABILITY.md):

  summarize <run.jsonl> [...]    per-run digest: record counts, steady-
                                 state rates, per-phase breakdown table
                                 (mean + p50/p95/max where recorded),
                                 ingest pipeline table, eval curve.
  compare  <a.jsonl> <b.jsonl>   side-by-side key metrics with % deltas —
                                 the A/B view for "did this PR move
                                 dispatch p95".
  gate <base.json> <cand.json>   CI regression gate over two bench.py
                                 JSONs: exit 2 when any gated key of the
                                 candidate falls more than --threshold
                                 below the baseline (or above, for
                                 lower-is-better keys prefixed '-').
  lint [findings.json]           pretty-print the invariant lint engine's
                                 findings JSON (scripts/lint_gate.sh
                                 artifact; docs/ANALYSIS.md) as the same
                                 digest tables; exit 2 on unsuppressed
                                 findings — the bench gate's contract.
  merge-trace <t0.json> ...      fuse N per-host flight-recorder traces
                                 into ONE Perfetto timeline (process
                                 track per host, clocks aligned by the
                                 startup handshake offsets —
                                 docs/OBSERVABILITY.md §4).

Pure stdlib, no numpy/jax: this must be runnable anywhere, instantly —
    python -m distributed_ddpg_tpu.tools.runs summarize runs/foo.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
from statistics import mean
from typing import Any, Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a metrics JSONL file; non-JSON lines (stray prints interleave
    with echo=True streams) are skipped, not fatal."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def load_bench(path: str) -> Dict[str, Any]:
    """A bench.py result: one JSON object. Driver wrappers (BENCH_r*.json)
    embed the object in a 'tail' string; unwrap when present so both
    shapes gate/compare identically."""
    with open(path) as f:
        obj = json.load(f)
    if "value" not in obj and isinstance(obj.get("tail"), str):
        tail = obj["tail"]
        start = tail.find('{"metric"')
        if start >= 0:
            try:
                obj = json.loads(tail[start:])
            except json.JSONDecodeError:
                pass
    return obj


def by_kind(records: Sequence[Dict[str, Any]]) -> Dict[str, List[Dict[str, Any]]]:
    out: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        out.setdefault(str(r.get("kind", "?")), []).append(r)
    return out


def phase_names(records: Sequence[Dict[str, Any]]) -> List[str]:
    names = set()
    for r in records:
        for k in r:
            if k.startswith("t_") and k.endswith("_ms"):
                names.add(k[2:-3])
    return sorted(names)


# ---------------------------------------------------------------------------
# tables
# ---------------------------------------------------------------------------


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:,.3f}" if abs(v) < 1000 else f"{v:,.1f}"
    return str(v)


def render_table(headers: Sequence[str], rows: Sequence[Sequence[Any]]) -> str:
    cells = [[_fmt(c) for c in row] for row in rows]
    widths = [
        max(len(h), *(len(r[i]) for r in cells)) if cells else len(h)
        for i, h in enumerate(headers)
    ]
    def line(row):
        return "  ".join(
            c.rjust(w) if i else c.ljust(w)
            for i, (c, w) in enumerate(zip(row, widths))
        )
    out = [line(list(headers)), line(["-" * w for w in widths])]
    out += [line(r) for r in cells]
    return "\n".join(out)


def _col(records, key) -> List[float]:
    return [
        r[key] for r in records
        if isinstance(r.get(key), (int, float))
        and not isinstance(r.get(key), bool)
    ]


def _tail_mean(vals: Sequence[float], frac: float = 0.25) -> Optional[float]:
    """Mean of the last `frac` of the series — the steady-state estimate
    (early records carry warmup/compile transients)."""
    if not vals:
        return None
    n = max(1, int(len(vals) * frac))
    return mean(vals[-n:])


# ---------------------------------------------------------------------------
# summarize
# ---------------------------------------------------------------------------

# The headline scalar columns a run summary/compare surfaces, in order.
KEY_METRICS = (
    "learner_steps_per_sec",
    "actor_steps_per_sec",
    "env_steps_per_sec",
    "buffer_fill",
    "staleness_mean",
    "critic_loss",
    "mean_q",
)

# Cumulative recovery counters (train.py recovery_fields; docs/RESILIENCE.md)
# — the run's fault history. `last` is the total; a nonzero anywhere means
# the run survived at least one injected or real failure.
RECOVERY_KEYS = (
    "actor_respawns",
    "actor_quarantined",
    "actor_unquarantined",
    "ckpt_write_retries",
    "emergency_ckpt",
    "ingest_shipper_restarts",
    "transfer_restarts",
)

# Pod-resilience counters (metrics.PodStats; docs/RESILIENCE.md pod rows)
# — present only on multi-process runs. Cumulative/gauge semantics, so the
# digest reports the LAST value; slack is the tune-the-deadline telemetry
# (trending toward 0 = pod_collective_timeout_s too tight).
POD_KEYS = (
    "pod_peer_lost",
    "pod_aborts",
    "pod_resume_step_elected",
    "pod_beats",
    "pod_collective_near_misses",
    "pod_collective_slack_p95_ms",
    # Elastic-pod events (docs/RESILIENCE.md shrink/grow state machine):
    # slice adoptions, membership transitions, and the typed degraded
    # state — also present on single-process runs that adopted a larger
    # world's slice set (the shrink-to-one case).
    "pod_slices_adopted",
    "pod_slice_adopted_step",
    "pod_shrinks",
    "pod_grows",
    "pod_state_degraded",
    # Straggler attribution (obs/aggregate.py; docs/OBSERVABILITY.md §4):
    # cumulative detections plus the last flagged host index (-1 = none).
    "pod_stragglers",
    "pod_straggler_host",
)

# Numerical-health counters (metrics.GuardrailStats; docs/RESILIENCE.md
# 'Numerical health') — present only when guardrails are armed. Cumulative,
# so the digest reports the LAST value; a nonzero rollback count means the
# run repaired itself at least once mid-flight.
GUARDRAIL_KEYS = (
    "guardrail_anomalies",
    "guardrail_nonfinite_steps",
    "guardrail_loss_spikes",
    "guardrail_skipped_updates",
    "guardrail_bad_rows",
    "guardrail_rollbacks",
    "guardrail_last_rollback_step",
    "guardrail_lr_cooldowns",
    "guardrail_source_quarantines",
)


def _drop_probe_failures(
    records: List[Dict[str, Any]], path: str
) -> List[Dict[str, Any]]:
    """Drop records carrying a TPU-probe failure tail (`probe_error` /
    `tpu_error` — the BENCH_r04/r05 shape: the harness recorded a CPU
    fallback after the TPU probe died). Their rates are fallback numbers,
    not the run's, and silently averaging them in would poison every A/B
    against a healthy baseline (BENCH_r03). Warns once per file so the
    exclusion is visible, never manual."""
    kept = [
        r for r in records
        if not (r.get("probe_error") or r.get("tpu_error"))
    ]
    dropped = len(records) - len(kept)
    if dropped:
        print(
            f"warning: {path}: skipped {dropped} record(s) with a "
            "TPU-probe failure tail (probe_error/tpu_error)",
            file=sys.stderr,
        )
    return kept


def summarize_run(path: str) -> Dict[str, Any]:
    """Machine-readable digest of one JSONL run (the CLI renders it; tests
    and future dashboards consume it directly)."""
    records = _drop_probe_failures(load_jsonl(path), path)
    kinds = by_kind(records)
    train = kinds.get("train", [])
    evals = kinds.get("eval", [])
    final = kinds.get("final", [])
    digest: Dict[str, Any] = {
        "path": path,
        "records": {k: len(v) for k, v in kinds.items()},
        "steps": (
            {"first": train[0].get("step"), "last": train[-1].get("step")}
            if train
            else {}
        ),
        "wall_time_s": records[-1].get("wall_time") if records else None,
    }
    metrics = {}
    for key in KEY_METRICS:
        vals = _col(train, key)
        if vals:
            metrics[key] = {
                "steady": _tail_mean(vals),
                "max": max(vals),
                "last": vals[-1],
            }
    digest["metrics"] = metrics

    phases = {}
    for name in phase_names(train + final):
        src = train if _col(train, f"t_{name}_ms") else final
        entry = {
            "mean_ms": _tail_mean(_col(src, f"t_{name}_ms")),
            "calls": sum(int(v) for v in _col(src, f"n_{name}")),
        }
        for q in ("p50", "p95", "max"):
            vals = _col(src, f"t_{name}_{q}")
            if vals:
                # max over intervals: the worst tail any interval saw.
                entry[f"{q}_ms"] = max(vals)
        phases[name] = entry
    digest["phases"] = phases

    ingest = {}
    ingest_keys = sorted(
        {k for r in train for k in r if k.startswith("ingest_")}
    )
    for key in ingest_keys:
        vals = _col(train, key)
        if vals:
            ingest[key] = {"steady": _tail_mean(vals), "max": max(vals)}
    digest["ingest"] = ingest

    # Transfer-scheduler digest (docs/TRANSFER.md): per-class dispatch
    # counters/tails, queue depths, and the adaptive-coalesce trajectory
    # (cap gauge + cumulative grows/shrinks).
    transfer = {}
    transfer_keys = sorted(
        {
            k for r in train for k in r
            if k.startswith("transfer_") and k not in RECOVERY_KEYS
        }
    )
    for key in transfer_keys:
        vals = _col(train, key)
        if vals:
            transfer[key] = {"steady": _tail_mean(vals), "max": max(vals)}
    digest["transfer"] = transfer

    # Pod digest (multi-process runs only): last value of each pod_*
    # counter/gauge across train+final records, plus whatever aggregation
    # keys the rank-0 `kind:"pod"` records carry (obs/aggregate.py emits
    # per-host min/max/spread families; the key set is family-templated,
    # so it is discovered, not enumerated).
    pod = {}
    pod_records = kinds.get("pod", [])
    pod_key_set = set(POD_KEYS) | {
        k for r in pod_records for k in r if k.startswith("pod_")
    }
    for key in sorted(pod_key_set):
        vals = _col(train + pod_records + kinds.get("final", []), key)
        if vals:
            pod[key] = {"last": vals[-1], "max": max(vals)}
    digest["pod"] = pod

    # Numerical-health digest (guardrail-armed runs only): last value of
    # each cumulative guardrail_* counter across train+final records.
    guardrail = {}
    for key in GUARDRAIL_KEYS:
        vals = _col(train + final, key)
        if vals:
            guardrail[key] = {"last": vals[-1], "max": max(vals)}
    digest["guardrail"] = guardrail

    # Serving digest (serve/; docs/SERVING.md): request/batch counters are
    # cumulative (report the last = total), latency/fill/depth tails are
    # interval-scoped (steady + worst interval).
    serve = {}
    serve_keys = sorted(
        {k for r in train + final for k in r if k.startswith("serve_")}
    )
    for key in serve_keys:
        vals = _col(train + final, key)
        if vals:
            serve[key] = {
                "steady": _tail_mean(vals), "max": max(vals),
                "last": vals[-1],
            }
    digest["serve"] = serve

    # Network-front digest (serve/front/; docs/SERVING.md 'Network
    # front'): counters are cumulative (last = total), the wire-latency
    # tails are interval-scoped (steady + worst interval). tenant_*
    # rides in the same section — the QoS view of the same traffic.
    front = {}
    front_keys = sorted(
        {
            k for r in train + final for k in r
            if k.startswith("front_") or k.startswith("tenant_")
        }
    )
    for key in front_keys:
        vals = _col(train + final, key)
        if vals:
            front[key] = {
                "steady": _tail_mean(vals), "max": max(vals),
                "last": vals[-1],
            }
    digest["front"] = front

    # Device-actor digest (actors/device_pool.py; docs/DEVICE_ACTORS.md):
    # rows/s and the per-chunk dispatch tails are interval-scoped
    # (steady + worst interval); env_steps/episodes/restarts are
    # cumulative (the last value is the total).
    devactor = {}
    devactor_keys = sorted(
        {k for r in train + final for k in r if k.startswith("devactor_")}
    )
    for key in devactor_keys:
        vals = _col(train + final, key)
        if vals:
            devactor[key] = {
                "steady": _tail_mean(vals), "max": max(vals),
                "last": vals[-1],
            }
    digest["devactor"] = devactor

    # Fused-megastep digest (parallel/megastep.py FusedBeatStats;
    # docs/FUSED_BEAT.md): beats, grad-steps/s, rows/s, and the per-beat
    # dispatch tails — all interval-scoped (steady + worst interval).
    fused = {}
    fused_keys = sorted(
        {k for r in train + final for k in r if k.startswith("fused_")}
    )
    for key in fused_keys:
        vals = _col(train + final, key)
        if vals:
            fused[key] = {
                "steady": _tail_mean(vals), "max": max(vals),
                "last": vals[-1],
            }
    digest["fused"] = fused

    # Mesh/TP-placement digest (metrics.MeshStats; docs/MESH.md): the
    # mesh shape and the per-device TrainState bytes are gauges — the
    # last value IS the placement fact.
    mesh = {}
    mesh_keys = sorted(
        {k for r in train + final for k in r if k.startswith("mesh_")}
    )
    for key in mesh_keys:
        vals = _col(train + final, key)
        if vals:
            mesh[key] = {"last": vals[-1]}
    digest["mesh"] = mesh

    # Replay-placement digest (replay/device.py ReplayShardStats;
    # docs/REPLAY_SHARDING.md): measured ingest bytes/row, per-device
    # storage bytes, per-shard fill, exchange-dispatch tails.
    replay_shard = {}
    replay_keys = sorted(
        {
            k
            for r in train + final
            for k in r
            if k.startswith(("replay_shard_", "replay_ingest_bytes",
                             "replay_exchange_", "replay_device_storage"))
        }
    )
    for key in replay_keys:
        vals = _col(train + final, key)
        if vals:
            replay_shard[key] = {
                "steady": _tail_mean(vals), "max": max(vals),
                "last": vals[-1],
            }
    digest["replay_sharding"] = replay_shard

    recovery = {}
    for key in RECOVERY_KEYS:
        vals = _col(train + final, key)
        if vals:
            recovery[key] = {"last": vals[-1], "max": max(vals)}
    digest["recovery"] = recovery

    # Supervision digest (supervisor/events.py; docs/OPERATIONS.md
    # supervisor runbook): the event timeline verbatim, plus the
    # cumulative supervisor_* counters off the last record that carries
    # them (the supervisor's `final` event).
    sup_records = kinds.get("supervisor", [])
    if sup_records:
        counters: Dict[str, Any] = {}
        for r in sup_records:
            for k, v in r.items():
                if k.startswith("supervisor_"):
                    counters[k] = v
        digest["supervisor"] = {
            "events": [
                {
                    k: r[k]
                    for k in (
                        "wall_time", "event", "gen", "proc", "code",
                        "code_name", "members", "target", "slots",
                        "backoff_s", "consecutive", "failures",
                        "reason", "transition", "state", "slot",
                    )
                    if k in r
                }
                for r in sup_records
            ],
            "counters": counters,
        }

    ev = _col(evals, "eval_return")
    if ev:
        digest["eval"] = {
            "n": len(ev), "first": ev[0], "best": max(ev), "last": ev[-1],
        }
    if final:
        digest["final"] = {
            k: v for k, v in final[-1].items()
            if k in ("learner_steps", "learner_steps_per_sec",
                     "final_return", "param_checksum")
        }
    return digest


def render_summary(digest: Dict[str, Any]) -> str:
    out = [f"== {digest['path']}"]
    rec = ", ".join(f"{k}:{v}" for k, v in sorted(digest["records"].items()))
    steps = digest.get("steps") or {}
    out.append(
        f"records [{rec}]  steps {steps.get('first', '-')}"
        f"..{steps.get('last', '-')}  wall {_fmt(digest.get('wall_time_s'))}s"
    )
    if digest.get("metrics"):
        out.append("\n-- key metrics (steady = mean of last 25% of records)")
        out.append(render_table(
            ["metric", "steady", "max", "last"],
            [
                [k, m["steady"], m["max"], m["last"]]
                for k, m in digest["metrics"].items()
            ],
        ))
    if digest.get("phases"):
        out.append("\n-- phase breakdown (ms per call)")
        out.append(render_table(
            ["phase", "mean", "p50", "p95", "max", "calls"],
            [
                [name, p.get("mean_ms"), p.get("p50_ms"), p.get("p95_ms"),
                 p.get("max_ms"), p.get("calls")]
                for name, p in digest["phases"].items()
            ],
        ))
    if digest.get("ingest"):
        out.append("\n-- ingest pipeline")
        out.append(render_table(
            ["field", "steady", "max"],
            [
                [k, v["steady"], v["max"]]
                for k, v in digest["ingest"].items()
            ],
        ))
    if digest.get("transfer"):
        out.append("\n-- transfer scheduler (docs/TRANSFER.md)")
        out.append(render_table(
            ["field", "steady", "max"],
            [
                [k, v["steady"], v["max"]]
                for k, v in digest["transfer"].items()
            ],
        ))
    if digest.get("serve"):
        out.append("\n-- inference serving (docs/SERVING.md)")
        out.append(render_table(
            ["field", "steady", "max", "last"],
            [
                [k, v["steady"], v["max"], v["last"]]
                for k, v in digest["serve"].items()
            ],
        ))
    if digest.get("front"):
        out.append("\n-- network front (docs/SERVING.md 'Network front')")
        out.append(render_table(
            ["field", "steady", "max", "last"],
            [
                [k, v["steady"], v["max"], v["last"]]
                for k, v in digest["front"].items()
            ],
        ))
    if digest.get("devactor"):
        out.append("\n-- device actors (docs/DEVICE_ACTORS.md)")
        out.append(render_table(
            ["field", "steady", "max", "last"],
            [
                [k, v["steady"], v["max"], v["last"]]
                for k, v in digest["devactor"].items()
            ],
        ))
    if digest.get("fused"):
        out.append("\n-- fused megastep (docs/FUSED_BEAT.md)")
        out.append(render_table(
            ["field", "steady", "max", "last"],
            [
                [k, v["steady"], v["max"], v["last"]]
                for k, v in digest["fused"].items()
            ],
        ))
    if digest.get("mesh"):
        out.append("\n-- mesh / tensor parallelism (docs/MESH.md)")
        out.append(render_table(
            ["field", "value"],
            [[k, v["last"]] for k, v in digest["mesh"].items()],
        ))
    if digest.get("replay_sharding"):
        out.append("\n-- replay placement (docs/REPLAY_SHARDING.md)")
        out.append(render_table(
            ["field", "steady", "max", "last"],
            [
                [k, v["steady"], v["max"], v["last"]]
                for k, v in digest["replay_sharding"].items()
            ],
        ))
    if digest.get("pod"):
        pod = digest["pod"]
        out.append("\n-- pod resilience (docs/RESILIENCE.md pod rows)")
        out.append(render_table(
            ["field", "last"],
            [[k, v["last"]] for k, v in pod.items()],
        ))
        # Elastic transitions get a one-line verdict above the raw
        # counters: shrink/grow restarts are the record that matters on
        # a membership-change run (docs/RESILIENCE.md state machine).
        def _last(k):
            return pod.get(k, {}).get("last", 0) or 0

        if _last("pod_shrinks") or _last("pod_grows") or _last(
            "pod_slices_adopted"
        ):
            state = "DEGRADED" if _last("pod_state_degraded") else "healthy"
            out.append(
                f"   elastic: {int(_last('pod_slices_adopted'))} slice "
                f"adoption(s) (step {int(_last('pod_slice_adopted_step'))}), "
                f"{int(_last('pod_shrinks'))} shrink(s), "
                f"{int(_last('pod_grows'))} grow(s) -> {state}"
            )
    if digest.get("guardrail"):
        g = digest["guardrail"]
        out.append("\n-- numerical health (docs/RESILIENCE.md; guardrails)")
        out.append(render_table(
            ["field", "last"],
            [[k, v["last"]] for k, v in g.items()],
        ))
    if digest.get("supervisor"):
        sup = digest["supervisor"]
        out.append(
            "\n-- supervision timeline (supervisor/; docs/OPERATIONS.md "
            "runbook)"
        )
        rows = []
        for e in sup["events"]:
            detail_bits = []
            for key in ("code", "code_name", "members", "target", "slots",
                        "slot", "transition", "state", "backoff_s",
                        "consecutive", "failures", "reason"):
                if key in e:
                    detail_bits.append(f"{key}={e[key]}")
            rows.append([
                _fmt(e.get("wall_time")),
                e.get("event", "?"),
                e.get("gen", ""),
                e.get("proc", ""),
                " ".join(detail_bits),
            ])
        out.append(render_table(["t(s)", "event", "gen", "proc", "detail"],
                                rows))
        if sup["counters"]:
            out.append(render_table(
                ["counter", "total"],
                [[k, v] for k, v in sorted(sup["counters"].items())],
            ))
    if digest.get("recovery"):
        rec = digest["recovery"]
        if any(v["max"] for v in rec.values()):
            out.append("\n-- recovery / fault history (cumulative)")
            out.append(render_table(
                ["counter", "total"],
                [[k, v["last"]] for k, v in rec.items()],
            ))
        else:
            out.append("\n-- recovery: clean run (all counters zero)")
    if digest.get("eval"):
        e = digest["eval"]
        out.append(
            f"\n-- eval: n={e['n']} first={_fmt(e['first'])} "
            f"best={_fmt(e['best'])} last={_fmt(e['last'])}"
        )
    if digest.get("final"):
        out.append(
            "-- final: "
            + "  ".join(f"{k}={_fmt(v)}" for k, v in digest["final"].items())
        )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------


def compare_runs(path_a: str, path_b: str) -> Tuple[str, List[List[Any]]]:
    a, b = summarize_run(path_a), summarize_run(path_b)
    rows: List[List[Any]] = []

    def add(label, va, vb, lower_better=False):
        if va is None and vb is None:
            return
        delta = None
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)) and va:
            delta = 100.0 * (vb - va) / abs(va)
        mark = ""
        if delta is not None and abs(delta) >= 5.0:
            worse = delta < 0 if not lower_better else delta > 0
            mark = "!" if worse else "+"
        rows.append([label, va, vb,
                     f"{delta:+.1f}% {mark}" if delta is not None else "-"])

    for key, ma in a.get("metrics", {}).items():
        mb = b.get("metrics", {}).get(key, {})
        add(key, ma.get("steady"), mb.get("steady"))
    names = sorted(set(a.get("phases", {})) | set(b.get("phases", {})))
    for name in names:
        pa = a["phases"].get(name, {})
        pb = b["phases"].get(name, {})
        add(f"t_{name}_ms", pa.get("mean_ms"), pb.get("mean_ms"),
            lower_better=True)
        if pa.get("p95_ms") is not None or pb.get("p95_ms") is not None:
            add(f"t_{name}_p95", pa.get("p95_ms"), pb.get("p95_ms"),
                lower_better=True)
    for key in sorted(set(a.get("ingest", {})) | set(b.get("ingest", {}))):
        ia = a["ingest"].get(key, {})
        ib = b["ingest"].get(key, {})
        add(key, ia.get("steady"), ib.get("steady"),
            lower_better=("stall" in key or "queue" in key or "_ms" in key))
    for key in sorted(
        set(a.get("transfer", {})) | set(b.get("transfer", {}))
    ):
        ta = a.get("transfer", {}).get(key, {})
        tb = b.get("transfer", {}).get(key, {})
        add(key, ta.get("steady"), tb.get("steady"),
            lower_better=(
                "queue" in key or "_ms" in key or "p95" in key
                or "fence" in key
            ))
    for key in sorted(set(a.get("serve", {})) | set(b.get("serve", {}))):
        sa = a.get("serve", {}).get(key, {})
        sb = b.get("serve", {}).get(key, {})
        # Batch fill is a fraction where HIGHER is better (fuller
        # batches), so it is exempt from the latency/backlog heuristics
        # even though serve_fill_p95 matches the 'p95' substring.
        add(key, sa.get("steady"), sb.get("steady"),
            lower_better=(
                "fill" not in key
                and (
                    "_ms" in key or "p95" in key or "overload" in key
                    or "error" in key or "fallback" in key or "depth" in key
                )
            ))
    for key in sorted(set(a.get("front", {})) | set(b.get("front", {}))):
        fa_ = a.get("front", {}).get(key, {})
        fb_ = b.get("front", {}).get(key, {})
        # front_* / tenant_*: request totals and tenant_served are
        # throughput (higher-is-better); wire-latency tails, sheds,
        # overloads, timeouts, bad frames, errors, and rollbacks are all
        # lower-is-better costs. front_promotes is a lifecycle fact —
        # neither direction is a regression — but a delta is still worth
        # seeing, so it rides the default higher-is-better arm.
        add(key, fa_.get("steady"), fb_.get("steady"),
            lower_better=(
                "_ms" in key or "p95" in key or "p50" in key
                or "shed" in key or "overload" in key or "timeout" in key
                or "bad_frame" in key or "error" in key
                or "rollback" in key
            ))
    for key in sorted(
        set(a.get("devactor", {})) | set(b.get("devactor", {}))
    ):
        da = a.get("devactor", {}).get(key, {})
        db = b.get("devactor", {}).get(key, {})
        # Throughput/episode-return are higher-is-better; dispatch-latency
        # tails (mean/p50/p95/max) and the restart counter are
        # lower-is-better.
        add(key, da.get("steady"), db.get("steady"),
            lower_better=("_ms" in key or "p95" in key or "p50" in key
                          or key.endswith("_max") or "restart" in key))
    for key in sorted(set(a.get("fused", {})) | set(b.get("fused", {}))):
        fa = a.get("fused", {}).get(key, {})
        fb = b.get("fused", {}).get(key, {})
        # Beat-dispatch latency tails (fused_beat_ms/p50/p95/max) are
        # lower-is-better; beats and the steps/rows rates are throughput.
        add(key, fa.get("steady"), fb.get("steady"),
            lower_better=("_ms" in key or "p95" in key or "p50" in key
                          or key.endswith("_max")))
    for key in sorted(set(a.get("mesh", {})) | set(b.get("mesh", {}))):
        if key in ("mesh_data_axis", "mesh_model_axis"):
            continue  # mesh shape is context, not a metric to delta
        ma_ = a.get("mesh", {}).get(key, {})
        mb_ = b.get("mesh", {}).get(key, {})
        # Both bytes gauges are lower-is-better: per-device is the
        # placement fact, and an unexplained TOTAL growth (an extra
        # state copy) is a memory regression, never an improvement.
        add(key, ma_.get("last"), mb_.get("last"),
            lower_better=("bytes" in key))
    for key in sorted(
        set(a.get("replay_sharding", {})) | set(b.get("replay_sharding", {}))
    ):
        ra = a.get("replay_sharding", {}).get(key, {})
        rb = b.get("replay_sharding", {}).get(key, {})
        # Shard count / fill / per-device storage bytes are placement
        # facts (context); bytes-per-row and exchange tails are the
        # lower-is-better costs.
        add(key, ra.get("steady"), rb.get("steady"),
            lower_better=("bytes_per_row" in key or "_ms" in key
                          or "p95" in key or "p50" in key))
    for key in sorted(set(a.get("pod", {})) | set(b.get("pod", {}))):
        if key in ("pod_resume_step_elected", "pod_slice_adopted_step",
                   "pod_straggler_host", "pod_agg_hosts"):
            continue  # steps/host indices/world size: context, not deltas
        pa = a.get("pod", {}).get(key, {})
        pb = b.get("pod", {}).get(key, {})
        add(key, pa.get("last"), pb.get("last"),
            lower_better=("slack" not in key and "beats" not in key))
    for key in sorted(
        set(a.get("guardrail", {})) | set(b.get("guardrail", {}))
    ):
        if key == "guardrail_last_rollback_step":
            continue  # a restore step is context, not a metric to delta
        ga = a.get("guardrail", {}).get(key, {})
        gb = b.get("guardrail", {}).get(key, {})
        add(key, ga.get("last"), gb.get("last"), lower_better=True)
    for key in sorted(set(a.get("recovery", {})) | set(b.get("recovery", {}))):
        ra = a.get("recovery", {}).get(key, {})
        rb = b.get("recovery", {}).get(key, {})
        add(key, ra.get("last"), rb.get("last"), lower_better=True)
    ea, eb = a.get("eval", {}), b.get("eval", {})
    add("eval_best", ea.get("best"), eb.get("best"))
    fa, fb = a.get("final", {}), b.get("final", {})
    add("final_return", fa.get("final_return"), fb.get("final_return"))
    add("final_learner_steps_per_sec", fa.get("learner_steps_per_sec"),
        fb.get("learner_steps_per_sec"))
    table = render_table(["metric (steady)", "A", "B", "delta"], rows)
    header = f"A = {path_a}\nB = {path_b}\n('!' = >=5% worse, '+' = >=5% better)"
    return header + "\n" + table, rows


# ---------------------------------------------------------------------------
# gate
# ---------------------------------------------------------------------------

DEFAULT_GATE_KEYS = ("value",)


def _lookup(obj: Dict[str, Any], dotted: str):
    """Resolve 'scaling_cpu_virtual.scaled_batch.8.rows_per_sec' style
    paths into nested bench JSON."""
    cur: Any = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def gate_bench(
    baseline: Dict[str, Any],
    candidate: Dict[str, Any],
    threshold: float,
    keys: Sequence[str] = DEFAULT_GATE_KEYS,
) -> Tuple[bool, List[str]]:
    """True = pass. A key prefixed '-' is lower-is-better (latencies);
    otherwise higher-is-better (rates). A key missing from the CANDIDATE
    while present in the baseline FAILS (a silently dropped metric must
    not read as healthy); missing from both is skipped with a note."""
    ok = True
    lines = []
    for raw in keys:
        lower_better = raw.startswith("-")
        key = raw[1:] if lower_better else raw
        base = _lookup(baseline, key)
        cand = _lookup(candidate, key)
        if not isinstance(base, (int, float)) or isinstance(base, bool):
            lines.append(f"SKIP {key}: not in baseline ({base!r})")
            continue
        if not isinstance(cand, (int, float)) or isinstance(cand, bool):
            ok = False
            lines.append(f"FAIL {key}: missing from candidate ({cand!r})")
            continue
        if base == 0:
            if lower_better and isinstance(base, int):
                # A zero baseline on a lower-is-better COUNTER (e.g.
                # -guardrail_rollbacks) is a real pin: any nonzero
                # candidate is a regression from "never happened", which
                # no relative threshold can express. Int-typed only:
                # latency keys (-ingest_ship_ms, -transfer_*_p95) emit
                # FLOAT 0.0 when their reservoir saw no samples, and
                # "no samples" must keep SKIPping, not fail the first
                # candidate that records any.
                bad = cand > 0
                lines.append(
                    f"{'FAIL' if bad else 'ok':4s} {key}: baseline=0 "
                    f"candidate={cand:g} (zero-baseline pin, "
                    "lower-is-better counter)"
                )
                ok = ok and not bad
            else:
                lines.append(f"SKIP {key}: baseline is 0")
            continue
        ratio = cand / base
        if lower_better:
            bad = ratio > 1.0 + threshold
            rel = ratio - 1.0
        else:
            bad = ratio < 1.0 - threshold
            rel = ratio - 1.0
        verdict = "FAIL" if bad else "ok"
        lines.append(
            f"{verdict:4s} {key}: baseline={base:g} candidate={cand:g} "
            f"({rel:+.1%}, threshold ±{threshold:.0%}, "
            f"{'lower' if lower_better else 'higher'}-is-better)"
        )
        ok = ok and not bad
    return ok, lines


# ---------------------------------------------------------------------------
# merge-trace
# ---------------------------------------------------------------------------


def merge_traces(paths: Sequence[str], out_path: str) -> Tuple[int, int]:
    """Fuse N per-host Chrome-trace files (trace.py export) into ONE
    Perfetto timeline with a process track per host, on an aligned clock.

    Each input's events carry ts relative to that process's own recorder
    start; its `otherData.wall_t0` anchors them to the host's wall clock,
    and `otherData.clock_offset_ms` (the startup clock handshake,
    parallel/multihost.clock_handshake) removes the host's measured skew
    from host 0 — so the merged timeline aligns on HANDSHAKE time, not on
    whatever NTP left each host believing. Events are re-based to the
    earliest aligned anchor, each input's pids are remapped to its host
    index (Perfetto renders one process track per pid), and a
    `process_name` metadata event labels each track with the host index,
    original pid, and source file. Returns (events_written, n_inputs)."""
    loaded = []
    for i, path in enumerate(paths):
        with open(path, encoding="utf-8") as fh:
            obj = json.load(fh)
        events = obj.get("traceEvents")
        if not isinstance(events, list):
            raise ValueError(f"{path}: not a Chrome trace (no traceEvents)")
        od = obj.get("otherData") or {}
        wall_t0 = od.get("wall_t0")
        offset_ms = od.get("clock_offset_ms") or 0.0
        # Aligned anchor: this recorder's ts=0 expressed on host 0's
        # clock. A file without wall_t0 (foreign trace) anchors at 0.
        base = (
            float(wall_t0) - float(offset_ms) / 1e3
            if isinstance(wall_t0, (int, float))
            else None
        )
        host = od.get("process_index")
        loaded.append((path, events, od, base,
                       host if isinstance(host, int) else i))
    known = [base for (_, _, _, base, _) in loaded if base is not None]
    t0 = min(known) if known else 0.0

    merged: List[Dict[str, Any]] = []
    for path, events, od, base, host in loaded:
        shift_us = ((base - t0) * 1e6) if base is not None else 0.0
        for ev in events:
            ev = dict(ev)
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = ev["ts"] + shift_us
            ev["pid"] = host
            merged.append(ev)
        label = f"host{host} pid={od.get('pid', '?')}"
        merged.append({
            "name": "process_name", "ph": "M", "pid": host, "ts": 0,
            "args": {"name": f"{label} ({path})"},
        })
        merged.append({
            "name": "process_sort_index", "ph": "M", "pid": host, "ts": 0,
            "args": {"sort_index": host},
        })
    out = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": list(paths),
            "t_unix_base": t0,
        },
    }
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(out, fh)
    return len(merged), len(loaded)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def render_lint(obj: Dict[str, Any]) -> Tuple[bool, str]:
    """Digest tables for an invariant-lint findings JSON (the artifact
    scripts/lint_gate.sh leaves behind; schema: analysis/engine.py
    LintResult.to_json). Returns (clean, text) — clean mirrors the gate's
    PASS/FAIL so CI boxes can render and re-check in one call."""
    counts = obj.get("counts", {})
    findings = obj.get("findings", [])
    live = [f for f in findings if not f.get("suppressed")]
    out = [
        f"lint: {counts.get('files', '?')} files, "
        f"{len(obj.get('rules', []))} rules, "
        f"{counts.get('findings', len(live))} findings "
        f"({counts.get('suppressed', 0)} suppressed) "
        f"in {obj.get('elapsed_s', 0.0):.2f}s"
    ]
    per_rule: Dict[str, List[int]] = {}
    for f in findings:
        row = per_rule.setdefault(f.get("rule", "?"), [0, 0])
        row[1 if f.get("suppressed") else 0] += 1
    if per_rule:
        out.append("")
        out.append(render_table(
            ["rule", "findings", "suppressed"],
            [[r, n, s] for r, (n, s) in sorted(per_rule.items())],
        ))
    if live:
        out.append("")
        out.append(render_table(
            ["location", "rule", "message"],
            [[f"{f.get('path')}:{f.get('line')}", f.get("rule"),
              f.get("message", "")] for f in live],
        ))
    return not live, "\n".join(out)


def render_programs(obj: Dict[str, Any]) -> Tuple[bool, str]:
    """Digest tables for a program-contract analyzer report JSON (the
    artifact scripts/proganalyze_gate.sh leaves behind; schema:
    analysis/programs.py ProgramReport.to_json). Returns (clean, text) —
    clean mirrors the gate's PASS/FAIL."""
    counts = obj.get("counts", {})
    findings = obj.get("findings", [])
    programs = obj.get("programs", [])
    out = [
        f"programs: {counts.get('programs', len(programs))} traced, "
        f"{counts.get('findings', len(findings))} findings "
        f"in {obj.get('elapsed_s', 0.0):.2f}s"
    ]
    if obj.get("updated"):
        out.append(f"updated goldens: {', '.join(obj['updated'])}")
    if programs:
        out.append("")
        out.append(render_table(
            ["program", "collectives", "fingerprint", "donated", "aliased"],
            [[p.get("name"), len(p.get("collectives", [])),
              p.get("fingerprint", "?"), p.get("donated_leaves", 0),
              p.get("aliased_leaves", 0)] for p in programs],
        ))
    if findings:
        out.append("")
        out.append(render_table(
            ["program", "check", "message"],
            [[f.get("program"), f.get("check"), f.get("message", "")]
             for f in findings],
        ))
    return not findings, "\n".join(out)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_ddpg_tpu.tools.runs",
        description=__doc__.split("\n\n")[0],
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_sum = sub.add_parser("summarize", help="digest one or more JSONL runs")
    p_sum.add_argument("paths", nargs="+")
    p_sum.add_argument("--json", action="store_true",
                       help="emit the digest as JSON instead of tables")

    p_cmp = sub.add_parser("compare", help="A/B two JSONL runs")
    p_cmp.add_argument("path_a")
    p_cmp.add_argument("path_b")

    p_gate = sub.add_parser(
        "gate", help="CI regression gate over two bench JSONs "
        "(exit 2 on regression)",
    )
    p_gate.add_argument("baseline")
    p_gate.add_argument("candidate")
    p_gate.add_argument("--threshold", type=float, default=0.1,
                        help="allowed relative regression (default 0.10)")
    p_gate.add_argument(
        "--keys", default=",".join(DEFAULT_GATE_KEYS),
        help="comma-separated bench keys; prefix '-' for lower-is-better "
        "(e.g. value,-t_dispatch_ms,ingest_rows_per_sec); dotted paths "
        "descend into nested objects",
    )
    p_lint = sub.add_parser(
        "lint", help="pretty-print an invariant-lint findings JSON "
        "(the scripts/lint_gate.sh artifact; exit 2 on unsuppressed "
        "findings, same contract as the bench gate)",
    )
    p_lint.add_argument(
        "path", nargs="?", default="runs/lint_findings.json",
        help="findings JSON (default: runs/lint_findings.json, the "
        "lint_gate.sh default artifact)",
    )
    p_prog = sub.add_parser(
        "programs", help="pretty-print a program-contract analyzer report "
        "JSON (the scripts/proganalyze_gate.sh artifact; exit 2 on "
        "findings, same contract as the lint digest)",
    )
    p_prog.add_argument(
        "path", nargs="?", default="runs/program_findings.json",
        help="report JSON (default: runs/program_findings.json, the "
        "proganalyze_gate.sh default artifact)",
    )
    p_mt = sub.add_parser(
        "merge-trace", help="fuse N per-host Chrome traces (trace.py "
        "export) into one Perfetto timeline with a process track per "
        "host, clock-aligned via the startup handshake offsets",
    )
    p_mt.add_argument("paths", nargs="+",
                      help="per-host trace JSON files, one per process")
    p_mt.add_argument("--out", default="trace_merged.json",
                      help="merged timeline path (default: "
                      "trace_merged.json)")

    args = parser.parse_args(argv)

    if args.cmd == "summarize":
        for i, path in enumerate(args.paths):
            try:
                digest = summarize_run(path)
            except OSError as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(digest))
            else:
                if i:
                    print()
                print(render_summary(digest))
        return 0

    if args.cmd == "compare":
        try:
            text, _ = compare_runs(args.path_a, args.path_b)
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(text)
        return 0

    if args.cmd == "gate":
        try:
            base = load_bench(args.baseline)
            cand = load_bench(args.candidate)
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        keys = [k for k in args.keys.split(",") if k]
        ok, lines = gate_bench(base, cand, args.threshold, keys)
        for line in lines:
            print(line)
        print("GATE PASS" if ok else "GATE FAIL")
        return 0 if ok else 2

    if args.cmd == "merge-trace":
        try:
            n_events, n_hosts = merge_traces(args.paths, args.out)
        except (OSError, json.JSONDecodeError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(
            f"merged {n_events} events from {n_hosts} host trace(s) -> "
            f"{args.out} (load in ui.perfetto.dev)"
        )
        return 0

    if args.cmd == "lint":
        try:
            with open(args.path, encoding="utf-8") as fh:
                obj = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if not isinstance(obj, dict):
            print(f"error: {args.path} is not a findings object "
                  "(truncated artifact?)", file=sys.stderr)
            return 1
        clean, text = render_lint(obj)
        print(text)
        print("LINT PASS" if clean else "LINT FAIL")
        return 0 if clean else 2

    if args.cmd == "programs":
        try:
            with open(args.path, encoding="utf-8") as fh:
                obj = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if not isinstance(obj, dict):
            print(f"error: {args.path} is not a program report object "
                  "(truncated artifact?)", file=sys.stderr)
            return 1
        clean, text = render_programs(obj)
        print(text)
        print("PROGRAMS PASS" if clean else "PROGRAMS FAIL")
        return 0 if clean else 2

    return 1  # unreachable (subparsers required)


if __name__ == "__main__":
    sys.exit(main())
