"""Offline analysis tooling for the JSONL/JSON artifacts the trainer and
bench emit. Pure stdlib — importing this package must never initialize
JAX (the CLIs run on laptops and in CI gates where no accelerator, and no
accelerator wait, is acceptable)."""
