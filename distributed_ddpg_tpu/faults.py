"""Deterministic fault injection: a seeded `FaultPlan` scripted from config
(`--faults='worker:2:crash@5000;worker:0:hang@8000;ckpt:write:ioerror@2'`)
that drives crashes, hangs, slowdowns, and IO errors into every recoverable
component — actor workers, the pool monitor's respawn path, the async
ingest shipper, the ChunkPrefetcher, and the checkpoint writer.

Why scripted, not random: D4PG-scale fleets (arXiv 1804.08617) and
Podracer-style scheduling (arXiv 2104.06272) treat preemption and partial
failure as the NORMAL operating mode, so the recovery paths must be
exercised continuously — and a recovery bug is only debuggable if the
fault schedule that provoked it replays exactly. Every fault fires at a
deterministic trigger point (an env step for workers, a call ordinal for
host-side sites); the plan `seed` only fills in durations left unspecified,
drawn from a PRNG seeded per-fault so the same spec string + seed always
yields the same schedule.

Grammar (';'-separated specs):

    spec      := component [':' target] ':' kind '@' at ['~' seconds]
               | 'pod' ':' proc ':' 'exit' '@' at ':' code
    component := worker | pool | shipper | prefetch | ckpt | transfer | pod
                 | numeric | serve | devactor | slice | front
    kind      := crash | crashloop | hang | stall | slow | ioerror | kill
                 | nan | inf | spike | corrupt | exit | regress

`at` is 1-based: for `worker` it is the env step inside that worker's
FIRST incarnation (a respawned worker gets a clean slate — except
`crashloop`, which re-arms on every incarnation to drive the pool's
crash-loop circuit breaker); for host-side sites it is the n-th call to
the instrumented operation; for `pod` it is the n-th STEADY-STATE
lockstep sync_ship beat that process issues (replay/device.py
_sync_ship_collective, armed by train_jax at the warmup/steady boundary
— one beat per learner chunk, the same ordinal on every process since
beats are lockstep; warmup beats don't count, their number is
wall-clock-paced by actor startup). `~seconds`
sets the duration of `slow`/`hang` faults (default: seeded draw, see
`_default_duration`; pod hangs default LONG — they exist to outlast the
pod collective deadline, not a host-site timeout).

Fault semantics by component:

    worker:<id>:crash@N      raise at env step N (kills the process)
    worker:<id>:crashloop@N  crash at local step N of EVERY incarnation
    worker:<id>:hang@N       freeze WITHOUT heartbeats (silent-timeout path)
    worker:<id>:stall@N      keep heartbeating, produce nothing (the
                             watchdog blind spot pool.monitor now covers)
    worker:<id>:slow@N~S     sleep S per env step for SLOW_FAULT_STEPS steps
    ckpt:write:ioerror@K     K-th checkpoint write attempt raises IOError
    ckpt:write:slow@K~S      K-th write attempt sleeps S first
    shipper:ship:crash@K     K-th ingest ship raises (thread-restart path)
    shipper:ship:slow@K~S    K-th ingest ship sleeps S first
    prefetch:sample:hang@K~S K-th prefetch sample sleeps S (PrefetchTimeout
                             territory when S exceeds next()'s deadline)
    pool:broadcast:slow@K~S  K-th param broadcast sleeps S first
    transfer:dispatch:crash@K K-th transfer-scheduler dispatch raises,
                             killing the scheduler THREAD (its bounded
                             self-restart path — transfer/scheduler.py)
    transfer:dispatch:slow@K~S K-th transfer dispatch sleeps S first
    pod:<proc>:kill@K        process <proc> SIGKILLs itself at its K-th
                             lockstep sync_ship beat — real process death
                             mid-collective; survivors must surface it as
                             PodPeerLost via the collective deadline
                             (parallel/multihost.py, docs/RESILIENCE.md)
    pod:<proc>:hang@K~S      process <proc> freezes S seconds (default:
                             effectively forever) at its K-th beat — the
                             hung-peer flavor of the same contract
    pod:<proc>:slow@K~S      process <proc> sleeps S seconds at its K-th
                             beat and CONTINUES — a surviving straggler,
                             not a lost peer: the pod aggregator's
                             per-host beat-time spread must attribute it
                             (obs/aggregate.py, docs/OBSERVABILITY.md §4)
    pod:<proc>:exit@K:<code> process <proc> hard-exits with exactly
                             <code> (0..255) at its K-th beat — typed-exit
                             injection for supervisor drills: every
                             exit-code branch of the contract (exits.py;
                             incl. the 77 refuse-and-report path) is
                             exercisable without real peer loss or NaN
                             poisoning. os._exit, so no cleanup runs and
                             peers still surface the loss as PodPeerLost
    numeric:grad:nan@K       the K-th guarded learner step computes against
                             a NaN-poisoned minibatch (NaN grads/TD) — the
                             guardrails probe (guardrails.py) must skip the
                             update and, sustained, roll back
    numeric:replay:inf@K     the K-th ingested env-step row lands in replay
                             with reward=+inf (host-side poisoning at drain
                             time) — the bad-row sample detector must
                             record it and attribute its ingest source
    numeric:loss:spike@K     the K-th guarded learner step sees rewards
                             scaled 1e6 (finite, absurd) — the EWMA z-score
                             anomaly detector's territory
    serve:batcher:stall@K~S  the K-th inference-batch dispatch sleeps S
                             before collecting (serve/batcher.py) — served
                             clients must time out and DEGRADE to their
                             local act() path instead of deadlocking
                             (docs/SERVING.md failure contract)
    serve:dispatch:crash@K   the K-th inference-batch apply raises: every
                             request in that batch fails typed, clients
                             fall back locally, the batcher survives
    devactor:rollout:crash@K the K-th device-actor rollout dispatch raises
                             (actors/device_pool.py) — the pool's bounded
                             self-restart path absorbs it (counter
                             devactor_restarts); past the budget a typed
                             DeviceActorError surfaces to the trainer
    devactor:rollout:slow@K~S the K-th rollout dispatch sleeps S first
                             (throughput-dent flavor; rows still land)
    slice:<proc>:corrupt@K   process <proc>'s K-th replay-slice write lands
                             TORN: the digest sidecar records the intact
                             payload, then the npz is truncated — exactly
                             the shape of a peer dying mid-write. Slice
                             verification (checkpoint.verify_replay_slices)
                             must quarantine that one slice and leave the
                             step's siblings intact (docs/RESILIENCE.md)
    slice:<proc>:kill@K      process <proc> SIGKILLs itself at its K-th
                             replay-slice write, BEFORE any byte lands —
                             peer-loss-during-checkpoint; the step's slice
                             set stays incomplete and restore must fall
                             back to an older complete step (or exit 76)
    front:accept:stall@K~S   the K-th accepted TCP connection's handler
                             sleeps S before reading frames
                             (serve/front/ingress.py) — that client sees
                             wire latency; the acceptor and every other
                             connection keep serving
    front:frame:corrupt@K    the K-th decoded request frame is treated as
                             corrupt: a typed bad_frame error goes back
                             on the wire and the CONNECTION SURVIVES —
                             the typed-error-never-kills-the-acceptor
                             contract (docs/SERVING.md failure contract)
    front:canary:regress@K~S every candidate-routed request from the K-th
                             onward serves S seconds slower — SUSTAINED,
                             not one-shot (FaultPlan.front_canary_
                             regressions), because the canary gate trips
                             on a p95 over min_requests samples, not an
                             outlier; the gate must auto-roll-back and
                             never promote the regressed version

Numeric `at` ordinals count GUARDED learner steps on a monotonic clock
(guardrails.GuardState.total) that is deliberately NOT rolled back by the
guardrails' checkpoint rollback — a step-keyed fault that re-fired after
every rollback would loop the run into its own repair forever. They are
consumed at program build time (parallel/learner.py), not via FaultSite.

The legacy one-shot hook `--inject_fault=actor:<id>:<step>` is accepted as
an alias for `worker:<id>:crash@<step>`.

Host-side consumers hold a `FaultSite` (`plan.site(component, target)`)
and call `site.tick()` once per instrumented operation; worker processes
receive their (picklable) fault tuples via `plan.for_worker(id)` and apply
them inline (actors/worker.py). An empty plan's `tick()` is a no-op
attribute check — safe to leave on every production call site.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

COMPONENTS = ("worker", "pool", "shipper", "prefetch", "ckpt", "transfer",
              "pod", "numeric", "serve", "devactor", "slice", "front")
KINDS = ("crash", "crashloop", "hang", "stall", "slow", "ioerror", "kill",
         "nan", "inf", "spike", "corrupt", "exit", "regress")

# Worker `slow` faults throttle this many consecutive env steps, then lift
# — bounded so a chaos soak keeps making progress past the fault.
SLOW_FAULT_STEPS = 200

# Worker-only kinds need a process to kill/freeze; site-only kinds need a
# call site that can raise/sleep inline; pod kinds target a whole PROCESS
# of a multi-host pod at a lockstep-beat ordinal (docs/RESILIENCE.md).
_WORKER_KINDS = ("crash", "crashloop", "hang", "stall", "slow")
_SITE_KINDS = ("crash", "hang", "slow", "ioerror")
_POD_KINDS = ("kill", "hang", "slow", "exit")
# Slice faults target one process's all-writer replay-slice writes
# (checkpoint.write_replay_slice): `corrupt` tears the payload after the
# digest landed, `kill` dies before any byte does.
_SLICE_KINDS = ("corrupt", "kill")
# Numeric faults are target->kind pairs (each target poisons one specific
# detector of the guardrails probe): grad->nan, replay->inf, loss->spike.
_NUMERIC_PAIRS = {"grad": "nan", "replay": "inf", "loss": "spike"}
# Serve faults target one of the two batcher fault points: the collection
# path (stall/slow — delayed responses, the client-timeout fallback path)
# or the batch apply (crash/slow — a failed batch fails typed).
_SERVE_KINDS = {
    "batcher": ("stall", "hang", "slow"),
    "dispatch": ("crash", "slow"),
}
# Front faults target the network serving front (serve/front/;
# docs/SERVING.md "Network front"): `accept` stalls the K-th accepted
# connection's handler (clients see wire latency, the acceptor survives),
# `frame` corrupts the K-th decoded request frame (typed bad-frame error
# on the wire, connection stays up), `canary` injects a SUSTAINED latency
# regression into every candidate-routed request from ordinal K on — the
# chaos vector the canary gate must catch and auto-roll-back.
_FRONT_KINDS = {
    "accept": ("stall", "slow", "hang"),
    "frame": ("corrupt",),
    "canary": ("regress",),
}


class InjectedFault(OSError):
    """A scripted fault from a FaultPlan. Subclasses OSError so recovery
    paths written for real IO failures (checkpoint write retry) treat an
    injected failure exactly like the genuine article."""


class InjectedCorruption(InjectedFault):
    """A scripted torn write: raised by a slice site's tick() and caught
    INSIDE checkpoint.write_replay_slice, which then truncates the payload
    it just wrote (after the digest sidecar landed intact). Distinct from
    plain InjectedFault so only the corruption-aware writer absorbs it —
    any other site treats it as the IO failure it subclasses."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    component: str
    target: str      # worker id as str, or site name ("write", "ship", ...)
    kind: str
    at: int          # env step (worker) / 1-based call ordinal (site)
    duration_s: float  # slow/hang duration; resolved at parse time
    code: int = 0    # exit-kind only: the injected typed exit status

    def describe(self) -> str:
        tgt = f":{self.target}" if self.target else ""
        suffix = f":{self.code}" if self.kind == "exit" else ""
        return f"{self.component}{tgt}:{self.kind}@{self.at}{suffix}"


def _default_duration(kind: str, rng: random.Random,
                      component: str = "") -> float:
    """Seeded default durations: slowdowns are sub-second hiccups, hangs
    are long enough to trip the timeouts they target (worker hangs ignore
    this — they freeze until terminated). A pod hang defaults to
    effectively-forever: its job is to outlast the pod collective
    deadline so survivors prove the PodPeerLost path, not to clear a
    host-site timeout."""
    if kind == "slow":
        return round(rng.uniform(0.05, 0.25), 3)
    if kind == "regress":
        # Canary latency regressions are per-request slowdowns applied to
        # EVERY candidate request past the trigger: big enough to clear
        # any live canary threshold, small enough to keep drills fast.
        return round(rng.uniform(0.02, 0.1), 3)
    if kind in ("hang", "stall"):
        if component == "pod":
            return 3600.0
        return round(rng.uniform(2.0, 5.0), 3)
    return 0.0


class FaultPlan:
    """An immutable, seeded schedule of FaultSpecs plus the factory for
    per-component injectors. Parse once (config validation does, to fail
    fast on typos), share everywhere."""

    def __init__(self, specs: Sequence[FaultSpec] = (), seed: int = 0):
        self.specs: Tuple[FaultSpec, ...] = tuple(specs)
        self.seed = seed

    def __bool__(self) -> bool:
        return bool(self.specs)

    def __repr__(self) -> str:
        return f"FaultPlan([{'; '.join(s.describe() for s in self.specs)}])"

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        """Parse the --faults grammar. Raises ValueError with the offending
        spec named — config.__post_init__ calls this so a typo dies at
        argument parsing, not at fault-fire time mid-run."""
        specs: List[FaultSpec] = []
        text = (text or "").strip()
        if not text:
            return cls((), seed=seed)
        for i, raw in enumerate(s.strip() for s in text.split(";")):
            if not raw:
                continue
            # str seeds hash via sha512 — deterministic across interpreters
            # (tuple seeding is deprecated and PYTHONHASHSEED-dependent).
            rng = random.Random(f"{seed}:{i}:{raw}")
            specs.append(_parse_one(raw, rng))
        return cls(specs, seed=seed)

    def for_worker(self, worker_id: int, incarnation: int = 0) -> List[Tuple[str, int, float]]:
        """Picklable (kind, at_step, duration_s) tuples for one worker
        process. First incarnation gets every scheduled fault; respawns get
        only `crashloop` (re-armed as a plain crash) so recovery is
        observable — a one-shot crash must not re-fire forever."""
        out = []
        for s in self.specs:
            if s.component != "worker" or s.target != str(worker_id):
                continue
            if s.kind == "crashloop":
                out.append(("crash", s.at, s.duration_s))
            elif incarnation == 0:
                out.append((s.kind, s.at, s.duration_s))
        return sorted(out, key=lambda t: t[1])

    def site(self, component: str, target: str = "") -> "FaultSite":
        matches = [
            s for s in self.specs
            if s.component == component and (not s.target or not target or s.target == target)
        ]
        return FaultSite(matches, component, target)

    def pod_site(self, process_index: int) -> "FaultSite":
        """The pod-scoped injector for ONE process of a multi-host run:
        only specs targeting `process_index` fire; every process still
        ticks the site once per lockstep beat so ordinals stay aligned
        with the (identical-everywhere) beat sequence."""
        return self.site("pod", str(int(process_index)))

    def slice_site(self, process_index: int) -> "FaultSite":
        """The replay-slice injector for ONE process: ticked once per
        write_replay_slice call (checkpoint.py), so `@K` is that process's
        K-th slice write — cadence and emergency writes both count."""
        return self.site("slice", str(int(process_index)))

    def numeric_steps(self) -> Dict[str, Tuple[int, ...]]:
        """Guarded-learner-step ordinals for the IN-PROGRAM numeric faults
        ('grad' -> NaN batch, 'loss' -> 1e6-scaled rewards), consumed at
        chunk-program build time (parallel/learner.py). 'replay' specs are
        host-side (see numeric_replay_rows) and excluded here."""
        out: Dict[str, List[int]] = {}
        for s in self.specs:
            if s.component == "numeric" and s.target in ("grad", "loss"):
                out.setdefault(s.target, []).append(s.at)
        return {k: tuple(sorted(v)) for k, v in out.items()}

    def front_canary_regressions(self) -> Tuple[Tuple[int, float], ...]:
        """(at, seconds) pairs for `front:canary:regress@K~S` specs:
        unlike a FaultSite one-shot, a canary regression is SUSTAINED —
        the front sleeps S on every candidate-routed request from its
        K-th onward (serve/front/ingress.py), because the canary gate
        needs a population of slow samples, not one outlier, before its
        p95 delta can trip (docs/SERVING.md 'Network front')."""
        return tuple(sorted(
            (s.at, s.duration_s) for s in self.specs
            if s.component == "front" and s.kind == "regress"
        ))

    def numeric_replay_rows(self) -> Tuple[int, ...]:
        """Ingested-row ordinals (1-based, per process) whose reward is
        poisoned to +inf at drain time (train.py) — the deterministic
        'poisoned replay row' chaos vector for the bad-row sample detector
        and its source-quarantine path."""
        return tuple(sorted(
            s.at for s in self.specs
            if s.component == "numeric" and s.target == "replay"
        ))


def _parse_one(raw: str, rng: random.Random) -> FaultSpec:
    def bad(why: str) -> ValueError:
        return ValueError(
            f"bad fault spec {raw!r}: {why} (grammar: "
            "component[:target]:kind@at[~seconds], e.g. "
            "'worker:2:crash@5000' or 'ckpt:write:ioerror@2')"
        )

    parts = raw.split(":")
    if len(parts) == 3 and parts[0] == "actor" and "@" not in parts[2]:
        # Legacy --inject_fault alias: actor:<id>:<step> == crash.
        try:
            wid, step = int(parts[1]), int(parts[2])
        except ValueError:
            raise bad("legacy actor:<id>:<step> needs two integers") from None
        return FaultSpec("worker", str(wid), "crash", step, 0.0)
    # Typed-exit injection is the one 4-field spec: the trailing field is
    # the exact exit status to die with (pod:<proc>:exit@<beat>:<code>).
    code = 0
    has_code = False
    if len(parts) == 4 and parts[0] == "pod" and parts[2].startswith("exit@"):
        has_code = True
        code_str = parts.pop()
        try:
            code = int(code_str)
        except ValueError:
            raise bad(
                f"bad exit code {code_str!r} (integer 0..255)"
            ) from None
        if not 0 <= code <= 255:
            raise bad(f"exit code {code} out of range (0..255)")
    if len(parts) == 2:
        component, tail = parts[0], parts[1]
        target = ""
    elif len(parts) == 3:
        component, target, tail = parts
    else:
        raise bad("expected 2 or 3 ':'-separated fields")
    if component not in COMPONENTS:
        raise bad(f"unknown component {component!r} (one of {COMPONENTS})")
    if "@" not in tail:
        raise bad("missing '@<at>' trigger")
    kind, _, at_part = tail.partition("@")
    if kind not in KINDS:
        raise bad(f"unknown kind {kind!r} (one of {KINDS})")
    duration: Optional[float] = None
    if "~" in at_part:
        at_str, _, dur_str = at_part.partition("~")
        try:
            duration = float(dur_str)
        except ValueError:
            raise bad(f"bad duration {dur_str!r}") from None
        if duration < 0:
            raise bad("duration must be >= 0")
    else:
        at_str = at_part
    try:
        at = int(at_str)
    except ValueError:
        raise bad(f"bad trigger {at_str!r} (integer step/ordinal)") from None
    if at < 1:
        raise bad("trigger must be >= 1")
    if component == "worker":
        if kind not in _WORKER_KINDS:
            raise bad(f"kind {kind!r} does not apply to workers")
        try:
            int(target)
        except ValueError:
            raise bad("worker target must be an integer id") from None
    elif component == "pod":
        if kind not in _POD_KINDS:
            raise bad(
                f"kind {kind!r} does not apply to pod (one of {_POD_KINDS})"
            )
        if kind == "exit" and not has_code:
            raise bad(
                "exit needs a trailing ':<code>' "
                "(pod:<proc>:exit@<beat>:<code>)"
            )
        try:
            int(target)
        except ValueError:
            raise bad("pod target must be an integer process id") from None
    elif component == "slice":
        if kind not in _SLICE_KINDS:
            raise bad(
                f"kind {kind!r} does not apply to slice "
                f"(one of {_SLICE_KINDS})"
            )
        try:
            int(target)
        except ValueError:
            raise bad("slice target must be an integer process id") from None
    elif component == "numeric":
        if target not in _NUMERIC_PAIRS:
            raise bad(
                f"numeric target must be one of {tuple(_NUMERIC_PAIRS)}"
            )
        if kind != _NUMERIC_PAIRS[target]:
            raise bad(
                f"numeric:{target} takes kind {_NUMERIC_PAIRS[target]!r} "
                f"(got {kind!r}) — grad:nan, replay:inf, loss:spike"
            )
    elif component == "serve":
        if target not in _SERVE_KINDS:
            raise bad(
                f"serve target must be one of {tuple(_SERVE_KINDS)}"
            )
        if kind not in _SERVE_KINDS[target]:
            raise bad(
                f"serve:{target} takes kind in {_SERVE_KINDS[target]} "
                f"(got {kind!r})"
            )
    elif component == "front":
        if target not in _FRONT_KINDS:
            raise bad(
                f"front target must be one of {tuple(_FRONT_KINDS)}"
            )
        if kind not in _FRONT_KINDS[target]:
            raise bad(
                f"front:{target} takes kind in {_FRONT_KINDS[target]} "
                f"(got {kind!r})"
            )
    else:
        if kind not in _SITE_KINDS:
            raise bad(f"kind {kind!r} does not apply to host sites")
    if duration is None:
        duration = _default_duration(kind, rng, component)
    return FaultSpec(component, target, kind, at, duration, code)


class FaultSite:
    """Call-ordinal injector for one host-side component: `tick()` once per
    instrumented operation; the n-th tick fires every spec scheduled
    `@n` — `ioerror`/`crash` raise InjectedFault, `slow`/`hang` sleep their
    duration. Thread-safe (sites sit on shipper/prefetch/ckpt threads)."""

    def __init__(self, specs: Sequence[FaultSpec], component: str, target: str = ""):
        self._by_at: Dict[int, List[FaultSpec]] = {}
        for s in specs:
            self._by_at.setdefault(s.at, []).append(s)
        self.component = component
        self.target = target
        self._count = 0
        self._lock = threading.Lock()
        self.fired: List[str] = []

    def __bool__(self) -> bool:
        return bool(self._by_at)

    @property
    def calls(self) -> int:
        return self._count

    def tick(self) -> None:
        if not self._by_at:
            return
        with self._lock:
            self._count += 1
            due = self._by_at.get(self._count, ())
        for s in due:
            self.fired.append(s.describe())
            if s.kind in ("slow", "hang", "stall"):
                time.sleep(s.duration_s)
            elif s.kind == "corrupt":
                # Torn-write request: the slice writer catches this AFTER
                # persisting the digest sidecar and truncates the payload
                # (checkpoint.write_replay_slice) — verification, not the
                # writer, must be what rejects the slice.
                raise InjectedCorruption(
                    f"injected {s.describe()} (call #{self._count})"
                )
            elif s.kind == "kill":
                # Pod-scoped process death (pod:<proc>:kill@beat): SIGKILL
                # ourselves — no cleanup, no exception, exactly the shape
                # of a real preemption. Survivors must detect the loss
                # through the collective deadline (PodPeerLost), not
                # through any in-process signal.
                os.kill(os.getpid(), signal.SIGKILL)
            elif s.kind == "exit":
                # Typed-exit injection (pod:<proc>:exit@beat:<code>):
                # hard-exit with exactly the scripted status — the
                # supervisor-drill lever that exercises every branch of
                # the exit-code contract (exits.py) without real peer
                # loss. os._exit like the kill flavor: no cleanup, and
                # peers still see the death as PodPeerLost.
                sys.stdout.flush()
                sys.stderr.flush()
                os._exit(s.code)
            else:  # ioerror / crash
                raise InjectedFault(
                    f"injected {s.describe()} (call #{self._count})"
                )


# Shared empty site: the no-plan fast path every production call site holds.
NULL_SITE = FaultSite((), "", "")
