"""ActorPool: N async rollout workers feeding one learner (SURVEY.md §1's
"N worker processes ... and 1+ PS processes" topology, minus the PS — params
flow learner->workers through shared memory instead of gRPC pulls).

- Param broadcast: one flat f32 shared-memory array + a version counter.
  Workers poll the version each env step and memcpy on change — the
  TPU-native replacement for the reference's per-step parameter pull
  (SURVEY.md §3.2 'pulls current theta from PS').
- Transitions: workers push batched n-step transitions over an mp.Queue;
  `drain_into(replay)` moves them into the host replay buffer.
- Failure detection (SURVEY.md §5): workers stamp heartbeats; `monitor()`
  respawns any worker that died, went silent past the heartbeat timeout,
  or — config.actor_no_progress_s — kept heartbeating while producing
  zero experience rows (the watchdog's documented actor-side blind spot).
  Actors are stateless given params, so a respawn is lossless except the
  in-flight episode. Respawns back off exponentially per slot, and a
  crash-looping slot (config.quarantine_respawns failures within
  config.quarantine_window_s) is QUARANTINED: the pool logs loudly, stops
  respawning it, and training continues degraded — a respawn stampede of
  doomed workers is strictly worse than one missing actor. After
  config.quarantine_probe_s the slot is PROBED with a single respawn
  attempt: sustained progress (rows delivered + surviving
  quarantine_window_s) un-quarantines it (counter actor_unquarantined),
  a probe failure re-quarantines for another cooldown — a half-capacity
  fleet recovers from transient faults without a run restart.
- Fault injection (config.faults; faults.py): each worker receives its
  slice of the run's FaultPlan at spawn time. One-shot faults arm only the
  slot's FIRST incarnation (recovery must be observable); `crashloop`
  re-arms every incarnation to drive the circuit breaker.

Uses the 'spawn' start method: workers must never inherit the parent's JAX
runtime state.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue as queue_mod
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.actors.policy import (
    actor_head_dim,
    decode_version,
    flatten_params,
    layout_size,
    param_layout,
)
from distributed_ddpg_tpu.actors.worker import run_worker
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs.registry import EnvSpec

# Reap bound for a worker we just terminate()d: long enough for the OS to
# deliver SIGTERM and tear the process down, short enough that a zombie
# never stalls the supervision tick. Not a config knob — no healthy run
# should ever be tuned by how long killing a dead worker takes.
_TERMINATE_JOIN_S = 2.0


class ActorPool:
    def __init__(
        self,
        config: DDPGConfig,
        spec: EnvSpec,
        num_actors: Optional[int] = None,
        heartbeat_timeout: Optional[float] = None,
    ):
        self.config = config
        self.spec = spec
        self.num_actors = num_actors or config.num_actors
        self.heartbeat_timeout = (
            config.heartbeat_timeout_s
            if heartbeat_timeout is None
            else heartbeat_timeout
        )
        heartbeat_timeout = self.heartbeat_timeout
        if config.actor_throttle_s >= heartbeat_timeout:
            raise ValueError(
                f"actor_throttle_s={config.actor_throttle_s} >= the pool's "
                f"heartbeat timeout ({heartbeat_timeout}s): the throttle "
                "sleep sits between heartbeat stamps, so the monitor would "
                "respawn every worker forever"
            )
        self._ctx = mp.get_context("spawn")
        self.layout = param_layout(
            spec.obs_dim,
            actor_head_dim(spec.act_dim, config.sac),
            tuple(config.actor_hidden),
        )
        self._shared = self._ctx.Array("f", layout_size(self.layout), lock=False)
        self._version = self._ctx.Value("l", 0)
        self._queue = self._ctx.Queue(maxsize=4 * self.num_actors)
        # Transport resolution (config.transport): per-worker C++ SPSC rings
        # in anonymous shared memory when available; mp.Queue otherwise. Row
        # layout: [obs, action, reward, discount, next_obs, version] — the
        # trailing version column carries the param-staleness tag that the
        # queue path sends alongside each batch.
        from distributed_ddpg_tpu import native

        if config.transport == "shm" and not native.available():
            raise ValueError(
                "transport='shm' but the native replay core is unavailable "
                "(no C++ toolchain?); use transport='queue'"
            )
        self.transport = (
            "shm"
            if config.transport in ("auto", "shm") and native.available()
            else "queue"
        )
        self.row_width = 2 * spec.obs_dim + spec.act_dim + 3
        self._rings = []
        self._ring_bufs = []
        if self.transport == "shm":
            nbytes = native.ShmRing.nbytes(config.shm_ring_rows, self.row_width)
            for _ in range(self.num_actors):
                buf = self._ctx.Array("B", nbytes, lock=False)
                self._ring_bufs.append(buf)
                self._rings.append(
                    native.ShmRing(
                        buf, config.shm_ring_rows, self.row_width, init=True
                    )
                )
        # --- served-actor transport (serve/; docs/SERVING.md) ---
        # config.serve_actors: workers request actions from the learner
        # process's InferenceServer over ONE bounded shared request queue
        # (obs rows are tiny — pickling cost is irrelevant at act()
        # granularity) and each worker gets a private response queue so
        # replies never fan out. The counter array records local-act
        # fallbacks (timeout/overload/dispatch failure — the degraded
        # mode the serve chaos tests pin); the pool only ever READS it.
        self.serving = bool(config.serve_actors)
        self._serve_req = None
        self._serve_resp: List = []
        self._serve_fallbacks = None
        if self.serving:
            self._serve_req = self._ctx.Queue(maxsize=config.serve_queue)
            self._serve_resp = [
                self._ctx.Queue(maxsize=8) for _ in range(self.num_actors)
            ]
            self._serve_fallbacks = self._ctx.Array(
                "l", self.num_actors, lock=False
            )
        self._episodes = self._ctx.Queue(maxsize=16 * self.num_actors)
        self._heartbeat = self._ctx.Array("d", self.num_actors, lock=False)
        self._stop = self._ctx.Value("b", 0)
        self._procs: List[Optional[mp.Process]] = [None] * self.num_actors
        self._respawns = 0
        self._steps_received = 0
        # --- supervised recovery state (one entry per worker slot) ---
        self._plan = config.fault_plan()
        self._broadcast_fault = self._plan.site("pool", "broadcast")
        # pool:monitor:slow@k delays the k-th supervision pass — the
        # "supervisor itself is slow" case: training must tolerate late
        # failure detection, not just fast fault recovery.
        self._monitor_fault = self._plan.site("pool", "monitor")
        self._incarnation = [0] * self.num_actors
        self._fail_times: List[List[float]] = [[] for _ in range(self.num_actors)]
        self._backoff_until = [0.0] * self.num_actors
        self._pending_respawn = [False] * self.num_actors
        self._quarantined = [False] * self.num_actors
        # Quarantine probing (config.quarantine_probe_s): after a
        # cooldown, a quarantined slot gets ONE respawn attempt; sustained
        # progress un-quarantines it, any failure during the probe
        # re-quarantines immediately. A half-capacity fleet whose fault
        # was transient recovers without a run restart.
        self._quarantined_at = [0.0] * self.num_actors
        self._probing = [False] * self.num_actors
        self._probe_t = [0.0] * self.num_actors
        self._unquarantines = 0
        # Zero-rows detector clock: 0.0 = "no rows seen this incarnation";
        # armed lazily at the first observed heartbeat (boot can take many
        # seconds under cold-start contention, and the detector must not
        # count boot time as silence).
        self._last_rows_t = [0.0] * self.num_actors
        # Actual-rows clock: written ONLY when experience is drained from
        # the worker (_note_version) — unlike _last_rows_t, which the
        # zero-rows detector also ARMS at first heartbeat. The probe's
        # sustained-progress check reads this one, so a heartbeating-but-
        # rowless probe can never be mistaken for a recovery.
        self._rows_seen_t = [0.0] * self.num_actors
        # Env-step progress restored from a checkpoint (set by the driver
        # BEFORE start()): counts against the uniform-warmup budget so a
        # resumed run doesn't re-inject warmup_uniform random actions.
        self.env_steps_offset = 0
        # Param-staleness tracking (SURVEY.md §5 'params-staleness per
        # actor'): even version -> learner step at broadcast, pruned to the
        # most recent entries; per-worker staleness updated on drain.
        self._version_steps: Dict[int, int] = {}
        self._last_broadcast_step = 0
        self._staleness = np.zeros(self.num_actors, np.int64)

    # --- lifecycle ---

    def warmup_budget_per_worker(self) -> int:
        """REMAINING per-worker uniform-warmup budget at spawn time: the
        global budget (config.resolved_warmup_uniform) net of checkpoint-
        resume progress and steps already drained — a respawned or resumed
        worker must not re-inject random actions into a trained run's
        replay — split evenly (ceil) across the pool."""
        remaining = max(
            0,
            self.config.resolved_warmup_uniform()
            - self.env_steps_offset
            - self._steps_received,
        )
        return (remaining + self.num_actors - 1) // self.num_actors

    def _spawn(self, worker_id: int) -> None:
        fault_specs = self._plan.for_worker(
            worker_id, incarnation=self._incarnation[worker_id]
        )
        self._incarnation[worker_id] += 1
        p = self._ctx.Process(
            target=run_worker,
            kwargs=dict(
                worker_id=worker_id,
                env_id=self.config.env_id,
                seed=self.config.seed + 1000 * (worker_id + 1) + self._respawns,
                layout=self.layout,
                action_scale=self.spec.action_scale,
                action_offset=self.spec.action_offset,
                action_low=self.spec.action_low,
                action_high=self.spec.action_high,
                shared_params=self._shared,
                param_version=self._version,
                transition_queue=self._queue,
                ring_buf=(
                    self._ring_bufs[worker_id] if self.transport == "shm" else None
                ),
                ring_rows=self.config.shm_ring_rows,
                heartbeat=self._heartbeat,
                stop_flag=self._stop,
                ou_theta=self.config.ou_theta,
                ou_sigma=self.config.ou_sigma,
                ou_dt=self.config.ou_dt,
                n_step=self.config.n_step,
                gamma=self.config.gamma,
                fault_specs=fault_specs,
                throttle_s=self.config.actor_throttle_s,
                gaussian_policy=self.config.sac,
                log_std_min=self.config.sac_log_std_min,
                log_std_max=self.config.sac_log_std_max,
                warmup_uniform=self.warmup_budget_per_worker(),
                episode_queue=self._episodes,
                # Served-actor transport (config.serve_actors; None = the
                # default per-worker act() path).
                serve_request_queue=self._serve_req,
                serve_response_queue=(
                    self._serve_resp[worker_id] if self.serving else None
                ),
                serve_fallbacks=self._serve_fallbacks,
                serve_timeout_s=self.config.serve_timeout_s,
                serve_fallback_s=self.config.serve_fallback_s,
                # Flight recorder: workers are separate processes, so each
                # keeps its OWN ring and exports trace_actor<k>.json on
                # clean exit; Perfetto merges the files by pid.
                trace_dir=self.config.trace_dir,
                # Orphan guard (worker.py): the worker compares getppid()
                # against the pool process's REAL pid, captured here at
                # spawn time — a late in-worker getppid() capture races
                # with a pool that dies during worker boot.
                parent_pid=os.getpid(),
            ),
            daemon=True,
            name=f"actor-{worker_id}",
        )
        p.start()
        # 0.0 = "never stamped": the worker is still booting (interpreter +
        # gym/mujoco imports + env build — under N-process cold-start
        # contention this takes many times the solo cost, easily past any
        # fixed timeout). The silent-timeout respawn only arms once the
        # worker's loop stamps its first real heartbeat; until then only
        # the liveness check (real deaths) can respawn it. A worker that
        # hangs FOREVER mid-boot while staying alive is therefore never
        # respawned — accepted trade against the respawn stampede, which
        # was self-sustaining (every respawn re-created the boot stampede
        # that caused the timeout).
        self._heartbeat[worker_id] = 0.0
        self._last_rows_t[worker_id] = 0.0  # re-armed at first heartbeat
        self._rows_seen_t[worker_id] = 0.0
        self._procs[worker_id] = p

    def start(self, actor_params) -> "ActorPool":
        self.broadcast(actor_params)
        for i in range(self.num_actors):
            self._spawn(i)
        return self

    def stop(self) -> None:
        self._stop.value = 1
        deadline = time.time() + 5.0
        for p in self._procs:
            if p is not None:
                p.join(timeout=max(0.1, deadline - time.time()))
        for p in self._procs:
            if p is not None and p.is_alive():
                p.terminate()

    # --- serving surface (serve/; docs/SERVING.md) ---

    def serve_channels(self):
        """(request_queue, response_queues) for the learner process's
        ServeFront. Only meaningful when config.serve_actors built them."""
        return self._serve_req, self._serve_resp

    def param_source(self):
        """(shared flat-param array, seqlock version) — the broadcast
        buffer the workers poll; the InferenceServer refreshes its policy
        from the same source, so serving needs no second param path."""
        return self._shared, self._version

    def serve_counters(self) -> Dict[str, int]:
        """Served-client fallback total for the serve_* metrics family:
        how many times workers degraded to their local act() path."""
        if self._serve_fallbacks is None:
            return {}
        return {
            "serve_client_fallbacks": int(sum(self._serve_fallbacks)),
        }

    # --- param broadcast (learner -> workers) ---

    def broadcast(self, actor_params, learner_step: int = 0) -> None:
        """Seqlock write (SURVEY.md §5 'Race detection'): version goes ODD
        while the flat array is being written, EVEN when it is consistent.
        Workers copy only at even versions and re-check the version after
        the copy, so a torn half-old/half-new parameter vector is never
        acted on.

        `learner_step` stamps which learner step these params come from so
        experience can be attributed a staleness (see staleness())."""
        self._broadcast_fault.tick()
        with trace.span("param_broadcast", learner_step=int(learner_step)):
            flat = flatten_params(actor_params)
            view = np.frombuffer(self._shared, dtype=np.float32)
            self._version.value += 1   # odd: write in progress
            view[:] = flat
            self._version.value += 1   # even: consistent
        self._last_broadcast_step = int(learner_step)
        self._version_steps[self._version.value] = self._last_broadcast_step
        while len(self._version_steps) > 64:
            self._version_steps.pop(next(iter(self._version_steps)))

    def _note_version(self, worker_id: int, version: int) -> None:
        acted_at = self._version_steps.get(version, 0)
        self._staleness[worker_id] = self._last_broadcast_step - acted_at
        # Rows arrived from this worker: feed the zero-rows detector and
        # the probe's sustained-progress clock.
        self._last_rows_t[worker_id] = time.time()
        self._rows_seen_t[worker_id] = self._last_rows_t[worker_id]

    def staleness(self) -> Dict[str, float]:
        """Learner-step staleness of the params behind each worker's most
        recently drained experience: 0 = acting on the latest broadcast."""
        s = self._staleness[: self.num_actors]
        return {
            "staleness_mean": float(s.mean()) if len(s) else 0.0,
            "staleness_max": int(s.max()) if len(s) else 0,
        }

    # --- experience (workers -> replay) ---

    def _rows_to_batch(self, rows: np.ndarray) -> Dict[str, np.ndarray]:
        o, a = self.spec.obs_dim, self.spec.act_dim
        return {
            "obs": rows[:, :o],
            "action": rows[:, o : o + a],
            "reward": rows[:, o + a],
            "discount": rows[:, o + a + 1],
            "next_obs": rows[:, o + a + 2 : 2 * o + a + 2],
        }

    def _pop_ring_batches(self, max_rows: Optional[int]) -> List[tuple]:
        out = []
        remaining = self.config.shm_ring_rows * self.num_actors if max_rows is None else int(max_rows)
        for wid, ring in enumerate(self._rings):
            if remaining <= 0:
                break
            # Cap the request at the ring's current occupancy: pop allocates
            # the full request up front, so asking for the worst case on
            # every drain churns tens of MB of empty buffers.
            avail = len(ring)
            if not avail:
                continue
            rows = ring.pop(min(remaining, avail))
            if rows.shape[0]:
                # The version column tags which param snapshot produced each
                # row; rows are in production order, so the last row carries
                # the freshest tag.
                self._note_version(wid, decode_version(rows[-1, -1]))
                out.append((wid, self._rows_to_batch(rows)))
                self._steps_received += rows.shape[0]
                remaining -= rows.shape[0]
        return out

    def drain_into(self, replay, max_batches: int = 1000, max_rows: Optional[int] = None) -> int:
        """Move pending transitions into replay; returns transitions moved.
        `max_rows` caps the transitions taken (the ingest rate limiter's
        budget); overshoot is at most one queue batch on the queue path."""
        moved = 0
        if self.transport == "shm":
            for _wid, batch in self._pop_ring_batches(max_rows):
                replay.add_batch(
                    batch["obs"],
                    batch["action"],
                    batch["reward"],
                    batch["discount"],
                    batch["next_obs"],
                )
                moved += len(batch["reward"])
            return moved
        for _ in range(max_batches):
            if max_rows is not None and moved >= max_rows:
                break
            try:
                wid, version, batch = self._queue.get_nowait()
            except queue_mod.Empty:
                break
            self._note_version(wid, version)
            replay.add_batch(
                batch["obs"],
                batch["action"],
                batch["reward"],
                batch["discount"],
                batch["next_obs"],
            )
            moved += len(batch["reward"])
        self._steps_received += moved
        return moved

    def drain_batches(
        self, max_batches: int = 1000, max_rows: Optional[int] = None,
        with_sources: bool = False,
    ) -> List:
        """Pop pending transition batches raw (for the device-replay ingest
        path, which packs them itself); returns a list of field dicts — or,
        with_sources=True, of (worker_id, fields) pairs so the guardrails'
        bad-row quarantine (train.py) can attribute non-finite replay rows
        back to the slot that produced them."""
        if self.transport == "shm":
            pairs = self._pop_ring_batches(max_rows)
            return pairs if with_sources else [b for _, b in pairs]
        out = []
        moved = 0
        for _ in range(max_batches):
            if max_rows is not None and moved >= max_rows:
                break
            try:
                wid, version, batch = self._queue.get_nowait()
            except queue_mod.Empty:
                break
            self._note_version(wid, version)
            out.append((wid, batch) if with_sources else batch)
            moved += len(batch["reward"])
        self._steps_received += moved
        return out

    def episode_stats(self) -> List[tuple]:
        out = []
        while True:
            try:
                out.append(self._episodes.get_nowait())
            except queue_mod.Empty:
                return out

    # --- failure detection / elastic recovery (SURVEY.md §5) ---

    def monitor(self) -> Dict[str, int]:
        """Supervise the worker fleet. Call periodically. Detects three
        failure shapes — death, heartbeat silence, and (when
        config.actor_no_progress_s > 0) heartbeating-but-zero-rows — and
        respawns through a per-slot exponential backoff; a slot failing
        config.quarantine_respawns times inside quarantine_window_s is
        quarantined instead of respawned (crash-loop circuit breaker)."""
        self._monitor_fault.tick()
        cfg = self.config
        now = time.time()
        respawned = 0
        for i, p in enumerate(self._procs):
            if self._quarantined[i]:
                # Quarantine probing: after the cooldown, one respawn
                # attempt. The slot leaves quarantine provisionally
                # (_probing) so the normal detectors cover it — but any
                # failure during the probe re-quarantines immediately
                # instead of re-entering the backoff/breaker cycle.
                if (
                    cfg.quarantine_probe_s > 0
                    and now - self._quarantined_at[i] >= cfg.quarantine_probe_s
                ):
                    self._quarantined[i] = False
                    self._probing[i] = True
                    self._probe_t[i] = now
                    self._fail_times[i] = []
                    self._respawns += 1
                    respawned += 1
                    trace.instant("actor_probe", worker=i)
                    print(
                        f"[pool] probing quarantined worker {i} after "
                        f"{cfg.quarantine_probe_s:.0f}s cooldown (single "
                        "respawn attempt)",
                        file=sys.stderr, flush=True,
                    )
                    self._spawn(i)
                continue
            if self._probing[i] and not self._pending_respawn[i]:
                # Probe success = sustained progress: rows delivered since
                # the probe spawn AND a full quarantine_window_s survived.
                if (
                    self._rows_seen_t[i] > self._probe_t[i]
                    and now - self._probe_t[i] >= cfg.quarantine_window_s
                ):
                    self._probing[i] = False
                    self._unquarantines += 1
                    trace.instant("actor_unquarantined", worker=i)
                    print(
                        f"[pool] worker {i} UN-QUARANTINED: sustained "
                        f"progress for {cfg.quarantine_window_s:.0f}s "
                        "after probe — fleet back to "
                        f"{self.num_actors - self.quarantined_count} "
                        "workers",
                        file=sys.stderr, flush=True,
                    )
            if not self._pending_respawn[i]:
                why = self._detect_failure(i, p, now)
                if why is None:
                    continue
                if p is not None and p.is_alive():
                    p.terminate()
                    p.join(timeout=_TERMINATE_JOIN_S)
                self._procs[i] = None
                if self._probing[i]:
                    # The single probe attempt failed: straight back to
                    # quarantine for another cooldown — no backoff loop.
                    self._probing[i] = False
                    self._quarantined[i] = True
                    self._quarantined_at[i] = now
                    trace.instant("actor_probe_failed", worker=i, why=why)
                    print(
                        f"[pool] probe of worker {i} failed ({why}); "
                        "re-quarantined",
                        file=sys.stderr, flush=True,
                    )
                    continue
                window = [
                    t for t in self._fail_times[i]
                    if now - t <= cfg.quarantine_window_s
                ]
                window.append(now)
                self._fail_times[i] = window
                if (
                    cfg.quarantine_respawns > 0
                    and len(window) >= cfg.quarantine_respawns
                ):
                    self._quarantined[i] = True
                    self._quarantined_at[i] = now
                    trace.instant("actor_quarantined", worker=i, why=why,
                                  failures=len(window))
                    print(
                        f"[pool] QUARANTINED worker {i}: {len(window)} "
                        f"failures (last: {why}) within "
                        f"{cfg.quarantine_window_s:.0f}s — respawns "
                        "suspended, training continues degraded on "
                        f"{self.num_actors - self.quarantined_count} "
                        "workers"
                        + (
                            f"; probe in {cfg.quarantine_probe_s:.0f}s"
                            if cfg.quarantine_probe_s > 0
                            else ""
                        ),
                        file=sys.stderr, flush=True,
                    )
                    continue
                backoff = min(
                    cfg.respawn_backoff_s * (2.0 ** (len(window) - 1)),
                    cfg.respawn_backoff_max_s,
                )
                self._backoff_until[i] = now + backoff
                self._pending_respawn[i] = True
                trace.instant("actor_respawn", worker=i, why=why,
                              backoff_s=round(backoff, 3))
            if self._pending_respawn[i] and now >= self._backoff_until[i]:
                self._pending_respawn[i] = False
                self._respawns += 1
                respawned += 1
                self._spawn(i)
        return {
            "respawned": respawned,
            "total_respawns": self._respawns,
            "quarantined": self.quarantined_count,
        }

    def _detect_failure(self, i: int, p, now: float) -> Optional[str]:
        """One worker slot's health check; returns the failure kind or
        None. heartbeat == 0 means the worker never finished booting (see
        _spawn) — the silent timeout and the zero-rows detector are not
        armed yet; real deaths are caught regardless."""
        if p is None or not p.is_alive():
            return "dead"
        hb = self._heartbeat[i]
        if hb <= 0.0:
            return None
        if now - hb > self.heartbeat_timeout:
            return "silent"
        no_progress_s = self.config.actor_no_progress_s
        if no_progress_s > 0.0:
            if self._last_rows_t[i] == 0.0:
                # First heartbeat seen with no rows yet: start the clock
                # here, not at spawn — boot time is not production time.
                self._last_rows_t[i] = now
            elif now - self._last_rows_t[i] > no_progress_s:
                return "no_rows"
        return None

    def quarantine_source(self, worker_id: int, why: str = "numeric") -> bool:
        """Quarantine one slot DIRECTLY — the guardrails' bad-row path
        (train.py): a worker repeatedly feeding non-finite experience is
        poisoning replay even though its process looks healthy, so it goes
        through the same breaker state the crash-loop detector uses
        (loud stderr, training continues degraded, probing un-quarantines
        it after quarantine_probe_s if it comes back clean). Returns False
        when the slot is already quarantined."""
        i = int(worker_id)
        if not 0 <= i < self.num_actors or self._quarantined[i]:
            return False
        p = self._procs[i]
        if p is not None and p.is_alive():
            p.terminate()
            p.join(timeout=_TERMINATE_JOIN_S)
        self._procs[i] = None
        self._probing[i] = False
        self._pending_respawn[i] = False
        self._fail_times[i] = []
        self._quarantined[i] = True
        self._quarantined_at[i] = time.time()
        trace.instant("actor_quarantined", worker=i, why=why)
        print(
            f"[pool] QUARANTINED worker {i} ({why}): repeatedly produced "
            "non-finite experience rows — respawns suspended, training "
            "continues degraded on "
            f"{self.num_actors - self.quarantined_count} workers"
            + (
                f"; probe in {self.config.quarantine_probe_s:.0f}s"
                if self.config.quarantine_probe_s > 0
                else ""
            ),
            file=sys.stderr, flush=True,
        )
        return True

    @property
    def quarantined_count(self) -> int:
        return sum(self._quarantined)

    def recovery_counters(self) -> Dict[str, int]:
        """Cumulative fault-history counters for the metrics JSONL
        (train.py logs them; tools.runs summarize surfaces them)."""
        return {
            "actor_respawns": self._respawns,
            "actor_quarantined": self.quarantined_count,
            "actor_unquarantined": self._unquarantines,
        }

    @property
    def steps_received(self) -> int:
        return self._steps_received
