from distributed_ddpg_tpu.actors.policy import NumpyPolicy, flatten_params, param_layout
from distributed_ddpg_tpu.actors.pool import ActorPool

__all__ = ["ActorPool", "NumpyPolicy", "flatten_params", "param_layout"]
