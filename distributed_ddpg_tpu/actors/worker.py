"""Rollout worker process (SURVEY.md §3.2's per-worker episode loop).

Each worker owns: one env, one OU noise process (per-worker instance, reset
per episode — SURVEY.md §2 #6), one n-step accumulator, and a numpy policy
refreshed from the shared-memory param buffer. It streams n-step transitions
back in batches over an mp.Queue and stamps a heartbeat every loop so the
pool's monitor can respawn it if it dies (SURVEY.md §5 'Failure detection';
the reference has none — a dead TF worker just stalls).

Workers never import jax (see policy.py). `fault_specs` is this worker's
slice of the run's FaultPlan (config.faults; faults.py) — (kind, at_step,
duration_s) tuples applied inline: `crash` raises, `hang` freezes WITHOUT
heartbeats (the silent-timeout respawn path), `stall` keeps heartbeating
but produces nothing (the pool monitor's zero-rows detector), `slow`
throttles env stepping for a bounded window.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np


def run_worker(
    worker_id: int,
    env_id: str,
    seed: int,
    layout,
    action_scale,
    action_offset,
    action_low,
    action_high,
    shared_params,          # mp.Array('f'), flat actor params
    param_version,          # mp.Value('l')
    transition_queue,       # mp.Queue (fallback transport)
    heartbeat,              # mp.Array('d', num_workers)
    stop_flag,              # mp.Value('b')
    ring_buf,               # mp.Array('B') backing a native.ShmRing, or None
    ring_rows: int,
    ou_theta: float,
    ou_sigma: float,
    ou_dt: float,
    n_step: int,
    gamma: float,
    send_every: int = 32,
    fault_specs=(),         # (kind, at_step, duration_s) tuples, sorted by step
    throttle_s: float = 0.0,
    gaussian_policy: bool = False,  # SAC: sample the policy, no OU noise
    log_std_min: float = -5.0,
    log_std_max: float = 2.0,
    warmup_uniform: int = 0,  # uniform-random actions for the first N steps
    episode_queue=None,     # optional mp.Queue for (worker_id, return, length)
    parent_pid: int = 0,    # pool process pid, captured at spawn time
    trace_dir: str = "",    # flight-recorder export dir ("" = off)
    serve_request_queue=None,   # served-actor transport (config.serve_actors):
    serve_response_queue=None,  # shared req queue + this worker's resp queue
    serve_fallbacks=None,       # mp.Array('l'): local-act fallback counters
    serve_timeout_s: float = 1.0,
    serve_fallback_s: float = 5.0,
) -> None:
    # Workers are CPU-only by construction; make BLAS behave in many procs.
    os.environ.setdefault("OMP_NUM_THREADS", "1")

    # NOTE: no heartbeat stamp until the loop below — heartbeat 0.0 is the
    # pool's "still booting" sentinel (ActorPool._spawn): under N-process
    # cold-start contention the imports + env build here take many times
    # the solo cost, and stamping mid-boot would arm the silent-timeout
    # respawn before the worker can possibly meet it.

    from distributed_ddpg_tpu import trace
    from distributed_ddpg_tpu.actors.policy import (
        NumpyPolicy,
        encode_version,
        seqlock_snapshot,
    )
    from distributed_ddpg_tpu.envs import make
    from distributed_ddpg_tpu.ops.noise import OUNoise
    from distributed_ddpg_tpu.replay.nstep import NStepAccumulator

    # Flight recorder (trace.py): a worker is its own interpreter, so it
    # owns its own ring and exports a per-process file on exit — Perfetto
    # merges by pid. Spans cover flushes (transport waits show up as long
    # actor_flush spans = learner-side backpressure) and episode instants.
    if trace_dir:
        trace.configure(capacity=8192)

    env = make(env_id, seed=seed)
    act_dim = len(np.atleast_1d(action_low))
    policy = NumpyPolicy(
        layout,
        action_scale,
        action_offset,
        gaussian=gaussian_policy,
        stochastic=gaussian_policy,
        seed=seed,
        log_std_min=log_std_min,
        log_std_max=log_std_max,
    )
    # SAC explores by sampling its own tanh-Gaussian; the OU process is
    # zeroed (sigma=0 keeps the loop shape identical at no cost).
    noise = OUNoise(
        (act_dim,),
        theta=ou_theta,
        sigma=0.0 if gaussian_policy else ou_sigma,
        dt=ou_dt,
        seed=seed,
    )
    nstep = NStepAccumulator(n_step, gamma)
    warmup_rng = np.random.default_rng(seed + 7919)  # uniform-warmup draws
    flat_view = np.frombuffer(shared_params, dtype=np.float32)
    flat_scratch = np.empty_like(flat_view)
    seen_version = -1

    # shm transport: attach to the pool's ring (the parent already ran
    # ring_init; the cached .so compiles in the parent so this load is a
    # dlopen, not a g++ run). The ring and the queue never mix — the pool
    # drains whichever transport it configured.
    ring = None
    if ring_buf is not None:
        from distributed_ddpg_tpu import native

        obs_dim = layout[0][0][0]  # first layer w is (obs_dim, hidden)
        ring = native.ShmRing(
            ring_buf, ring_rows, 2 * obs_dim + act_dim + 3, init=False
        )

    pending: list = []
    carry = None  # rows the ring had no room for on the last flush

    def maybe_refresh():
        """Seqlock read (policy.seqlock_snapshot; see ActorPool.broadcast):
        a torn or mid-write snapshot is discarded and the previous
        consistent params keep acting until the next step."""
        nonlocal seen_version
        v = seqlock_snapshot(shared_params, param_version, flat_scratch,
                             seen_version)
        if v is not None:
            policy.load_flat(flat_scratch)
            seen_version = v

    def flush():
        # seen_version tags which param snapshot produced this experience —
        # the pool converts it to learner-step staleness (SURVEY.md §5
        # 'params-staleness per actor').
        with trace.span("actor_flush", rows=len(pending)):
            _flush_impl()

    def _flush_impl():
        nonlocal carry
        if ring is not None:
            if pending:
                n = len(pending)
                rows = np.empty((n, ring.width), np.float32)
                o = pending[0][0].shape[-1]
                rows[:, :o] = np.stack([p[0] for p in pending])
                rows[:, o : o + act_dim] = np.stack([p[1] for p in pending])
                rows[:, o + act_dim] = [p[2] for p in pending]
                rows[:, o + act_dim + 1] = [p[3] for p in pending]
                rows[:, o + act_dim + 2 : 2 * o + act_dim + 2] = np.stack(
                    [p[4] for p in pending]
                )
                rows[:, -1] = encode_version(seen_version)
                pending.clear()
                carry = rows if carry is None else np.concatenate([carry, rows])
            # Backpressure mirrors mp.Queue.put: block (stamping the
            # heartbeat so the monitor doesn't respawn a merely-throttled
            # worker) until the learner drains the ring. This throttles env
            # stepping instead of dropping experience.
            while carry is not None and not stop_flag.value:
                if parent_pid and os.getppid() != parent_pid:
                    return  # orphaned mid-backpressure: drainer is gone
                accepted = ring.push(carry)
                carry = carry[accepted:] if accepted < carry.shape[0] else None
                if carry is not None:
                    heartbeat[worker_id] = time.time()
                    time.sleep(0.001)
            return
        if not pending:
            return
        batch = {
            "obs": np.stack([p[0] for p in pending]),
            "action": np.stack([p[1] for p in pending]),
            "reward": np.asarray([p[2] for p in pending], np.float32),
            "discount": np.asarray([p[3] for p in pending], np.float32),
            "next_obs": np.stack([p[4] for p in pending]),
        }
        # The queue is BOUNDED (pool maxsize): a blocking put() on a full
        # queue whose drainer died would hang past the orphan guard, so
        # mirror the ring path — bounded waits with the guard between them.
        import queue as queue_mod

        delivered = False
        while not stop_flag.value:
            if parent_pid and os.getppid() != parent_pid:
                return  # orphaned mid-backpressure: drainer is gone
            try:
                transition_queue.put((worker_id, seen_version, batch), timeout=0.1)
                delivered = True
                break
            except queue_mod.Full:
                heartbeat[worker_id] = time.time()
        if not delivered:
            # Clean shutdown (stop_flag set before or during the loop):
            # one non-blocking attempt delivers the tail when there's room;
            # a full queue drops it — bounded loss (< send_every rows),
            # matching the ring path's shutdown behavior.
            try:
                transition_queue.put_nowait((worker_id, seen_version, batch))
            except queue_mod.Full:
                pass
        pending.clear()

    # --- served acting (serve/; docs/SERVING.md) ---
    # With the serve transport attached, mu(s) comes from the learner
    # process's InferenceServer (dynamic batching across the fleet); the
    # local policy mirror stays loaded as the FALLBACK — any failure to
    # get a served action (queue full, timeout, dispatch error) degrades
    # to it for serve_fallback_s. The failure contract: a stalled or dead
    # serving stack costs latency, never a deadlock (chaos tests pin it).
    import queue as serve_queue_mod

    # Request ids start at a per-incarnation random 48-bit offset, and any
    # replies already sitting in the response queue are drained: the pool
    # reuses the SAME response queue across respawns of this slot, so a
    # late reply addressed to a dead incarnation must never collide with a
    # fresh incarnation's rid and deliver an action computed for a
    # different observation.
    serve_rid = int.from_bytes(os.urandom(6), "little")
    serve_down_until = 0.0
    if serve_response_queue is not None:
        while True:
            try:
                serve_response_queue.get_nowait()
            except Exception:
                break

    def _serve_degrade() -> None:
        nonlocal serve_down_until
        serve_down_until = time.time() + serve_fallback_s
        if serve_fallbacks is not None:
            serve_fallbacks[worker_id] += 1

    def served_mu(o: np.ndarray) -> np.ndarray:
        """One served action request, bounded by serve_timeout_s; the
        local mirror answers whenever the served path cannot."""
        nonlocal serve_rid
        if time.time() < serve_down_until:
            return policy(o)[0]
        serve_rid += 1
        try:
            serve_request_queue.put_nowait(
                (worker_id, serve_rid, np.asarray(o, np.float32))
            )
        except serve_queue_mod.Full:
            _serve_degrade()
            return policy(o)[0]
        deadline = time.time() + serve_timeout_s
        while time.time() < deadline and not stop_flag.value:
            if parent_pid and os.getppid() != parent_pid:
                return policy(o)[0]  # orphaned: server is gone
            try:
                rid, action = serve_response_queue.get(timeout=0.05)
            except serve_queue_mod.Empty:
                # Keep the heartbeat warm: a served wait is bounded and
                # healthy, not a silent worker.
                heartbeat[worker_id] = time.time()
                continue
            if rid != serve_rid:
                continue  # stale reply from a request we already gave up on
            if action is None:
                _serve_degrade()  # server shed or failed this request
                return policy(o)[0]
            return np.asarray(action, np.float32)
        _serve_degrade()
        return policy(o)[0]

    # --- scripted faults (faults.py; see module docstring) ---
    faults = sorted(fault_specs, key=lambda t: t[1])
    fault_i = 0
    slow_until, slow_sleep = 0, 0.0
    hung = False

    def _freeze(stamp_heartbeat: bool) -> None:
        """Injected hang/stall: park until the pool terminates this process
        (the recovery under test) or a clean stop/orphaning ends the run.
        `hang` parks WITHOUT heartbeats — the silent-timeout respawn path;
        `stall` keeps stamping them while producing nothing — the zero-rows
        detector path (pool.monitor)."""
        while not stop_flag.value:
            if parent_pid and os.getppid() != parent_pid:
                return
            if stamp_heartbeat:
                heartbeat[worker_id] = time.time()
            time.sleep(0.05)

    def apply_faults(step: int) -> bool:
        """Fire faults due at `step`; returns True if the worker must exit
        (it was hung/stalled and released by stop/orphaning)."""
        nonlocal fault_i, slow_until, slow_sleep
        while fault_i < len(faults) and faults[fault_i][1] <= step:
            kind, _, dur = faults[fault_i]
            fault_i += 1
            if kind == "crash":
                from distributed_ddpg_tpu.faults import InjectedFault

                raise InjectedFault(
                    f"injected crash in worker {worker_id} at step {step}"
                )
            if kind in ("hang", "stall"):
                _freeze(stamp_heartbeat=(kind == "stall"))
                return True
            if kind == "slow":
                from distributed_ddpg_tpu.faults import SLOW_FAULT_STEPS

                slow_until = step + SLOW_FAULT_STEPS
                slow_sleep = dur
        if step < slow_until and slow_sleep > 0.0:
            time.sleep(slow_sleep)
        return False

    maybe_refresh()
    obs, _ = env.reset(seed=seed)
    noise.reset()
    ep_return, ep_len, total_steps = 0.0, 0, 0

    # Orphan guard: stop_flag is only ever set by pool.stop(), which a
    # hard-killed pool process (SIGKILL, watchdog os._exit) never runs —
    # daemon=True also doesn't help there, since the interpreter's atexit
    # cleanup is skipped. A reparented worker (getppid no longer the pool
    # pid passed at spawn — capturing getppid() here instead would race
    # with a pool that dies during worker boot) has no consumer left, so
    # it must exit — without flush(), whose ring backpressure would
    # otherwise block forever on the dead drainer.
    orphaned = False
    while not stop_flag.value:
        if parent_pid and os.getppid() != parent_pid:
            orphaned = True
            break
        heartbeat[worker_id] = time.time()
        maybe_refresh()
        if throttle_s > 0.0:
            # Staleness-sweep experiment knob (config.actor_throttle_s):
            # slow env production so the learner can saturate the ratio
            # caps on slow hosts. Sleep sits BEFORE the step so the
            # heartbeat above keeps the respawn monitor quiet.
            time.sleep(throttle_s)
        if total_steps < warmup_uniform:
            # Uniform-random warmup (config.warmup_uniform_steps — SAC's
            # start_steps): broad seed data before the policy takes over.
            action = warmup_rng.uniform(action_low, action_high).astype(
                np.float32
            )
        else:
            mu = (
                served_mu(obs)
                if serve_request_queue is not None
                else policy(obs)[0]
            )
            action = mu + noise() * np.asarray(action_scale, np.float32)
        action = np.clip(action, action_low, action_high).astype(np.float32)
        next_obs, reward, terminated, truncated, _ = env.step(action)
        done = terminated  # truncation bootstraps: discount stays gamma^n
        pending.extend(
            nstep.push(obs[None], action[None], [reward], [done], next_obs[None])
        )
        ep_return += reward
        ep_len += 1
        total_steps += 1
        obs = next_obs

        if apply_faults(total_steps):
            hung = True  # parked by an injected hang/stall, then released
            break

        if terminated or truncated:
            # Flush the truncation tail through the accumulator so no
            # experience is stranded, then reset per-episode state.
            if truncated and not terminated:
                pending.extend(_flush_truncated(nstep, next_obs))
            trace.instant(
                "episode", ret=round(ep_return, 3), length=ep_len
            )
            if episode_queue is not None:
                try:
                    episode_queue.put_nowait((worker_id, ep_return, ep_len))
                except Exception:
                    pass
            obs, _ = env.reset()
            noise.reset()
            nstep.reset()
            ep_return, ep_len = 0.0, 0

        if len(pending) >= send_every:
            flush()

    # Orphaned workers skip the final flush (its backpressure would block
    # forever on the dead drainer) but still try to land their trace; so do
    # workers released from an injected hang/stall — their in-flight rows
    # are the "lost on crash" loss the fault is simulating.
    if not orphaned and not hung:
        flush()
    if trace_dir:
        try:
            trace.export(
                os.path.join(trace_dir, f"trace_actor{worker_id}.json")
            )
        except Exception:
            pass  # diagnostics must never fail a clean worker exit


def _flush_truncated(nstep, bootstrap_obs):
    """Emit the pending partial windows of a TRUNCATED episode. Unlike the
    terminal flush inside NStepAccumulator.push, these keep a nonzero
    bootstrap discount (the episode didn't end — time just ran out)."""
    out = []
    for e, pend in enumerate(nstep._pending):
        while pend:
            o, a, r, disc, nobs = nstep._emit(
                pend, bootstrap_obs, terminal=False, length=len(pend)
            )
            out.append((o, a, r, disc, nobs))
            pend.popleft()
    return out
