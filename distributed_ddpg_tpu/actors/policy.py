"""Pure-numpy actor policy for rollout workers.

The north star keeps rollout workers on CPU, unchanged in role
(BASELINE.json:5, SURVEY.md §3.2). Workers here run a numpy mirror of the
actor MLP — they never import jax, so worker processes are cheap to spawn,
can't contend for the TPU, and can't deadlock a forked XLA runtime.

Params travel learner -> workers as ONE flat f32 array in shared memory
(pool.py); `param_layout`/`flatten_params`/`NumpyPolicy.load_flat` define
the stable layout (layer order, w-then-b, C order).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

Layout = List[Tuple[Tuple[int, ...], Tuple[int, ...]]]  # [(w_shape, b_shape)]


def encode_version(version: int) -> np.float32:
    """Bit-cast an int32 param-version tag into the transition ring's f32
    version column. A plain float(version) loses integer exactness past
    2^24; bit-casting keeps the full int32 range. Safe because every hop
    (row assignment, concatenate, shm ring memcpy) is a bit-preserving
    f32 copy — nothing does arithmetic on the column."""
    return np.int32(version).view(np.float32)


def decode_version(tag) -> int:
    return int(np.float32(tag).view(np.int32))


def actor_head_dim(act_dim: int, sac: bool) -> int:
    """Actor output width: SAC's Gaussian head is [mean | log_std]."""
    return 2 * act_dim if sac else act_dim


def param_layout(obs_dim: int, act_dim: int, hidden: Sequence[int]) -> Layout:
    """`act_dim` here is the HEAD width — pass actor_head_dim(...) for SAC."""
    dims = [obs_dim, *hidden, act_dim]
    return [((dims[i], dims[i + 1]), (dims[i + 1],)) for i in range(len(dims) - 1)]


def layout_size(layout: Layout) -> int:
    return sum(int(np.prod(w)) + int(np.prod(b)) for w, b in layout)


def seqlock_snapshot(shared, version, out: np.ndarray, seen_version: int):
    """One seqlock read attempt of the pool's broadcast buffer
    (ActorPool.broadcast writes it: version odd while the flat array is
    mid-write, even when consistent). Copies into `out` and returns the
    new version when a CONSISTENT, not-yet-seen snapshot was read; returns
    None otherwise (nothing new, write in progress, or torn — the caller
    keeps acting on its previous params). Shared by the worker's local
    mirror (worker.py) and the inference server (serve/server.py) so the
    subtle discard discipline lives in exactly one place."""
    v = version.value
    if v == seen_version or v % 2 == 1:
        return None
    flat = np.frombuffer(shared, dtype=np.float32)
    out[:] = flat[: out.size]
    if version.value != v:
        return None
    return v


def flatten_params(params, out: np.ndarray | None = None) -> np.ndarray:
    """Flatten a (tuple of {'w','b'}) tree into one f32 vector (w then b,
    layer order). Writes into `out` when given (the shared-memory buffer)."""
    chunks = []
    for layer in params:
        chunks.append(np.asarray(layer["w"], np.float32).ravel())
        chunks.append(np.asarray(layer["b"], np.float32).ravel())
    flat = np.concatenate(chunks)
    if out is not None:
        out[: flat.size] = flat
        return out
    return flat


class NumpyPolicy:
    """mu(s) in numpy: relu hiddens, tanh output onto the action box.

    `gaussian=True` mirrors the SAC head (models/mlp.actor_gaussian_apply):
    the final layer is [mean | log_std]; deterministic mode acts on
    tanh(mean), `stochastic=True` samples the tanh-Gaussian with a local
    numpy RNG (workers explore by sampling the policy — no OU noise)."""

    def __init__(
        self,
        layout: Layout,
        action_scale,
        action_offset=0.0,
        gaussian: bool = False,
        stochastic: bool = False,
        seed: int | None = None,
        log_std_min: float = -5.0,
        log_std_max: float = 2.0,
    ):
        self.layout = layout
        self.scale = np.asarray(action_scale, np.float32)
        self.offset = np.asarray(action_offset, np.float32)
        self.gaussian = gaussian
        self.stochastic = stochastic
        self.log_std_min = log_std_min
        self.log_std_max = log_std_max
        self._rng = np.random.default_rng(seed) if stochastic else None
        self.layers = [
            {"w": np.zeros(w, np.float32), "b": np.zeros(b, np.float32)}
            for w, b in layout
        ]

    def load_flat(self, flat: np.ndarray) -> None:
        i = 0
        for layer, (w_shape, b_shape) in zip(self.layers, self.layout):
            n = int(np.prod(w_shape))
            layer["w"] = flat[i : i + n].reshape(w_shape).copy()
            i += n
            n = int(np.prod(b_shape))
            layer["b"] = flat[i : i + n].copy()
            i += n

    def head(self, obs: np.ndarray) -> np.ndarray:
        """Raw final-layer output — [mean | log_std_raw] for the SAC
        head, pre-tanh mu otherwise. The serve path's building block
        (serve/server.py): the server ships head rows and applies the
        squash/sampling itself, with per-client keys."""
        x = np.atleast_2d(obs)
        for layer in self.layers[:-1]:
            x = np.maximum(x @ layer["w"] + layer["b"], 0.0)
        return x @ self.layers[-1]["w"] + self.layers[-1]["b"]

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        x = np.atleast_2d(obs)
        for layer in self.layers[:-1]:
            x = np.maximum(x @ layer["w"] + layer["b"], 0.0)
        x = x @ self.layers[-1]["w"] + self.layers[-1]["b"]
        if self.gaussian:
            mean, log_std_raw = np.split(x, 2, axis=-1)
            if not self.stochastic:
                return np.tanh(mean) * self.scale + self.offset
            # Same soft clamp as the jax head so worker and learner agree
            # on the distribution the experience was drawn from.
            log_std = self.log_std_min + 0.5 * (
                self.log_std_max - self.log_std_min
            ) * (np.tanh(log_std_raw) + 1.0)
            u = mean + np.exp(log_std) * self._rng.standard_normal(
                mean.shape
            ).astype(np.float32)
            return np.tanh(u) * self.scale + self.offset
        return np.tanh(x) * self.scale + self.offset
