"""--strict_sync lockstep actor pool (SURVEY.md §5 'Race detection' row;
VERDICT r4 Missing #5).

The production ActorPool runs workers in separate processes: experience
arrival order, param-refresh timing, and drain interleaving all depend on
OS scheduling, so two runs of the same config differ bit-for-bit — which is
exactly what makes an async race impossible to replay. SyncActorPool is the
debug-mode replacement: the SAME worker semantics (NumpyPolicy + OU noise /
uniform warmup / n-step accumulation / truncation flush, mirroring
actors/worker.py run_worker step for step) executed INLINE on the driver
thread in a fixed round-robin env order. Every drain steps the envs a
deterministic number of times (the caller's ingest budget), so the whole
ingest→learn schedule is a pure function of the config — two runs produce
bit-identical metrics (tests/test_strict_sync.py) and any divergence from
an async run isolates the race to the async machinery.

One env step per grad step: train_jax requires both ratio gates armed with
strict_sync (config.py validation), which pins learner and ingest to the
configured ratio deterministically — at the default 1.0/1.0 that is the
reference's synchronous 1:1 schedule.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from distributed_ddpg_tpu.actors.policy import (
    NumpyPolicy,
    actor_head_dim,
    flatten_params,
    param_layout,
)
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs import make
from distributed_ddpg_tpu.envs.registry import EnvSpec
from distributed_ddpg_tpu.ops.noise import OUNoise
from distributed_ddpg_tpu.replay.nstep import NStepAccumulator


class _InlineActor:
    """One env's worth of worker state — the per-process state of
    actors/worker.py run_worker, held inline."""

    def __init__(self, config: DDPGConfig, spec: EnvSpec, seed: int):
        self.spec = spec
        self.env = make(config.env_id, seed=seed)
        self.noise = OUNoise(
            (spec.act_dim,),
            theta=config.ou_theta,
            sigma=0.0 if config.sac else config.ou_sigma,
            dt=config.ou_dt,
            seed=seed,
        )
        self.nstep = NStepAccumulator(config.n_step, config.gamma)
        self.warmup_rng = np.random.default_rng(seed + 7919)
        self.obs, _ = self.env.reset(seed=seed)
        self.ep_return = 0.0
        self.ep_len = 0

    def step(self, policy: NumpyPolicy, uniform: bool) -> tuple:
        """One env step; returns (nstep_rows, finished_episode|None)."""
        spec = self.spec
        if uniform:
            action = self.warmup_rng.uniform(
                spec.action_low, spec.action_high
            ).astype(np.float32)
        else:
            action = policy(self.obs)[0] + self.noise() * np.asarray(
                spec.action_scale, np.float32
            )
        action = np.clip(action, spec.action_low, spec.action_high).astype(
            np.float32
        )
        next_obs, reward, terminated, truncated, _ = self.env.step(action)
        rows = list(
            self.nstep.push(
                self.obs[None], action[None], [reward], [terminated],
                next_obs[None],
            )
        )
        self.ep_return += reward
        self.ep_len += 1
        self.obs = next_obs
        episode = None
        if terminated or truncated:
            if truncated and not terminated:
                from distributed_ddpg_tpu.actors.worker import _flush_truncated

                rows.extend(_flush_truncated(self.nstep, next_obs))
            episode = (self.ep_return, self.ep_len)
            self.obs, _ = self.env.reset()
            self.noise.reset()
            self.nstep.reset()
            self.ep_return, self.ep_len = 0.0, 0
        return rows, episode


class SyncActorPool:
    """Drop-in ActorPool replacement with deterministic inline stepping.
    Same driver-facing surface (train.py uses: start/stop/broadcast/
    drain_batches/drain_into/steps_received/monitor/episode_stats/
    staleness/env_steps_offset)."""

    def __init__(self, config: DDPGConfig, spec: EnvSpec,
                 num_actors: Optional[int] = None):
        self.config = config
        self.spec = spec
        self.num_actors = num_actors or config.num_actors
        self.layout = param_layout(
            spec.obs_dim,
            actor_head_dim(spec.act_dim, config.sac),
            tuple(config.actor_hidden),
        )
        self._policy = NumpyPolicy(
            self.layout,
            spec.action_scale,
            spec.action_offset,
            gaussian=config.sac,
            stochastic=config.sac,
            seed=config.seed + 1,
            log_std_min=config.sac_log_std_min,
            log_std_max=config.sac_log_std_max,
        )
        self._actors: List[_InlineActor] = []
        self._episodes: List[tuple] = []
        self._steps_received = 0
        self._env_steps_taken = 0
        self._next = 0  # round-robin cursor
        self._broadcast_step = 0
        self.env_steps_offset = 0

    # --- lifecycle ---

    def start(self, actor_params) -> "SyncActorPool":
        self._policy.load_flat(flatten_params(actor_params))
        self._actors = [
            # Same per-worker seed spacing as ActorPool._spawn gives its
            # processes a distinct stream per actor.
            _InlineActor(self.config, self.spec, self.config.seed + 101 * i)
            for i in range(self.num_actors)
        ]
        return self

    def stop(self) -> None:
        for a in self._actors:
            close = getattr(a.env, "close", None)
            if close is not None:
                close()
        self._actors = []

    # --- params ---

    def broadcast(self, actor_params, learner_step: int = 0) -> None:
        self._policy.load_flat(flatten_params(actor_params))
        self._broadcast_step = learner_step

    def staleness(self) -> Dict[str, float]:
        # Lockstep: experience is produced synchronously under the latest
        # broadcast params — staleness is zero by construction.
        return {"staleness_mean": 0.0, "staleness_max": 0}

    # --- experience ---

    def _produce(self, n_steps: int) -> List[Dict[str, np.ndarray]]:
        """Step the envs round-robin exactly n_steps times; returns the
        resulting n-step rows as one batch dict (possibly empty while the
        accumulators warm)."""
        warmup_total = self.config.resolved_warmup_uniform()
        fields: Dict[str, List[np.ndarray]] = {
            "obs": [], "action": [], "reward": [], "discount": [],
            "next_obs": [],
        }
        produced = 0
        for _ in range(n_steps):
            idx = self._next
            actor = self._actors[idx]
            self._next = (idx + 1) % self.num_actors
            uniform = (
                self.env_steps_offset + self._env_steps_taken < warmup_total
            )
            rows, episode = actor.step(self._policy, uniform)
            self._env_steps_taken += 1
            if episode is not None:
                # Same tuple shape as ActorPool's episode queue:
                # (actor_id, episode_return, episode_length).
                self._episodes.append((idx,) + episode)
            # nstep.push yields UNBATCHED rows: (obs_dim,), (act_dim,),
            # scalar reward/discount, (obs_dim,).
            for o, a, r, disc, nobs in rows:
                fields["obs"].append(o)
                fields["action"].append(a)
                fields["reward"].append(np.float32(r))
                fields["discount"].append(np.float32(disc))
                fields["next_obs"].append(nobs)
                produced += 1
        if not produced:
            return []
        batch = {
            "obs": np.stack(fields["obs"]),
            "action": np.stack(fields["action"]),
            "reward": np.asarray(fields["reward"], np.float32),
            "discount": np.asarray(fields["discount"], np.float32),
            "next_obs": np.stack(fields["next_obs"]),
        }
        self._steps_received += produced
        return [batch]

    def drain_batches(
        self, max_batches: int = 1000, max_rows: Optional[int] = None,
        with_sources: bool = False,
    ) -> List:
        if max_rows is None or max_rows <= 0:
            # strict_sync requires the ingest gate armed (config.py), so a
            # budget always arrives on the hot path; the warmup loop's
            # budget is the min-fill allowance.
            return []
        batches = self._produce(int(max_rows))
        if with_sources:
            # Inline actors interleave round-robin into ONE batch; there
            # is no per-row source to attribute (and no process to
            # quarantine) — the guardrails treat -1 as "untracked".
            return [(-1, b) for b in batches]
        return batches

    def drain_into(self, replay, max_batches: int = 1000,
                   max_rows: Optional[int] = None) -> int:
        moved = 0
        for batch in self.drain_batches(max_batches, max_rows):
            replay.add_batch(
                batch["obs"], batch["action"], batch["reward"],
                batch["discount"], batch["next_obs"],
            )
            moved += len(batch["reward"])
        return moved

    # --- bookkeeping ---

    @property
    def steps_received(self) -> int:
        # ROWS delivered, matching ActorPool's accounting exactly: the
        # driver's ingest budget and total_env_steps both count received
        # rows, and the warmup fill loop must see the gate open until the
        # REPLAY (not the env clock) reaches min_fill — the n-step
        # accumulator's held-back rows would otherwise stall warmup at the
        # budget boundary. The true env clock (self._env_steps_taken) runs
        # slightly ahead and only gates the uniform-warmup budget.
        return self._steps_received

    def episode_stats(self) -> List[tuple]:
        out, self._episodes = self._episodes, []
        return out

    def monitor(self) -> Dict[str, int]:
        return {"respawned": 0, "total_respawns": 0, "quarantined": 0}

    def recovery_counters(self) -> Dict[str, int]:
        # Inline actors cannot crash independently of the driver; the
        # counters exist for JSONL-schema parity with ActorPool.
        return {"actor_respawns": 0, "actor_quarantined": 0}

    def quarantine_source(self, worker_id: int, why: str = "numeric") -> bool:
        # Inline actors share the driver process; there is nothing to
        # quarantine (surface parity with ActorPool for the guardrails).
        return False
