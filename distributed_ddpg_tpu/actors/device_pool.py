"""On-device vectorized actors: Podracer/Anakin-style rollouts that never
leave HBM (config.actor_backend='device'; docs/DEVICE_ACTORS.md; PAPERS.md
arXiv 2104.06272, with the device-resident sample path motivated by the
in-network experience-sampling line, arXiv 2110.13506).

The host pool (actors/pool.py) steps CPU envs in worker processes, runs OU
noise in numpy, and ships rows host->HBM through the ingest pipeline —
mandatory for Gym/Mujoco, but for envs with JAX dynamics
(envs/jax_envs.py) it caps rollout throughput at the host ingest path
(~300 rows/ms measured ceiling) while the accelerator learner is hundreds
of times faster than the CPU baseline. This pool removes the host from the
experience path entirely:

  - ONE jitted program per chunk: a `lax.scan` of K iterations, each
    advancing E vmapped envs — per-env OU noise update, a = clip(mu(s) +
    ou * scale, bounds) (one MXU matmul over the E-batch), vmapped
    env.step with auto-reset, and the packed [E, D] transition rows —
    returning a [K*E, D] block that is already device-resident;
  - the block scatters into DeviceReplay's HBM ring via
    `DeviceReplay.insert_device_rows` (a donated jitted insert): no host
    staging ring, no transfer-scheduler ingest class, zero host<->device
    bytes per transition. The scheduler keeps its other lanes (lockstep /
    prefetch / d2h / serve) untouched;
  - param refresh is a POINTER SWAP: `set_params` stores a reference to
    the learner's live (device-resident, correctly sharded) actor params,
    and the next rollout dispatch reads them — no pool-broadcast
    shared-memory copy, no d2h. train.py re-swaps every chunk (the
    previous chunk's dispatch DONATED the old TrainState, so the stale
    reference must never be dispatched again).

Unlike `backend='jax_ondevice'` (the fused env+replay+learner monolith),
the learner keeps its full feature set — PER, guardrails, serving,
multi-host — because replay stays an ordinary DeviceReplay and the learner
programs are unchanged; this module only replaces WHO produces the rows.
The host pool can run alongside (num_actors > 0): both sources feed the
same ring, host rows through the ingest pipeline, device rows through the
donated insert, with the replay's host pointer-mirror advanced for both so
source attribution (guardrails) stays aligned.

Multi-host: the rollout and the insert are global SPMD programs over the
learner's (possibly process-spanning) mesh — every process executes the
identical program at the identical loop point (train_jax drives the pool
at lockstep sites only), so the rows landed in the replicated storage are
bit-identical on every replica and the `sync_ship` lockstep accounting for
HOST rows is untouched. Env state shards over the mesh's 'data' axis when
E divides it (physics FLOPs are negligible — sharding is a bonus); the
rows output is replicated, which is exactly what the replicated-storage
insert needs.

Failure contract (docs/RESILIENCE.md discipline): the `devactor:rollout`
chaos site ticks once per dispatch; a dispatch-time failure that left the
carry intact restarts bounded (<= 3, counter devactor_restarts, trace
instant devactor_restart) — past the budget, or when the donated carry was
already consumed, a typed DeviceActorError surfaces to the trainer.
"""

from __future__ import annotations

import sys
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs.jax_envs import make_jax_env
from distributed_ddpg_tpu.metrics import DevActorStats
from distributed_ddpg_tpu.ops.exploration import vector_env_step


class DeviceActorError(RuntimeError):
    """The device-actor rollout loop died past its bounded-restart budget
    (or with its donated carry already consumed); the original exception
    rides along as __cause__ — the same surfacing discipline as
    IngestError / PrefetchTimeout."""


def resolve_device_actor_chunk(config: DDPGConfig) -> int:
    """K (env steps per rollout dispatch): config.device_actor_chunk when
    set, else 64 on kernel-native TPU backends and 8 elsewhere — the same
    resolution discipline as resolve_learner_chunk, so CPU dev/test
    dispatches stay snappy while TPU chunks amortize dispatch overhead."""
    if config.device_actor_chunk > 0:
        return config.device_actor_chunk
    from distributed_ddpg_tpu.ops.fused_chunk import runs_native

    return 64 if runs_native() else 8


class ActorCarry(NamedTuple):
    """Everything the rollout loop owns between dispatches, as one donated
    pytree. Cumulative episode stats live ON DEVICE so the host only pays
    a two-scalar d2h at log cadence (snapshot), never per chunk."""

    env_state: object        # vmapped env state pytree, leading dim E
    obs: jnp.ndarray         # f32[E, obs_dim] current policy observations
    ou: jnp.ndarray          # f32[E, act_dim] OU noise state
    ep_ret: jnp.ndarray      # f32[E] running episode returns
    steps: jnp.ndarray       # i32[] cumulative env steps (warmup gate)
    episodes: jnp.ndarray    # i32[] cumulative finished episodes
    ret_sum: jnp.ndarray     # f32[] cumulative sum of finished returns
    key: jnp.ndarray         # PRNG key


class DeviceActorPool:
    """E vectorized JAX envs + policy + OU noise as one compiled rollout
    chunk, feeding DeviceReplay without leaving HBM (module docstring)."""

    def __init__(
        self,
        config: DDPGConfig,
        mesh: Optional[Mesh] = None,
        fault=None,
        warmup_offset: int = 0,
    ):
        from distributed_ddpg_tpu.parallel import mesh as mesh_lib

        self.config = config
        self.env = make_jax_env(config.env_id)
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(
            config.data_axis, config.model_axis
        )
        self.num_envs = E = int(config.device_actor_envs)
        self.chunk_size = K = resolve_device_actor_chunk(config)
        self.rows_per_chunk = K * E
        self._fault = fault
        self._stats = DevActorStats(seed=config.seed)
        self._params = None
        self._restarts = 0
        self._max_restarts = 3
        self._dispatches = 0
        self._steps = 0
        # Interval episode accounting: snapshot() differences the carry's
        # cumulative device counters against these host mirrors.
        self._eps_seen = 0
        self._ret_seen = 0.0

        env = self.env
        obs_dim, act_dim = env.obs_dim, env.act_dim
        self.obs_dim, self.act_dim = obs_dim, act_dim
        scale = ((env.action_high - env.action_low) / 2.0).astype(np.float32)
        offset = ((env.action_high + env.action_low) / 2.0).astype(np.float32)
        self.action_scale, self.action_offset = scale, offset
        low = jnp.asarray(env.action_low)
        high = jnp.asarray(env.action_high)
        cfg = config
        # REMAINING uniform-warmup budget (actors/pool.py
        # warmup_budget_per_worker parity): resumed progress counts against
        # the global budget, so a restored run never re-injects random
        # actions into a trained replay.
        warmup_uniform = max(
            0, cfg.resolved_warmup_uniform() - int(warmup_offset)
        )

        # Envs shard over 'data' when divisible; replicate otherwise (the
        # ondevice.py rule — physics FLOPs are negligible either way).
        data_size = self.mesh.shape["data"]
        env_axis = "data" if E % data_size == 0 else None

        def env_step(params, carry: ActorCarry):
            """One vectorized env step — the shared ops/exploration body
            (noise -> action -> vmapped step -> packed rows; key always
            splits 4 ways so the host-stepped parity reference in the
            tests can replay the exact stream) plus this pool's episode
            accounting. The warmup gate reads the pool's OWN cumulative
            step counter (the ondevice monolith gates on its ring fill;
            this pool shares the ring with other sources, so it counts
            its own production instead)."""
            key, ou, action, out, rows = vector_env_step(
                cfg, env, E, params, carry.env_state, carry.obs, carry.ou,
                carry.key, scale, offset, low, high,
                warmup_active=(
                    carry.steps < warmup_uniform
                    if warmup_uniform > 0
                    else None
                ),
            )
            ep_ret = carry.ep_ret + out.reward
            done_ret = jnp.where(out.done, ep_ret, 0.0)
            new_carry = ActorCarry(
                env_state=out.state,
                obs=out.obs,
                ou=ou,
                ep_ret=jnp.where(out.done, 0.0, ep_ret),
                steps=carry.steps + E,
                episodes=carry.episodes + out.done.sum().astype(jnp.int32),
                ret_sum=carry.ret_sum + done_ret.sum(),
                key=key,
            )
            return new_carry, rows

        def rollout(params, carry: ActorCarry):
            carry, rows = jax.lax.scan(
                lambda c, _: env_step(params, c), carry, None, length=K
            )
            # [K, E, D] -> [K*E, D], step-major: row order matches K serial
            # E-wide inserts, so the ring layout is what a per-step insert
            # sequence would have produced.
            return carry, rows.reshape(K * E, rows.shape[-1])

        # --- shardings + initial carry ---
        key = jax.random.PRNGKey(config.seed + 0xDA)
        k_init, k_run = jax.random.split(key)
        env_state = jax.vmap(env.init)(jax.random.split(k_init, E))
        carry = ActorCarry(
            env_state=env_state,
            obs=jax.vmap(env.observe)(env_state),
            ou=jnp.zeros((E, act_dim), jnp.float32),
            ep_ret=jnp.zeros((E,), jnp.float32),
            steps=jnp.zeros((), jnp.int32),
            episodes=jnp.zeros((), jnp.int32),
            ret_sum=jnp.zeros((), jnp.float32),
            key=k_run,
        )
        carry_spec = ActorCarry(
            env_state=jax.tree.map(lambda _: P(env_axis), env_state),
            obs=P(env_axis, None),
            ou=P(env_axis, None),
            ep_ret=P(env_axis),
            steps=P(),
            episodes=P(),
            ret_sum=P(),
            key=P(),
        )
        self._carry_sharding = mesh_lib.to_named(self.mesh, carry_spec)
        # Rows come out REPLICATED: that is the block sharding
        # DeviceReplay's donated insert expects against its replicated
        # storage (and what makes multi-host replicas bit-identical).
        rows_sharding = NamedSharding(self.mesh, P(None, None))
        # Pure rollout body, kept for composition inside LARGER jitted
        # programs (the fused megastep, parallel/megastep.py): the fused
        # beat calls it on the freshly-updated actor params in the same
        # program, so its rows land with zero extra dispatches. The jitted
        # wrapper below stays the standalone (warmup / unfused) path.
        self._rollout_fn = rollout
        # Params keep whatever sharding the learner's live tree carries
        # (replicated, or TP-sharded under model_axis > 1): no in_shardings
        # pin, so the pointer-swap refresh never pays a resharding copy.
        self._rollout = jax.jit(
            rollout,
            out_shardings=(self._carry_sharding, rows_sharding),
            donate_argnums=(1,),
        )
        self._carry: ActorCarry = jax.device_put(carry, self._carry_sharding)

    # --- param refresh (device-side pointer swap) ---

    def set_params(self, actor_params) -> None:
        """Swap in the learner's LIVE actor params (a device-resident
        pytree reference — nothing is copied or transferred). Callers must
        re-swap after every learner dispatch that donates the TrainState:
        the previously-stored tree is deleted by that donation, and
        dispatching a rollout against it would raise. train.py does this
        at the top of every after_chunk."""
        self._params = actor_params

    # --- driving ---

    def run_chunk(self, replay) -> int:
        """One rollout dispatch: K scan steps x E envs -> [K*E, D] rows ->
        donated scatter into `replay` (DeviceReplay.insert_device_rows).
        Returns rows produced. Dispatch-time failures with the carry
        intact restart bounded (module docstring failure contract)."""
        if self._params is None:
            raise DeviceActorError(
                "set_params() must install the learner's live actor params "
                "before the first rollout dispatch"
            )
        while True:
            try:
                # Chaos site ticks BEFORE the dispatch consumes the donated
                # carry, so an injected crash always leaves it retryable.
                if self._fault is not None:
                    self._fault.tick()
                t0 = time.perf_counter()
                with trace.span(
                    "devactor_rollout",
                    rows=self.rows_per_chunk, envs=self.num_envs,
                ):
                    carry, rows = self._rollout(self._params, self._carry)
                    self._carry = carry
                    replay.insert_device_rows(rows)
                dt = time.perf_counter() - t0
            except Exception as e:  # NOT BaseException: Ctrl-C must abort
                if not self._recoverable(e):
                    raise DeviceActorError(
                        "device-actor rollout failed past the restart "
                        "budget"
                    ) from e
                continue
            self._stats.record_chunk(self.rows_per_chunk, dt)
            self._dispatches += 1
            self._steps += self.rows_per_chunk
            return self.rows_per_chunk

    def _recoverable(self, exc: Exception) -> bool:
        """Bounded-restart policy: recover only while the budget holds AND
        the donated carry is still intact (a failure after donation
        consumed the buffers cannot be retried against deleted arrays —
        the run_sample_chunk fallback's discipline). Single-process ONLY:
        on a multi-host mesh the rollout+insert are global SPMD programs,
        and a per-process retry would enqueue extra programs on THIS
        process alone — forking the pod's per-process device-op order
        (the docs/TRANSFER.md invariant). There the failure must surface
        immediately so the pod deadline/abort contract (PodPeerLost,
        exit 76) handles it pod-wide."""
        if jax.process_count() > 1:
            return False
        if self._restarts >= self._max_restarts:
            return False
        if any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree.leaves(self._carry)
        ):
            return False
        self._restarts += 1
        trace.instant("devactor_restart", n=self._restarts)
        print(
            f"[devactor] rollout dispatch failed ({exc!r}); restarting "
            f"({self._restarts}/{self._max_restarts})",
            file=sys.stderr, flush=True,
        )
        return True

    # --- fused-megastep composition (parallel/megastep.py) ---

    @property
    def rollout_fn(self):
        """The pure rollout body — (params, carry) -> (carry, rows[K*E, D])
        — for composition inside the fused megastep's beat program. Same
        function the standalone jit wraps, so the fused and unfused row
        streams are bit-identical for the same params/carry/key."""
        return self._rollout_fn

    def absorb_fused_chunk(self, carry: ActorCarry, dur_s: float,
                           beats: int = 1) -> None:
        """Install the rollout carry returned by a fused megastep beat and
        advance the host counters exactly as run_chunk would. The rollout
        ran INSIDE the beat program, so there is no separate dispatch to
        time — dur_s is the whole beat, and devactor_chunk_ms equals the
        fused beat time in fused mode (docs/FUSED_BEAT.md). A B-beat
        superstep passes beats=B: one dispatch that rolled out B chunks
        (step accounting scales; the chunk timer records the whole
        superstep as one dispatch, so devactor_chunk_ms reads as the
        superstep time — the amortization IS the point)."""
        self._carry = carry
        self._stats.record_chunk(self.rows_per_chunk * beats, dur_s)
        self._dispatches += 1
        self._steps += self.rows_per_chunk * beats

    # --- rollout-state checkpointing (docs/DEVICE_ACTORS.md) ---

    def carry_state_dict(self) -> dict:
        """Host snapshot of the rollout carry — env state, observations,
        OU noise, per-env episode accumulators, the step/episode counters,
        and the PRNG key — as flat numpy leaves keyed by tree position
        (the carry is a fixed NamedTuple for a given config, so position
        is a stable identity). Rides the checkpoint as a sidecar
        (checkpoint.py devactor_carry.npz, covered by the manifest) so a
        resumed device-actor run CONTINUES its E episodes instead of
        restarting them. One bounded d2h, called at checkpoint cadence
        only.

        Multi-host with the env axis sharded over processes: no single
        writer can pull shards it doesn't address — returns None (the
        checkpoint simply omits the sidecar and a resumed run starts
        fresh episodes, the pre-PR-10 behavior), same single-writer
        limitation as the multi-host sharded replay snapshot
        (docs/REPLAY_SHARDING.md)."""
        leaves = jax.tree.leaves(self._carry)
        if any(
            not getattr(leaf, "is_fully_addressable", True)
            for leaf in leaves
        ):
            return None
        return {
            f"leaf_{i}": np.asarray(jax.device_get(leaf))
            for i, leaf in enumerate(leaves)
        }

    def load_carry_state(self, state: dict) -> bool:
        """Restore a carry_state_dict snapshot into the live carry
        (shape/dtype-validated leaf by leaf). Returns False — with a loud
        note, episodes then start fresh — when the snapshot does not
        match this pool's carry tree (changed env, E, or algorithm
        family): a mismatched resume must degrade to the pre-checkpoint
        behavior, not crash the run. On success the interval episode
        mirrors re-sync so the first snapshot() after resume reports
        deltas, not the whole restored history."""
        leaves, treedef = jax.tree.flatten(self._carry)
        restored = []
        for i, ref in enumerate(leaves):
            arr = state.get(f"leaf_{i}")
            if (
                arr is None
                or tuple(arr.shape) != tuple(ref.shape)
                or np.dtype(arr.dtype) != np.dtype(ref.dtype)
            ):
                print(
                    f"[devactor] checkpointed rollout state does not match "
                    f"this config's carry (leaf {i}: "
                    f"{None if arr is None else (arr.shape, str(arr.dtype))}"
                    f" vs {(tuple(ref.shape), str(ref.dtype))}); starting "
                    "fresh episodes",
                    file=sys.stderr, flush=True,
                )
                return False
            restored.append(arr)
        if len(state) > len(leaves):
            print(
                "[devactor] checkpointed rollout state has extra leaves; "
                "starting fresh episodes",
                file=sys.stderr, flush=True,
            )
            return False
        carry = jax.tree.unflatten(treedef, [jnp.asarray(a) for a in restored])
        self._carry = jax.device_put(carry, self._carry_sharding)
        self._eps_seen = int(jax.device_get(self._carry.episodes))
        self._ret_seen = float(jax.device_get(self._carry.ret_sum))
        # NOTE: the host step mirror (steps_done) stays at 0 — restored
        # production is already counted by the trainer's env_steps_offset,
        # and double-counting would eat the remaining env-step budget. The
        # DEVICE counter (carry.steps) keeps its cumulative value, which
        # is exactly what the uniform-warmup gate needs to stay closed.
        trace.instant(
            "devactor_carry_restored",
            steps=int(jax.device_get(self._carry.steps)),
            episodes=self._eps_seen,
        )
        return True

    # --- host-side views ---

    @property
    def steps_done(self) -> int:
        """Env steps produced so far (host counter — dispatches * K * E;
        identical on every process, so multi-host budget math may use it)."""
        return self._steps

    @property
    def restarts(self) -> int:
        return self._restarts

    def snapshot(self) -> dict:
        """devactor_* observability fields for the train/final records:
        interval rows/s + per-chunk dispatch tails (metrics.DevActorStats)
        plus the episode stats differenced from the carry's cumulative
        device counters — a two-scalar d2h, paid only at log cadence."""
        out = self._stats.snapshot()
        eps = int(jax.device_get(self._carry.episodes))
        ret = float(jax.device_get(self._carry.ret_sum))
        d_eps = eps - self._eps_seen
        d_ret = ret - self._ret_seen
        self._eps_seen, self._ret_seen = eps, ret
        out["devactor_env_steps"] = self._steps
        out["devactor_episodes"] = eps
        if d_eps > 0:
            out["devactor_episode_return"] = round(d_ret / d_eps, 6)
        out["devactor_restarts"] = self._restarts
        return out


# ---------------------------------------------------------------------------
# program-contract analyzer hook (analysis/programs.py; docs/ANALYSIS.md
# "Layer 2")
# ---------------------------------------------------------------------------


def program_specs():
    """The rollout scan as one traced program: 4 vmapped probe envs x a
    chunk of 2, under the 2-device CPU probe mesh. The donated carry must
    alias through in the lowered artifact — a rollout that silently stops
    aliasing would double the env-state HBM every dispatch."""
    from distributed_ddpg_tpu.analysis.programs import (
        BuiltProgram,
        ProgramSpec,
        probe_config,
        probe_mesh,
    )

    def build(tp: bool = False):
        def _build():
            config = probe_config(
                device_actor_envs=4, device_actor_chunk=2,
                model_axis=2 if tp else 1,
            )
            mesh = probe_mesh(2 if tp else 1)
            pool = DeviceActorPool(config, mesh=mesh)
            from distributed_ddpg_tpu.learner import init_train_state
            from distributed_ddpg_tpu.parallel import mesh as mesh_lib

            params = init_train_state(
                config, pool.env.obs_dim, pool.env.act_dim, config.seed
            ).actor_params
            if tp:
                # The live tree's placement: TP-sharded kernels per the
                # rule table, exactly what the pointer-swap refresh hands
                # the rollout (docs/MESH.md).
                params = jax.device_put(
                    params,
                    mesh_lib.to_named(
                        mesh, mesh_lib.net_pspec(params, mesh.shape["model"])
                    ),
                )
            return BuiltProgram(pool._rollout, (params, pool._carry), (1,))
        return _build

    return [
        ProgramSpec("devactor.rollout", "actors/device_pool.py", build()),
        ProgramSpec(
            "devactor.rollout.tp", "actors/device_pool.py", build(tp=True)
        ),
    ]
