"""Built-in Pendulum environment with Pendulum-v1 dynamics (SURVEY.md §2
'Environment': one Gym continuous-control env per worker).

Implements the exact classic-control equations (g=10, m=1, l=1, dt=0.05,
max_torque=2, max_speed=8, reward = -(th^2 + 0.1*thdot^2 + 0.001*u^2),
200-step time limit) so the integration ladder's first rung
(BASELINE.json:7) runs with zero external dependencies; the registry prefers
gymnasium's Pendulum-v1 when it is importable and falls back to this.

Gymnasium-style API: reset(seed) -> (obs, info); step(a) -> (obs, reward,
terminated, truncated, info).
"""

from __future__ import annotations

import numpy as np


def _angle_normalize(x):
    return ((x + np.pi) % (2 * np.pi)) - np.pi


class Pendulum:
    max_speed = 8.0
    max_torque = 2.0
    dt = 0.05
    g = 10.0
    m = 1.0
    l = 1.0
    max_episode_steps = 200

    observation_dim = 3
    action_dim = 1
    action_low = np.array([-2.0], np.float32)
    action_high = np.array([2.0], np.float32)

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(2, np.float64)  # (theta, theta_dot)
        self._t = 0

    def _obs(self) -> np.ndarray:
        th, thdot = self._state
        return np.array([np.cos(th), np.sin(th), thdot], np.float32)

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        high = np.array([np.pi, 1.0])
        self._state = self._rng.uniform(-high, high)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        th, thdot = self._state
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -self.max_torque, self.max_torque))
        cost = _angle_normalize(th) ** 2 + 0.1 * thdot**2 + 0.001 * u**2
        newthdot = thdot + (
            3.0 * self.g / (2.0 * self.l) * np.sin(th) + 3.0 / (self.m * self.l**2) * u
        ) * self.dt
        newthdot = np.clip(newthdot, -self.max_speed, self.max_speed)
        newth = th + newthdot * self.dt
        self._state = np.array([newth, newthdot])
        self._t += 1
        truncated = self._t >= self.max_episode_steps
        return self._obs(), -float(cost), False, truncated, {}

    def close(self):
        pass
