"""On-device (JAX) environments — the TPU-native extension of SURVEY.md §1's
'Environment' row.

The reference (and the `jax_tpu` backend here) steps CPU envs in worker
processes. For envs whose dynamics are a few FLOPs of arithmetic, that
topology leaves the accelerator idle between batches; these implementations
express the dynamics as pure JAX functions so the WHOLE actor-learner loop —
policy forward, exploration noise, env physics, replay insert, learner
update — compiles into one XLA program (ondevice.py). vmap supplies the
batch dimension: one `step` call advances E envs in lockstep on the MXU/VPU.

API (functional, scan/vmap-friendly; no Python state):
  env.init(key)            -> state pytree (single env)
  env.step(state, u, key)  -> StepOut(state, obs, boot_obs, reward, done)
                              with AUTO-RESET: when an episode ends, `state`
                              is already the reset state and `obs` its first
                              observation (what the policy acts on next),
                              while `boot_obs` is the PRE-reset next
                              observation — the correct bootstrap target for
                              the stored transition (time-limit truncation
                              keeps bootstrapping; conflating the two would
                              bootstrap across the episode boundary).
  env.observe(state)       -> obs

JaxPendulum mirrors the builtin numpy Pendulum (envs/pendulum.py) equation
for equation — g=10, m=1, l=1, dt=0.05, max_torque=2, max_speed=8,
200-step time limit — asserted by tests/test_jax_env.py, so `Pendulum-v1`
results are comparable across all three backends.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class StepOut(NamedTuple):
    state: object             # post-step state (reset already applied if done)
    obs: jnp.ndarray          # observation of `state` (policy input)
    boot_obs: jnp.ndarray     # pre-reset next observation (replay next_obs)
    reward: jnp.ndarray       # f32[]
    done: jnp.ndarray         # bool[] episode boundary (truncation included)
    # bool[] TRUE termination (env reached an absorbing state): bootstrap
    # discount is 0. Time-limit truncation keeps done=True, terminated=False
    # and keeps bootstrapping. Pendulum only truncates; MountainCar also
    # terminates at the goal.
    terminated: jnp.ndarray


class PendulumState(NamedTuple):
    th: jnp.ndarray       # f32[] angle
    thdot: jnp.ndarray    # f32[] angular velocity
    t: jnp.ndarray        # i32[] step-in-episode counter


def _angle_normalize(x):
    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


class JaxPendulum:
    """Pendulum-v1 dynamics as pure JAX (see module docstring)."""

    max_speed = 8.0
    max_torque = 2.0
    dt = 0.05
    g = 10.0
    m = 1.0
    l = 1.0
    max_episode_steps = 200

    obs_dim = 3
    act_dim = 1
    action_low = np.array([-2.0], np.float32)
    action_high = np.array([2.0], np.float32)

    def init(self, key) -> PendulumState:
        high = jnp.array([jnp.pi, 1.0], jnp.float32)
        th, thdot = jax.random.uniform(key, (2,), jnp.float32, -high, high)
        return PendulumState(th=th, thdot=thdot, t=jnp.zeros((), jnp.int32))

    def observe(self, s: PendulumState) -> jnp.ndarray:
        return jnp.stack([jnp.cos(s.th), jnp.sin(s.th), s.thdot]).astype(jnp.float32)

    def step(self, s: PendulumState, action, key):
        u = jnp.clip(action.reshape(())[None], -self.max_torque, self.max_torque)[0]
        cost = (
            _angle_normalize(s.th) ** 2 + 0.1 * s.thdot**2 + 0.001 * u**2
        )
        newthdot = s.thdot + (
            3.0 * self.g / (2.0 * self.l) * jnp.sin(s.th)
            + 3.0 / (self.m * self.l**2) * u
        ) * self.dt
        newthdot = jnp.clip(newthdot, -self.max_speed, self.max_speed)
        newth = s.th + newthdot * self.dt
        t = s.t + 1
        done = t >= self.max_episode_steps
        stepped = PendulumState(th=newth, thdot=newthdot, t=t)
        # Auto-reset: where the time limit hit, the next state is a fresh
        # episode start (same distribution as init).
        fresh = self.init(key)
        nxt = PendulumState(
            th=jnp.where(done, fresh.th, newth),
            thdot=jnp.where(done, fresh.thdot, newthdot),
            t=jnp.where(done, fresh.t, t),
        )
        return StepOut(
            state=nxt,
            obs=self.observe(nxt),
            boot_obs=self.observe(stepped),
            reward=-cost.astype(jnp.float32),
            done=done,
            terminated=jnp.zeros((), bool),  # Pendulum only truncates
        )


class MountainCarState(NamedTuple):
    pos: jnp.ndarray      # f32[] position
    vel: jnp.ndarray      # f32[] velocity
    t: jnp.ndarray        # i32[] step-in-episode counter


class JaxMountainCar:
    """MountainCarContinuous-v0 dynamics as pure JAX, equation for equation
    with gymnasium's continuous_mountain_car (power=0.0015, gravity term
    0.0025*cos(3x), goal at x>=0.45 with vel>=0, +100 terminal reward,
    -0.1*a^2 action cost, 999-step time limit) — asserted against the real
    gymnasium env by tests/test_ondevice.py. Unlike Pendulum this env truly
    TERMINATES, exercising the terminated/truncated split end to end."""

    power = 0.0015
    gravity = 0.0025
    min_position = -1.2
    max_position = 0.6
    max_speed = 0.07
    goal_position = 0.45
    goal_velocity = 0.0
    max_episode_steps = 999

    obs_dim = 2
    act_dim = 1
    action_low = np.array([-1.0], np.float32)
    action_high = np.array([1.0], np.float32)

    def init(self, key) -> MountainCarState:
        pos = jax.random.uniform(key, (), jnp.float32, -0.6, -0.4)
        return MountainCarState(
            pos=pos, vel=jnp.zeros((), jnp.float32), t=jnp.zeros((), jnp.int32)
        )

    def observe(self, s: MountainCarState) -> jnp.ndarray:
        return jnp.stack([s.pos, s.vel]).astype(jnp.float32)

    def step(self, s: MountainCarState, action, key):
        force = jnp.clip(action.reshape(())[None], -1.0, 1.0)[0]
        vel = s.vel + force * self.power - self.gravity * jnp.cos(3.0 * s.pos)
        vel = jnp.clip(vel, -self.max_speed, self.max_speed)
        pos = jnp.clip(s.pos + vel, self.min_position, self.max_position)
        vel = jnp.where((pos <= self.min_position) & (vel < 0.0), 0.0, vel)
        t = s.t + 1
        terminated = (pos >= self.goal_position) & (vel >= self.goal_velocity)
        done = terminated | (t >= self.max_episode_steps)
        reward = jnp.where(terminated, 100.0, 0.0) - 0.1 * force**2
        stepped = MountainCarState(pos=pos, vel=vel, t=t)
        fresh = self.init(key)
        nxt = MountainCarState(
            pos=jnp.where(done, fresh.pos, pos),
            vel=jnp.where(done, fresh.vel, vel),
            t=jnp.where(done, fresh.t, t),
        )
        return StepOut(
            state=nxt,
            obs=self.observe(nxt),
            boot_obs=self.observe(stepped),
            reward=reward.astype(jnp.float32),
            done=done,
            terminated=terminated,
        )


_JAX_ENVS = {
    "Pendulum-v1": JaxPendulum,
    "builtin/Pendulum-v1": JaxPendulum,
    "MountainCarContinuous-v0": JaxMountainCar,
    "builtin/MountainCarContinuous-v0": JaxMountainCar,
}


def has_jax_env(env_id: str) -> bool:
    return env_id in _JAX_ENVS


def make_jax_env(env_id: str):
    if env_id not in _JAX_ENVS:
        raise ValueError(
            f"no on-device (JAX) implementation for {env_id!r}; available: "
            f"{sorted(set(_JAX_ENVS))} — use --backend=jax_tpu for CPU envs"
        )
    return _JAX_ENVS[env_id]()
