from distributed_ddpg_tpu.envs.registry import EnvSpec, make, spec_of

__all__ = ["make", "spec_of", "EnvSpec"]
