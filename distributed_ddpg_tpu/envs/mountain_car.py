"""Built-in MountainCarContinuous environment (SURVEY.md §2 'Environment'
row; companion to envs/pendulum.py).

Implements gymnasium's continuous_mountain_car equations exactly
(power=0.0015, gravity term 0.0025*cos(3x), goal at x>=0.45 with vel>=0,
+100 terminal reward, -0.1*a^2 per-step action cost, 999-step time limit)
so this second integration env — the first with TRUE termination rather
than time-limit truncation only — runs with zero external dependencies.
The on-device twin is envs/jax_envs.JaxMountainCar.

Gymnasium-style API: reset(seed) -> (obs, info); step(a) -> (obs, reward,
terminated, truncated, info).
"""

from __future__ import annotations

import numpy as np


class MountainCarContinuous:
    power = 0.0015
    gravity = 0.0025
    min_position = -1.2
    max_position = 0.6
    max_speed = 0.07
    goal_position = 0.45
    goal_velocity = 0.0
    max_episode_steps = 999

    observation_dim = 2
    action_dim = 1
    action_low = np.array([-1.0], np.float32)
    action_high = np.array([1.0], np.float32)

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._pos = 0.0
        self._vel = 0.0
        self._t = 0

    def _obs(self) -> np.ndarray:
        return np.array([self._pos, self._vel], np.float32)

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = float(self._rng.uniform(-0.6, -0.4))
        self._vel = 0.0
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        force = float(np.clip(np.asarray(action).reshape(-1)[0], -1.0, 1.0))
        self._vel += force * self.power - self.gravity * np.cos(3.0 * self._pos)
        self._vel = float(np.clip(self._vel, -self.max_speed, self.max_speed))
        self._pos = float(
            np.clip(self._pos + self._vel, self.min_position, self.max_position)
        )
        if self._pos <= self.min_position and self._vel < 0.0:
            self._vel = 0.0
        self._t += 1
        terminated = (
            self._pos >= self.goal_position and self._vel >= self.goal_velocity
        )
        truncated = not terminated and self._t >= self.max_episode_steps
        reward = (100.0 if terminated else 0.0) - 0.1 * force**2
        return self._obs(), reward, terminated, truncated, {}

    def close(self):
        pass
