"""Environment registry (SURVEY.md §1 'Environment' row).

`make(env_id, seed)` resolves, in order:
1. built-in pure-numpy envs (zero-dependency: Pendulum);
2. gymnasium, if importable (covers the BASELINE.json ladder:
   LunarLanderContinuous, BipedalWalker, HalfCheetah, Humanoid).

Everything downstream (actors, replay, learner) only sees the EnvSpec +
the gymnasium 5-tuple step API, so new env sources plug in here.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from distributed_ddpg_tpu.envs.mountain_car import MountainCarContinuous
from distributed_ddpg_tpu.envs.pendulum import Pendulum

_BUILTIN = {
    "Pendulum-v1": Pendulum,
    "builtin/Pendulum-v1": Pendulum,
    "MountainCarContinuous-v0": MountainCarContinuous,
    "builtin/MountainCarContinuous-v0": MountainCarContinuous,
}

# Gymnasium retires env versions (DeprecatedEnv); keep the BASELINE.md ladder
# ids working by bumping to the successor when the pinned version is gone.
_VERSION_ALIASES = {
    "LunarLanderContinuous-v2": "LunarLanderContinuous-v3",
}


class EnvSpec(NamedTuple):
    obs_dim: int
    act_dim: int
    action_low: np.ndarray
    action_high: np.ndarray

    @property
    def action_scale(self) -> np.ndarray:
        """Symmetric bound for tanh squashing (classic DDPG assumes
        symmetric action spaces; asymmetric spaces use scale+offset)."""
        return ((self.action_high - self.action_low) / 2.0).astype(np.float32)

    @property
    def action_offset(self) -> np.ndarray:
        return ((self.action_high + self.action_low) / 2.0).astype(np.float32)


class _GymnasiumAdapter:
    """Wraps a gymnasium env; normalizes seeding and exposes spec fields."""

    def __init__(self, env_id: str, seed: int = 0):
        import gymnasium

        self._env = gymnasium.make(env_id)
        self._seed = seed
        self._first_reset = True

    def reset(self, seed: int | None = None):
        if seed is None and self._first_reset:
            seed = self._seed
        self._first_reset = False
        return self._env.reset(seed=seed)

    def step(self, action):
        return self._env.step(np.asarray(action, np.float32))

    @property
    def observation_dim(self) -> int:
        return int(np.prod(self._env.observation_space.shape))

    @property
    def action_dim(self) -> int:
        return int(np.prod(self._env.action_space.shape))

    @property
    def action_low(self) -> np.ndarray:
        return np.asarray(self._env.action_space.low, np.float32)

    @property
    def action_high(self) -> np.ndarray:
        return np.asarray(self._env.action_space.high, np.float32)

    def close(self):
        self._env.close()


def make(env_id: str, seed: int = 0, prefer_builtin: bool = False):
    if env_id in _BUILTIN and (prefer_builtin or not _has_gymnasium()):
        return _BUILTIN[env_id](seed=seed)
    if _has_gymnasium():
        try:
            return _GymnasiumAdapter(env_id, seed=seed)
        except Exception:
            if env_id in _VERSION_ALIASES:
                return _GymnasiumAdapter(_VERSION_ALIASES[env_id], seed=seed)
            if env_id in _BUILTIN:
                return _BUILTIN[env_id](seed=seed)
            raise
    if env_id in _BUILTIN:
        return _BUILTIN[env_id](seed=seed)
    raise ValueError(
        f"Unknown env {env_id!r}: not a builtin and gymnasium is unavailable"
    )


def _has_gymnasium() -> bool:
    try:
        import gymnasium  # noqa: F401

        return True
    except ImportError:
        return False


def spec_of(env) -> EnvSpec:
    return EnvSpec(
        obs_dim=int(env.observation_dim),
        act_dim=int(env.action_dim),
        action_low=np.asarray(env.action_low, np.float32),
        action_high=np.asarray(env.action_high, np.float32),
    )
