"""distributed_ddpg_tpu — a TPU-native distributed DDPG/D4PG framework.

Re-designed from scratch for TPU (JAX/XLA/pjit/pallas) with the capability
surface of camigord/Distributed_DDPG (see SURVEY.md; the reference mount was
empty, so parity is against the behavioral spec in SURVEY.md §1-§6 and
BASELINE.json):

- Actor/critic MLPs with Polyak target networks (SURVEY.md §2 #3, #4).
- TD-error critic loss + deterministic-policy-gradient actor loss
  (SURVEY.md §3.3), fused into ONE jitted learner step.
- CPU rollout workers with Ornstein-Uhlenbeck exploration and a host-side
  replay buffer (uniform + prioritized) (SURVEY.md §2 #5, #6, #7).
- The reference's async gRPC parameter-server gradient path (SURVEY.md §2 #10)
  is replaced by XLA collectives over an ICI/DCN device mesh: a single
  sharded learner step whose gradient AllReduce rides `jax.lax.psum` /
  sharding-induced collectives instead of parameter-server round trips.
- `--backend {native,jax_tpu}` gate: the pure-numpy `native` backend is the
  bit-comparability oracle and CPU baseline (BASELINE.json:5).
"""

from distributed_ddpg_tpu.config import DDPGConfig

__version__ = "0.1.0"

__all__ = ["DDPGConfig", "DDPGAgent", "__version__"]


def __getattr__(name):
    # DDPGAgent pulls in jax; load it lazily (PEP 562) so the N CPU actor
    # worker processes — which import this package for actors/policy and
    # envs only — never pay the jax import (time or RSS). See
    # actors/worker.py: 'Workers never import jax'.
    if name == "DDPGAgent":
        from distributed_ddpg_tpu.agent import DDPGAgent

        return DDPGAgent
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
