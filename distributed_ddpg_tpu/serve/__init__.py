"""Batched policy-inference service (docs/SERVING.md).

The serving subsystem that turns the repo from "training job" into
"training + serving system" (ROADMAP north star): an `InferenceServer`
owns the policy params (refreshed from the learner through the existing
pool-broadcast buffer), a dynamic `Batcher` collects client observations
and dispatches at `max_batch` OR `max_latency_ms` — whichever fires
first (TorchBeast's knobs, PAPERS.md arXiv 1910.03552) — and clients
attach in-process (`ServeClient`; tools.serve_bench) or across processes
(actor workers through `ServeFront`, behind config.serve_actors).

  - batcher.Batcher: deadline dispatch, bounded queue with typed
    `ServeOverload` backpressure, flush-on-shutdown.
  - server.InferenceServer: params + compute (numpy parity oracle / jax
    device path), transfer-scheduler `serve` class routing, `serve_*`
    observability (metrics.ServeStats).
  - client.ServeClient / client.ServeFront: the blocking local handle and
    the served-actor mp-queue front.
  - front/: the production network front (docs/SERVING.md 'Network
    front') — framed-TCP + HTTP ingress, versioned snapshots with canary
    promote, per-tenant QoS.
"""

from distributed_ddpg_tpu.serve.batcher import (
    Batcher,
    ServeClosed,
    ServeDispatchError,
    ServeOverload,
    ServeTimeout,
)
from distributed_ddpg_tpu.serve.client import ServeClient, ServeFront
from distributed_ddpg_tpu.serve.front import (
    FrontClient,
    FrontError,
    FrontServer,
    SnapshotStore,
)
from distributed_ddpg_tpu.serve.server import InferenceServer

__all__ = [
    "Batcher",
    "FrontClient",
    "FrontError",
    "FrontServer",
    "InferenceServer",
    "ServeClient",
    "ServeClosed",
    "ServeDispatchError",
    "ServeFront",
    "ServeOverload",
    "ServeTimeout",
    "SnapshotStore",
]
