"""InferenceServer: one policy, many callers (docs/SERVING.md).

The serving half of the TorchBeast topology (PAPERS.md arXiv 1910.03552):
the server owns the policy parameters, a dynamic `Batcher` collects client
observations, and each collected batch is applied in ONE policy evaluation
— the shape under which inference cost dominates at scale (the CPU-GPU
architectural-implications study, arXiv 2012.04210).

Two compute backends:

  numpy  (default) The parity oracle: each batch row is evaluated through
         the SAME NumpyPolicy `(1, obs_dim)` call the per-worker `act()`
         path runs, so served actions are BIT-IDENTICAL to local actions
         for the same params (tests/test_serve.py pins it). Row-wise
         evaluation is deliberate: batched BLAS GEMM is NOT row-wise
         bit-stable against the single-row kernel (measured ~2e-5
         divergence at 256-wide hiddens), and the bit-identity contract
         outranks CPU matmul efficiency — on CPU the batching win is in
         the dispatch/queueing machinery, not the math.
  jax    The device-serving path: params live device-resident, each batch
         is padded to the FIXED (max_batch, obs_dim) shape (one compiled
         program, no shape churn) and applied with a jitted mirror of
         models/mlp.actor_apply. Actions match the numpy oracle to float
         tolerance, not bitwise — same contract as the learner itself.

Param refresh rides the EXISTING pool-broadcast path: the server holds the
same shared-memory flat buffer + seqlock version the workers poll
(actors/pool.py `broadcast`), and re-reads it at most once per batch
dispatch — a torn snapshot is discarded exactly like a worker's
(actors/worker.py `maybe_refresh`).

Transfer integration (docs/TRANSFER.md): with a TransferScheduler
attached, every batch apply is submitted as a `serve` work item —
byte-fair against ingest/prefetch, never ahead of lockstep — so serving
and training share the host<->device bus under one accounting.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional, Tuple

import numpy as np

from distributed_ddpg_tpu.actors.policy import NumpyPolicy, layout_size
from distributed_ddpg_tpu.metrics import ServeStats
from distributed_ddpg_tpu.serve.batcher import Batcher

# One serve dispatch is bounded by the scheduler's worst-case backlog
# (lockstep beats + ingest super-blocks ahead of it), not by compute.
_SCHED_TIMEOUT_S = 60.0


class InferenceServer:
    def __init__(
        self,
        layout,
        action_scale,
        action_offset=0.0,
        *,
        max_batch: int = 32,
        max_latency_s: float = 0.005,
        max_queue: int = 1024,
        backend: str = "numpy",
        param_source: Optional[Tuple] = None,  # (shared f32 array, version)
        scheduler=None,
        stats: Optional[ServeStats] = None,
        seed: int = 0,
        fault_batcher=None,
        fault_dispatch=None,
        mesh=None,
        sac: bool = False,
        log_std_min: float = -5.0,
        log_std_max: float = 2.0,
    ):
        if backend not in ("numpy", "jax"):
            raise ValueError(f"serve backend must be 'numpy' or 'jax', got {backend!r}")
        if mesh is not None and backend != "jax":
            raise ValueError(
                "mesh= shards the jitted serve apply; the numpy backend "
                "is the single-threaded bit-parity oracle — use "
                "backend='jax' or drop the mesh"
            )
        self.backend = backend
        # Optional (data, model) mesh for the jax backend: params shard
        # over 'model' per the partition rule tables (parallel/
        # partition.py; docs/MESH.md) — the serve path of the 2D
        # composition, so a TP learner's policy serves without gathering
        # the kernels onto one device. Activations stay replicated (the
        # padded (max_batch, obs) block is tiny next to the kernels).
        self._mesh = mesh
        self.layout = layout
        self.obs_dim = int(layout[0][0][0])  # first layer w is (obs, hidden)
        self.head_dim = int(layout[-1][0][1])
        # SAC head: the final layer is [mean | log_std] (2*act_dim wide,
        # actors/policy.actor_head_dim). The server ships HEAD rows
        # ([mean | soft-clamped log_std]) out of the batch apply and
        # squashes/samples per request with `sample()` — each client's
        # exploration stream keyed by (seed, tenant, request_id), so the
        # sampling RNG lives server-side without any cross-client
        # coupling (docs/SERVING.md 'SAC serve head').
        self.sac = bool(sac)
        if self.sac and self.head_dim % 2:
            raise ValueError(
                "SAC head layout must be [mean | log_std] (even width); "
                f"got final-layer width {self.head_dim} — build the "
                "layout with actor_head_dim(act_dim, sac=True)"
            )
        self.act_dim = self.head_dim // 2 if self.sac else self.head_dim
        self.log_std_min = float(log_std_min)
        self.log_std_max = float(log_std_max)
        self._sample_seed = int(seed)
        self._policy = NumpyPolicy(layout, action_scale, action_offset)
        self._param_lock = threading.Lock()
        self._param_source = param_source
        self._seen_version = -1
        self._scratch = np.empty(layout_size(layout), np.float32)
        self.scheduler = scheduler
        self.stats = stats or ServeStats(seed=seed, max_batch=max_batch)
        self._jax_apply = None
        self._jax_params = None
        if backend == "jax":
            self._build_jax_apply()
        self.batcher = Batcher(
            self._apply_batch,
            max_batch=max_batch,
            max_latency_s=max_latency_s,
            max_queue=max_queue,
            stats=self.stats,
            fault_batcher=fault_batcher,
            fault_dispatch=fault_dispatch,
        )

    # --- lifecycle ---

    def start(self) -> "InferenceServer":
        self.batcher.start()
        return self

    def overloaded(self, frac: float = 0.9) -> bool:
        """Live degraded-condition probe for the telemetry plane
        (obs/health.py `register_probe`): True while the bounded request
        queue sits past `frac` of capacity — the point where new
        requests are about to shed (Batcher's typed backpressure) and a
        canary gate must stop shifting traffic toward this process.
        Evaluated on the /healthz scrape thread, so it reads the queue
        as it is NOW, not at the last log cadence (docs/SERVING.md)."""
        return self.batcher.depth() >= frac * self.batcher.max_queue

    def close(self, timeout: float = 30.0) -> None:
        """Flush-on-shutdown: the batcher drains every accepted request
        before its thread exits (serve/batcher.py contract)."""
        self.batcher.close(timeout=timeout)

    def client(self, timeout_s: float = 1.0):
        from distributed_ddpg_tpu.serve.client import ServeClient

        return ServeClient(self, timeout_s=timeout_s)

    # --- params ---

    def refresh(self, flat: np.ndarray) -> None:
        """Install params directly from a flat f32 vector (serve_bench,
        tests; the pool path goes through _maybe_refresh instead)."""
        with self._param_lock:
            self._policy.load_flat(np.asarray(flat, np.float32))
            if self.backend == "jax":
                self._ship_jax_params()
        self.stats.record_refresh()

    def _maybe_refresh(self) -> None:
        """Seqlock read of the pool's broadcast buffer
        (policy.seqlock_snapshot — the same discard discipline the worker
        mirror uses). At most one check per batch dispatch — an int
        compare when nothing changed."""
        if self._param_source is None:
            return
        from distributed_ddpg_tpu.actors.policy import seqlock_snapshot

        shared, version = self._param_source
        v = seqlock_snapshot(shared, version, self._scratch,
                             self._seen_version)
        if v is not None:
            with self._param_lock:
                self._policy.load_flat(self._scratch)
                if self.backend == "jax":
                    self._ship_jax_params()
            self._seen_version = v
            self.stats.record_refresh()

    # --- compute ---

    def _apply_batch(self, obs: np.ndarray) -> np.ndarray:
        """The Batcher's apply_fn: refresh params, then run the batch —
        through the transfer scheduler's `serve` class when attached (the
        obs h2d + apply + action d2h accounted like any other bus user),
        inline otherwise."""
        self._maybe_refresh()
        out_dim = self.head_dim if self.sac else self.act_dim
        nbytes = obs.nbytes + obs.shape[0] * out_dim * 4
        if self.scheduler is not None:
            return self.scheduler.submit(
                "serve",
                lambda: self._compute(obs),
                nbytes=nbytes,
                label=f"serve_batch_{obs.shape[0]}",
            ).result(timeout=_SCHED_TIMEOUT_S)
        return self._compute(obs)

    def _compute(self, obs: np.ndarray) -> np.ndarray:
        with self._param_lock:
            if self.backend == "jax":
                return self._compute_jax(obs)
            # Row-wise (1, obs_dim) evaluation — the bit-identity parity
            # contract with the per-worker act() path (module docstring).
            if self.sac:
                return np.concatenate(
                    [self._head_row(row) for row in obs], axis=0
                )
            return np.concatenate([self._policy(row) for row in obs], axis=0)

    def _head_row(self, row: np.ndarray) -> np.ndarray:
        """SAC batch output: [mean | log_std] with the SAME soft clamp as
        the jax head (models/mlp.actor_gaussian_apply), so the two
        backends agree on the distribution `sample()` draws from."""
        raw = self._policy.head(row)
        mean, log_std_raw = np.split(raw, 2, axis=-1)
        log_std = self.log_std_min + 0.5 * (
            self.log_std_max - self.log_std_min
        ) * (np.tanh(log_std_raw) + 1.0)
        return np.concatenate([mean, log_std], axis=-1).astype(
            np.float32, copy=False
        )

    def sample(self, head, tenant: str, request_id: int,
               explore: bool = True) -> np.ndarray:
        """Turn one SAC head row [mean | log_std] into an action row.
        The exploration key is derived from (seed, tenant, request_id) —
        stable across processes and replayable, so the SAME request
        always samples the SAME action (the parity contract
        tests/test_serve_front.py pins) and no two clients ever share an
        RNG stream. explore=False returns the deterministic tanh(mean)
        squash (eval traffic)."""
        if not self.sac:
            # lint: ok(typed-error): caller bug (sampling a deterministic
            # head), not a runtime failure any recovery path handles
            raise RuntimeError("sample() is the SAC serve head's API")
        head = np.asarray(head, np.float32).reshape(-1)
        mean, log_std = head[: self.act_dim], head[self.act_dim:]
        if explore:
            digest = hashlib.sha256(
                f"{self._sample_seed}:{tenant}:{request_id}".encode()
            ).digest()
            rng = np.random.default_rng(
                int.from_bytes(digest[:8], "little")
            )
            eps = rng.standard_normal(mean.shape).astype(np.float32)
            u = mean + np.exp(log_std) * eps
        else:
            u = mean
        return (
            np.tanh(u) * self._policy.scale + self._policy.offset
        ).astype(np.float32)

    def _build_jax_apply(self) -> None:
        # THE learner's actor head (models/mlp.actor_apply), not a local
        # mirror: the serve jax backend must track any future change to
        # the head (activation, mixed-precision handling) automatically.
        import functools

        import jax

        from distributed_ddpg_tpu.models.mlp import (
            actor_apply,
            actor_gaussian_apply,
        )

        if self.sac:
            # Head rows out, same [mean | log_std] contract as the numpy
            # path; sampling stays host-side in sample() (per-client
            # keys are a host concern, not a device one).
            import jax.numpy as jnp

            def apply(params, obs):
                mean, log_std = actor_gaussian_apply(
                    params, obs, self.log_std_min, self.log_std_max
                )
                return jnp.concatenate([mean, log_std], axis=-1)
        else:
            apply = functools.partial(
                actor_apply,
                action_scale=self._policy.scale,
                action_offset=self._policy.offset,
            )
        if self._mesh is None:
            self._jax_apply = jax.jit(apply)
        else:
            # TP-sharded apply (docs/MESH.md): params carry their rule-
            # table shardings (shipped below); actions come back
            # replicated so the d2h slice is placement-oblivious.
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._jax_apply = jax.jit(
                apply, out_shardings=NamedSharding(self._mesh, P())
            )
        self._ship_jax_params()

    def _ship_jax_params(self) -> None:
        import jax
        import jax.numpy as jnp

        params = tuple(
            {"w": jnp.asarray(l["w"]), "b": jnp.asarray(l["b"])}
            for l in self._policy.layers
        )
        if self._mesh is None:
            self._jax_params = jax.device_put(params)
            return
        # Same rule table as the learner (parallel/partition.py), so the
        # served mu(s) shards exactly like the training-time actor.
        from distributed_ddpg_tpu.parallel import mesh as mesh_lib

        specs = mesh_lib.net_pspec(params, self._mesh.shape["model"])
        self._jax_params = jax.device_put(
            params, mesh_lib.to_named(self._mesh, specs)
        )

    def _compute_jax(self, obs: np.ndarray) -> np.ndarray:
        n = obs.shape[0]
        if n < self.batcher.max_batch:
            # Pad to the ONE compiled shape; padded rows compute garbage
            # that is sliced away below.
            padded = np.zeros((self.batcher.max_batch, self.obs_dim), np.float32)
            padded[:n] = obs
            obs = padded
        return np.asarray(self._jax_apply(self._jax_params, obs))[:n]

    # --- observability ---

    def snapshot(self) -> dict:
        """The serve_* family (metrics.ServeStats) with the live queue
        depth riding in as a gauge."""
        return self.stats.snapshot(queue_depth=self.batcher.depth())


# ---------------------------------------------------------------------------
# program-contract analyzer hook (analysis/programs.py; docs/ANALYSIS.md
# "Layer 2")
# ---------------------------------------------------------------------------


def program_specs():
    """The jax-backend serve apply: one fixed-shape jitted mu(s) over the
    padded (max_batch, obs_dim) batch. No donation (params are shared
    across dispatches); the checks that matter here are the callback leak
    (a debug print in the serve path would ride inside every request
    deadline) and the empty collective fingerprint (serving must never
    stage a collective — it runs outside the pod's lockstep beats)."""
    from distributed_ddpg_tpu.analysis.programs import (
        BuiltProgram,
        ProgramSpec,
    )

    def build(tp: bool = False):
        def _build():
            from distributed_ddpg_tpu.actors.policy import param_layout

            layout = param_layout(3, 1, (16, 16))
            mesh = None
            if tp:
                from distributed_ddpg_tpu.analysis.programs import probe_mesh

                mesh = probe_mesh(2)
            server = InferenceServer(
                layout, np.ones(1, np.float32), backend="jax", max_batch=8,
                mesh=mesh,
            )
            obs = np.zeros((8, 3), np.float32)
            return BuiltProgram(server._jax_apply, (server._jax_params, obs))
        return _build

    return [
        ProgramSpec("serve.apply.jax", "serve/server.py", build()),
        # TP-sharded apply (docs/MESH.md): still collective-free at the
        # jaxpr level — the partitioner's kernel-shard exchange follows
        # the lowering deterministically, and serving must never stage an
        # EXPLICIT collective (it runs outside the pod's lockstep beats).
        ProgramSpec("serve.apply.jax.tp", "serve/server.py", build(tp=True)),
    ]
