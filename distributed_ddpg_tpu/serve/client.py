"""Serve clients: the in-process blocking handle and the served-actor
front (docs/SERVING.md).

`ServeClient` is the local RPC surface: `act(obs)` blocks until the
server's batcher delivers this request's action row (or raises typed —
ServeOverload / ServeClosed when the request was shed, ServeDispatchError
when its batch failed, ServeTimeout when the client's own deadline
passed). `tools.serve_bench` drives load through it without Gym.

`ServeFront` bridges the actor POOL's multiprocessing transport onto the
in-process batcher: worker processes put `(worker_id, request_id, obs)`
on one shared bounded request queue (actors/pool.py builds it when
config.serve_actors), the front drains it into `Batcher.submit`, and each
completion callback pushes `(request_id, action | None)` onto that
worker's private response queue. `None` tells the worker "the service
could not serve this request" — it degrades to its local act() path
(actors/worker.py `served_mu`), which is the whole failure contract:
a stalled or crashed serving stack costs latency, never a deadlock.
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import Optional

import numpy as np

from distributed_ddpg_tpu.serve.batcher import (
    ServeClosed,
    ServeOverload,
    ServeTimeout,
)


class ServeClient:
    """Blocking in-process handle over one InferenceServer."""

    def __init__(self, server, timeout_s: float = 1.0,
                 tenant: str = "local"):
        self._server = server
        self.timeout_s = float(timeout_s)
        self.tenant = tenant
        self._rid = 0

    def act(self, obs, timeout_s: Optional[float] = None) -> np.ndarray:
        """One observation row in, one action row out. Raises typed on
        shed/failed/late requests (module docstring)."""
        done = threading.Event()
        box: list = []

        def _cb(result):
            box.append(result)
            done.set()

        self._server.batcher.submit(np.asarray(obs, np.float32), _cb)
        if not done.wait(self.timeout_s if timeout_s is None else timeout_s):
            raise ServeTimeout(
                f"no response within {timeout_s or self.timeout_s}s"
            )
        result = box[0]
        if isinstance(result, BaseException):
            raise result
        if getattr(self._server, "sac", False):
            # SAC serve head: the batch apply returns [mean | log_std];
            # sample server-side with this client's per-request key
            # (serve/server.py `sample`).
            self._rid += 1
            return self._server.sample(
                result, tenant=self.tenant, request_id=self._rid
            )
        return result


class ServeFront:
    """Drain thread: pool request queue -> batcher -> per-worker response
    queues. Lives in the learner process next to the InferenceServer."""

    def __init__(self, server, request_queue, response_queues):
        self._server = server
        self._req = request_queue
        self._resp = response_queues
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ServeFront":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-front"
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)

    def _respond(self, wid: int, rid: int, action) -> None:
        """Best-effort response delivery: a full response queue means the
        worker already abandoned this request (it bounds its own wait and
        falls back locally) — dropping the reply is the correct move."""
        try:
            self._resp[wid].put_nowait((rid, action))
        except (queue_mod.Full, ValueError, OSError):
            pass

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                wid, rid, obs = self._req.get(timeout=0.05)
            except queue_mod.Empty:
                continue
            except (OSError, ValueError, EOFError):
                return  # transport torn down under us: pool is stopping

            def _cb(result, wid=wid, rid=rid):
                if isinstance(result, BaseException):
                    self._respond(wid, rid, None)
                    return
                if getattr(self._server, "sac", False):
                    # SAC serve head: sample with this worker's key
                    # (tenant = worker id, request_id = its own rid
                    # counter) so every worker gets its own replayable
                    # exploration stream — the per-client RNG that used
                    # to forbid sac + serve_actors now lives here.
                    result = self._server.sample(
                        result, tenant=str(wid), request_id=rid
                    )
                self._respond(wid, rid, result)

            try:
                self._server.batcher.submit(np.asarray(obs, np.float32), _cb)
            except (ServeOverload, ServeClosed):
                self._respond(wid, rid, None)
