"""Production serving front (docs/SERVING.md 'Network front'): network
ingress + versioned policy snapshots with canary promote + per-tenant
QoS, layered over the serve subsystem's Batcher/InferenceServer.

  - wire.py       length-prefixed JSON frames, the typed error contract
  - qos.py        tenant table, token buckets, priority-ordered shedding
  - snapshots.py  immutable named versions, atomic promote, canary gate
  - ingress.py    FrontServer: TCP frame server + HTTP adapter + routing
  - client.py     FrontClient: the socket client serve_bench/tests use
"""

from distributed_ddpg_tpu.serve.front.client import FrontClient, FrontError
from distributed_ddpg_tpu.serve.front.ingress import FrontServer
from distributed_ddpg_tpu.serve.front.qos import (
    QosGate,
    TenantPolicy,
    TokenBucket,
    parse_tenants,
)
from distributed_ddpg_tpu.serve.front.snapshots import (
    CanaryGate,
    SnapshotStore,
)
from distributed_ddpg_tpu.serve.front.wire import (
    ERROR_CODES,
    MAX_FRAME,
    WireError,
)

__all__ = [
    "CanaryGate",
    "ERROR_CODES",
    "FrontClient",
    "FrontError",
    "FrontServer",
    "MAX_FRAME",
    "QosGate",
    "SnapshotStore",
    "TenantPolicy",
    "TokenBucket",
    "WireError",
    "parse_tenants",
]
