"""Per-tenant QoS for the network front (docs/SERVING.md 'Network
front'): token-bucket rate caps plus priority-ordered overload shedding
on the batcher's bounded queue.

Two independent shed causes, counted separately (metrics.TenantStats):

  rate      the tenant's own token bucket is empty — a per-tenant cap
            that fires regardless of load, so one chatty tenant cannot
            crowd out the rest even when the queue is shallow.
  priority  the queue is deep enough that this tenant's PRIORITY CLASS
            sheds: class thresholds are staggered so the lowest class
            sheds first and priority 0 never depth-sheds at all (it only
            ever sees the batcher's own typed overload at a full
            queue). This is the "overload sheds lowest-priority tenants
            first" contract tests/test_serve_front.py pins.

Tenant table grammar (config.front_tenants):

    name:priority[:rate[:burst]];name:priority...

priority 0 is highest; rate is tokens/second (0 = uncapped); burst is
the bucket depth (default max(1, rate)). Unknown tenants get
`default_priority` and no rate cap.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, NamedTuple, Optional


class TenantPolicy(NamedTuple):
    name: str
    priority: int
    rate: float  # tokens/s; 0 = uncapped
    burst: float


def parse_tenants(spec: str) -> Dict[str, TenantPolicy]:
    """Parse the tenant table; raises ValueError with the offending entry
    (config validation calls this at parse time — fail fast, not at the
    first shed)."""
    table: Dict[str, TenantPolicy] = {}
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        parts = entry.split(":")
        if not 2 <= len(parts) <= 4 or not parts[0]:
            raise ValueError(
                f"front_tenants entry {entry!r}: expected "
                "name:priority[:rate[:burst]]"
            )
        name = parts[0]
        if name in table:
            raise ValueError(f"front_tenants: duplicate tenant {name!r}")
        try:
            priority = int(parts[1])
            rate = float(parts[2]) if len(parts) > 2 else 0.0
            burst = float(parts[3]) if len(parts) > 3 else max(1.0, rate)
        except ValueError:
            raise ValueError(
                f"front_tenants entry {entry!r}: non-numeric field"
            )
        if priority < 0:
            raise ValueError(
                f"front_tenants entry {entry!r}: priority must be >= 0"
            )
        if rate < 0:
            raise ValueError(
                f"front_tenants entry {entry!r}: rate must be >= 0"
            )
        if burst < 1:
            raise ValueError(
                f"front_tenants entry {entry!r}: burst must be >= 1"
            )
        table[name] = TenantPolicy(name, priority, rate, burst)
    return table


class TokenBucket:
    """Classic token bucket; `now` is injectable so tests drive it with a
    fake clock instead of sleeping."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._last: Optional[float] = None

    def allow(self, now: float) -> bool:
        if self._last is not None:
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class QosGate:
    """Admission control in front of one version's batcher.

    `admit(tenant, depth)` returns None to admit, or the shed cause
    ('rate' | 'priority'). Depth thresholds per priority class p, with
    P = the highest priority in play and s = shed_start:

        p == 0:  1.0            (never depth-shed; the full queue's own
                                 typed overload is the only backpressure)
        p >= 1:  s + (1-s) * (P-p) / P

    Strictly decreasing in p, so as the queue fills the classes shed in
    exact priority order: the lowest class crosses its threshold first
    (at s), the next class only at a strictly deeper queue, and so on.
    """

    def __init__(
        self,
        tenants: Dict[str, TenantPolicy],
        default_priority: int = 1,
        shed_start: float = 0.5,
        clock=time.monotonic,
    ):
        self._tenants = dict(tenants)
        self._default_priority = max(0, int(default_priority))
        self._shed_start = float(shed_start)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._max_priority = max(
            [p.priority for p in self._tenants.values()]
            + [self._default_priority, 1]
        )

    def priority(self, tenant: str) -> int:
        pol = self._tenants.get(tenant)
        return pol.priority if pol is not None else self._default_priority

    def threshold(self, priority: int) -> float:
        if priority <= 0:
            return 1.0
        p = min(priority, self._max_priority)
        s = self._shed_start
        return s + (1.0 - s) * (self._max_priority - p) / self._max_priority

    def admit(self, tenant: str, depth: int, max_queue: int):
        """None = admitted; 'rate' / 'priority' = shed cause."""
        pol = self._tenants.get(tenant)
        if pol is not None and pol.rate > 0:
            with self._lock:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = TokenBucket(pol.rate, pol.burst)
                    self._buckets[tenant] = bucket
                if not bucket.allow(self._clock()):
                    return "rate"
        if max_queue > 0 and depth / max_queue >= self.threshold(
            self.priority(tenant)
        ):
            return "priority"
        return None
