"""Versioned policy snapshots with canary promote (docs/SERVING.md
'Network front').

`SnapshotStore` holds immutable named flat param vectors. Exactly one
version is STABLE (serves by default); at most one is the CANDIDATE,
serving `fraction` of traffic through a deterministic canary split —
crc32("tenant:request_id") bucketing, so the same request replays to the
same version and the split is auditable, not random.

`CanaryGate` is the ci_gate pattern applied to live traffic: the
candidate promotes only after BOTH arms have `min_requests` latency
samples (arm-on-first-capture: never promote on thin data) and its p95
is within `threshold` relative regression of stable's — and it
auto-rolls-back the moment either the latency gate or the error-rate
gate trips, without waiting for the sample quota. Rollback is instant
and atomic: the candidate is dropped, routing reverts to 100% stable.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from distributed_ddpg_tpu.metrics import PhaseTimers, _Reservoir
from distributed_ddpg_tpu.serve.batcher import ServeClosed

_BUCKETS = 10_000


class SnapshotStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._versions: Dict[str, np.ndarray] = {}
        self._stable: Optional[str] = None
        self._candidate: Optional[str] = None
        self._fraction = 0.0

    # --- publishing / lifecycle ---

    def publish(self, name: str, flat: np.ndarray) -> None:
        """Register an immutable named snapshot (read-only copy — a later
        in-place learner update must not mutate a served version). The
        FIRST published version becomes stable (there is nothing to
        canary against)."""
        if not name:
            raise ValueError("snapshot name must be non-empty")
        frozen = np.array(flat, np.float32, copy=True)
        frozen.setflags(write=False)
        with self._lock:
            if name in self._versions:
                raise ValueError(
                    f"snapshot {name!r} already published (versions are "
                    "immutable — publish under a new name)"
                )
            self._versions[name] = frozen
            if self._stable is None:
                self._stable = name

    def get(self, name: str) -> np.ndarray:
        with self._lock:
            try:
                return self._versions[name]
            except KeyError:
                raise KeyError(f"unknown snapshot {name!r}")

    def names(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(self._versions)

    @property
    def stable(self) -> Optional[str]:
        with self._lock:
            return self._stable

    @property
    def candidate(self) -> Optional[str]:
        with self._lock:
            return self._candidate

    def start_canary(self, name: str, fraction: float) -> None:
        if not 0.0 < fraction < 1.0:
            raise ValueError("canary fraction must be in (0, 1)")
        with self._lock:
            if name not in self._versions:
                raise KeyError(f"unknown snapshot {name!r}")
            if name == self._stable:
                raise ValueError(f"{name!r} is already stable")
            if self._candidate is not None:
                raise ValueError(
                    f"a canary is already running ({self._candidate!r}); "
                    "promote or roll it back first"
                )
            self._candidate = name
            self._fraction = float(fraction)

    def promote(self, name: Optional[str] = None) -> str:
        """Atomically make `name` (default: the current candidate) the
        stable version and clear the canary split."""
        with self._lock:
            target = name if name is not None else self._candidate
            if target is None:
                raise ValueError("no candidate to promote")
            if target not in self._versions:
                raise KeyError(f"unknown snapshot {target!r}")
            self._stable = target
            self._candidate = None
            self._fraction = 0.0
            return target

    def rollback(self) -> Optional[str]:
        """Drop the candidate, reverting to 100% stable. Returns the
        dropped name (None when no canary was running — idempotent)."""
        with self._lock:
            dropped = self._candidate
            self._candidate = None
            self._fraction = 0.0
            return dropped

    # --- routing ---

    def route(self, tenant: str, request_id: int) -> Tuple[str, bool]:
        """(version_name, is_canary) for one request. Deterministic:
        crc32 of "tenant:request_id" into 10k buckets, candidate gets the
        first fraction*10k of them."""
        with self._lock:
            stable, candidate, fraction = (
                self._stable, self._candidate, self._fraction,
            )
        if stable is None:
            # Typed: the service cannot serve yet — the ingress answers
            # this as a `closed` wire error, same as during shutdown.
            raise ServeClosed("no snapshot published yet")
        if candidate is None:
            return stable, False
        bucket = zlib.crc32(f"{tenant}:{request_id}".encode()) % _BUCKETS
        if bucket < int(fraction * _BUCKETS):
            return candidate, True
        return stable, False


class CanaryGate:
    """Live stable-vs-candidate comparison. record() feeds one served
    request's arm/latency/error; verdict() is evaluated after each canary
    request (serve/front/ingress.py):

      'rollback'  candidate p95 regressed past `threshold` relative to
                  stable (both arms populated >= min_requests), OR the
                  candidate's error RATE exceeds stable's by more than
                  5 percentage points with >= min_requests candidate
                  observations — errors don't wait for the latency quota.
      'promote'   both arms have >= min_requests latency samples and
                  neither gate trips.
      None        not enough data yet: keep splitting traffic.
    """

    # Error-rate regression allowance (absolute). Tighter than the
    # latency gate on purpose: a version that ERRORS is broken, not slow.
    ERROR_RATE_SLACK = 0.05

    def __init__(self, min_requests: int, threshold: float, seed: int = 0):
        self.min_requests = max(1, int(min_requests))
        self.threshold = float(threshold)
        self._lock = threading.Lock()
        self._seed = int(seed)
        self._reset()

    def _reset(self) -> None:
        def res(name: str) -> _Reservoir:
            return _Reservoir(
                PhaseTimers.RESERVOIR_K,
                (zlib.crc32(name.encode()) ^ self._seed) & 0x7FFFFFFF,
            )

        self._lat = {False: res("canary_stable"), True: res("canary_cand")}
        self._seen = {False: 0, True: 0}
        self._errors = {False: 0, True: 0}

    def reset(self) -> None:
        """New canary round: forget the previous candidate's samples."""
        with self._lock:
            self._reset()

    def record(self, is_canary: bool, latency_s: float,
               error: bool = False) -> None:
        with self._lock:
            self._seen[is_canary] += 1
            if error:
                self._errors[is_canary] += 1
            else:
                self._lat[is_canary].add(float(latency_s))

    def stats(self) -> dict:
        with self._lock:
            return {
                "stable_n": self._lat[False].n,
                "candidate_n": self._lat[True].n,
                "stable_p95_ms": round(
                    1000.0 * self._lat[False].percentile(0.95), 3
                ),
                "candidate_p95_ms": round(
                    1000.0 * self._lat[True].percentile(0.95), 3
                ),
                "stable_errors": self._errors[False],
                "candidate_errors": self._errors[True],
            }

    def verdict(self) -> Optional[str]:
        with self._lock:
            cand_seen = self._seen[True]
            if cand_seen >= self.min_requests:
                stable_rate = (
                    self._errors[False] / self._seen[False]
                    if self._seen[False]
                    else 0.0
                )
                cand_rate = self._errors[True] / cand_seen
                if cand_rate > stable_rate + self.ERROR_RATE_SLACK:
                    return "rollback"
            if (
                self._lat[False].n < self.min_requests
                or self._lat[True].n < self.min_requests
            ):
                return None
            stable_p95 = self._lat[False].percentile(0.95)
            cand_p95 = self._lat[True].percentile(0.95)
            if stable_p95 > 0 and (
                (cand_p95 - stable_p95) / stable_p95 > self.threshold
            ):
                return "rollback"
            return "promote"
