"""Socket client for the network front (docs/SERVING.md 'Network
front'): the closed-loop load generator's transport
(tools.serve_bench --transport socket) and the test harness's.

`FrontClient.act` is the one-call surface: frame the request, block on
the response, return the action row — or raise `FrontError` carrying the
typed wire code, so a caller degrades on `shed`/`overload` exactly like
ServeClient degrades on ServeOverload."""

from __future__ import annotations

import socket
from typing import Optional, Tuple

import numpy as np

from distributed_ddpg_tpu.serve.front import wire


class FrontError(RuntimeError):
    """A typed error response from the front; `code` is one of
    wire.ERROR_CODES."""

    def __init__(self, code: str, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code


class FrontClient:
    """One persistent framed-TCP connection; NOT thread-safe (one client
    per load thread — requests on a connection are strictly serial)."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 tenant: str = "default", timeout_s: float = 5.0):
        self.tenant = tenant
        self._rid = 0
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "FrontClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def request(self, obj: dict) -> dict:
        """Raw frame round-trip (tests drive malformed objects through
        this). ConnectionError when the server tore the stream down."""
        wire.send_frame(self._sock, obj)
        resp = wire.read_frame(self._sock)
        if resp is None:
            raise ConnectionError("front closed the connection")
        return resp

    def act(
        self,
        obs,
        request_id: Optional[int] = None,
        version: Optional[str] = None,
    ) -> Tuple[np.ndarray, str]:
        """One observation -> (action row, serving version name). Raises
        FrontError with the typed code on any error response."""
        if request_id is None:
            self._rid += 1
            request_id = self._rid
        req = {
            "tenant": self.tenant,
            "request_id": request_id,
            "obs": np.asarray(obs, np.float32).reshape(-1).tolist(),
        }
        if version is not None:
            req["version"] = version
        resp = self.request(req)
        if "error" in resp:
            raise FrontError(resp["error"], resp.get("message", ""))
        return (
            np.asarray(resp["action"], np.float32),
            resp.get("version", ""),
        )
