"""Wire protocol for the network serving front (docs/SERVING.md
'Network front').

Frames are 4-byte big-endian length prefixes followed by a UTF-8 JSON
body — the simplest framing that survives partial reads, needs no
dependency, and keeps the HTTP adapter's body format identical to the
socket path's (one JSON object either way).

Request object:

    {"tenant": "<id>", "request_id": <int>, "obs": [<floats>],
     "version": "<name>"?}          # version pins a specific snapshot;
                                    # omitted = canary-split routing

Response object — exactly one of:

    {"request_id": <int>, "action": [<floats>], "version": "<name>"}
    {"request_id": <int>, "error": "<code>", "message": "<text>"}

Error codes (`ERROR_CODES`) are the TYPED failure contract: a client can
switch on the code, and none of them ever kills the acceptor —

    bad_frame  undecodable/oversized frame or malformed request object
    shed       rejected by per-tenant QoS (rate cap or priority shed)
    overload   the target version's bounded batcher queue is full
    timeout    the request aged past front_timeout_s before its batch
               completed
    dispatch   the batch apply failed (ServeDispatchError on the wire)
    closed     the front (or its engine) is shutting down

An undecodable LENGTH PREFIX is unrecoverable (the stream has lost
framing): the server answers one bad_frame error and closes THAT
connection — the acceptor and every other connection survive. JSON-level
garbage inside a well-framed body is recoverable: typed bad_frame
response, connection stays open.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional

ERROR_CODES = (
    "bad_frame", "shed", "overload", "timeout", "dispatch", "closed",
)

# One frame bounds one observation row plus envelope; 1 MiB is orders of
# magnitude past any proprioceptive obs and small enough that a garbage
# length prefix can't make the server allocate unbounded memory.
MAX_FRAME = 1 << 20

_LEN = struct.Struct(">I")


class WireError(RuntimeError):
    """A typed wire-level failure; `code` is one of ERROR_CODES."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        if code not in ERROR_CODES:
            raise ValueError(f"unknown wire error code {code!r}")
        self.code = code


def encode_frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise WireError("bad_frame", f"frame body {len(body)}B > {MAX_FRAME}B")
    return _LEN.pack(len(body)) + body


def recv_exactly(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes or return None on clean EOF before any byte.
    EOF MID-object raises (torn frame — the peer died mid-write)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            if got == 0:
                return None
            raise WireError(
                "bad_frame", f"connection closed mid-frame ({got}/{n}B)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock: socket.socket) -> Optional[dict]:
    """One framed JSON object off the socket; None on clean EOF.
    Raises WireError('bad_frame', ...) on oversized length or invalid
    JSON — the CALLER decides whether that tears the connection (a bad
    length prefix does; a bad body does not)."""
    header = recv_exactly(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise WireError(
            "bad_frame",
            f"frame length {length}B > {MAX_FRAME}B (lost framing)",
        )
    body = recv_exactly(sock, length)
    if body is None:
        raise WireError("bad_frame", "connection closed before frame body")
    try:
        obj = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError("bad_frame", f"invalid JSON body: {e!r}")
    if not isinstance(obj, dict):
        raise WireError("bad_frame", "frame body must be a JSON object")
    return obj


def send_frame(sock: socket.socket, obj: dict) -> None:
    sock.sendall(encode_frame(obj))


def validate_request(obj: dict) -> dict:
    """Normalize + type-check a request object; raises
    WireError('bad_frame') with a field-specific message otherwise."""
    tenant = obj.get("tenant", "")
    if not isinstance(tenant, str) or not tenant:
        raise WireError("bad_frame", "request needs a non-empty 'tenant'")
    rid = obj.get("request_id")
    if not isinstance(rid, int) or isinstance(rid, bool):
        raise WireError("bad_frame", "request needs an int 'request_id'")
    obs = obj.get("obs")
    if not isinstance(obs, list) or not obs or not all(
        isinstance(x, (int, float)) and not isinstance(x, bool) for x in obs
    ):
        raise WireError(
            "bad_frame", "request needs 'obs': a non-empty number list"
        )
    version = obj.get("version")
    if version is not None and not isinstance(version, str):
        raise WireError("bad_frame", "'version' must be a string when given")
    return {"tenant": tenant, "request_id": rid, "obs": obs,
            "version": version}


def error_response(rid, code: str, message: str) -> dict:
    if code not in ERROR_CODES:
        raise ValueError(f"unknown wire error code {code!r}")
    return {"request_id": rid, "error": code, "message": message}
