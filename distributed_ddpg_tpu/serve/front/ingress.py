"""Network ingress for the serving front (docs/SERVING.md 'Network
front').

`FrontServer` binds two listeners onto one request path:

  - a length-prefixed-frame TCP server (serve/front/wire.py; stdlib
    socketserver, thread-per-connection — the obs/ daemon-thread
    pattern), and
  - an HTTP/JSON adapter (POST /act) carrying the SAME body objects, so
    curl and load balancers speak to the front without a custom client.

Every accepted request flows: validate -> version route (SnapshotStore
canary split) -> per-tenant QoS admit (QosGate) -> that version's
Batcher -> typed response. Each ACTIVE version (stable + candidate) gets
its own engine — a full InferenceServer with its own Batcher — created
lazily on first route and closed when the version retires, so a canary's
latency is measured against an isolated queue, not polluted by stable's.

The failure contract (wire.py ERROR_CODES) is absolute: overload,
timeout, bad frames, QoS sheds, dispatch failures, and injected chaos
all surface as typed error RESPONSES; none of them may kill the acceptor
or another connection. The only per-connection teardown is a lost frame
boundary (garbage length prefix), and even that answers one bad_frame
first.

Canary verdicts run inline: after every request routed while a candidate
is active, the CanaryGate is consulted — 'rollback' drops the candidate
instantly (front_rollbacks), 'promote' atomically makes it stable
(front_promotes). Chaos: `front:accept:{stall,slow,hang}@K` ticks per
accepted TCP connection, `front:frame:corrupt@K` per decoded frame
(typed bad_frame, connection survives), and `front:canary:regress@K~S`
adds S seconds to every candidate-routed request from its K-th onward —
sustained, because the gate trips on a p95, not an outlier.
"""

from __future__ import annotations

import json
import socketserver
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

import numpy as np

from distributed_ddpg_tpu.faults import InjectedFault
from distributed_ddpg_tpu.metrics import FrontStats, TenantStats
from distributed_ddpg_tpu.serve.batcher import (
    ServeClosed,
    ServeOverload,
)
from distributed_ddpg_tpu.serve.front import wire
from distributed_ddpg_tpu.serve.front.qos import QosGate, parse_tenants
from distributed_ddpg_tpu.serve.front.snapshots import CanaryGate, SnapshotStore

_STOP_JOIN_TIMEOUT_S = 5.0
_ENGINE_CLOSE_TIMEOUT_S = 5.0

# HTTP status per typed wire error code — the body always carries the
# same JSON error object the socket path sends, the status is advisory.
_HTTP_STATUS = {
    "bad_frame": 400,
    "shed": 429,
    "overload": 429,
    "timeout": 504,
    "dispatch": 500,
    "closed": 503,
}


class FrontServer:
    """The production serving front: TCP frames + HTTP JSON in, typed
    responses out, versioned engines behind a QoS gate."""

    def __init__(
        self,
        make_engine: Callable,
        *,
        port: int = 0,
        http_port: Optional[int] = 0,
        timeout_s: float = 2.0,
        canary_fraction: float = 0.1,
        canary_min_requests: int = 50,
        canary_threshold: float = 0.5,
        tenants="",
        default_priority: int = 1,
        shed_start: float = 0.5,
        stats: Optional[FrontStats] = None,
        tenant_stats: Optional[TenantStats] = None,
        seed: int = 0,
        fault_accept=None,
        fault_frame=None,
        canary_regressions=(),
    ):
        """`make_engine()` returns a fresh, UNSTARTED InferenceServer
        (serve/server.py) — one is built per active version and fed that
        version's flat params via refresh(). port/http_port: 0 = bind an
        ephemeral port (read .port/.http_port after start()), None for
        http_port = no HTTP adapter."""
        self._make_engine = make_engine
        self._req_port = int(port)
        self._req_http_port = http_port if http_port is None else int(http_port)
        self.timeout_s = float(timeout_s)
        self.canary_fraction = float(canary_fraction)
        self.stats = stats or FrontStats(seed=seed)
        self.tenant_stats = tenant_stats or TenantStats()
        table = parse_tenants(tenants) if isinstance(tenants, str) else tenants
        self.qos = QosGate(
            table, default_priority=default_priority, shed_start=shed_start
        )
        self.store = SnapshotStore()
        self.gate = CanaryGate(
            canary_min_requests, canary_threshold, seed=seed
        )
        self._fault_accept = fault_accept
        self._fault_frame = fault_frame
        self._canary_regs = tuple(canary_regressions)
        self._cand_ordinal = 0
        self._lock = threading.Lock()  # engines + verdict application
        self._engines: Dict[str, object] = {}
        self._tcp = None
        self._http = None
        self._threads = []
        self.port = 0
        self.http_port = 0

    # --- version lifecycle ---

    def publish(self, name: str, flat: np.ndarray) -> None:
        self.store.publish(name, flat)

    def start_canary(self, name: str, fraction: Optional[float] = None) -> None:
        self.gate.reset()
        with self._lock:
            self._cand_ordinal = 0
        self.store.start_canary(
            name, self.canary_fraction if fraction is None else fraction
        )

    def promote(self, name: Optional[str] = None) -> str:
        with self._lock:
            old_stable = self.store.stable
            promoted = self.store.promote(name)
            self.stats.record_promote()
            self.gate.reset()
            retired = (
                self._engines.pop(old_stable, None)
                if old_stable not in (None, promoted)
                else None
            )
        if retired is not None:
            retired.close(timeout=_ENGINE_CLOSE_TIMEOUT_S)
        return promoted

    def rollback(self) -> Optional[str]:
        with self._lock:
            dropped = self.store.rollback()
            if dropped is None:
                return None
            self.stats.record_rollback()
            self.gate.reset()
            retired = self._engines.pop(dropped, None)
        if retired is not None:
            retired.close(timeout=_ENGINE_CLOSE_TIMEOUT_S)
        return dropped

    def engine(self, name: str):
        """Get-or-create the live engine for a version (started, params
        installed). KeyError for unknown names."""
        with self._lock:
            eng = self._engines.get(name)
            if eng is None:
                flat = self.store.get(name)
                eng = self._make_engine()
                eng.refresh(flat)
                eng.start()
                self._engines[name] = eng
        return eng

    # --- request path (shared by TCP and HTTP) ---

    def handle_request(self, obj: dict, http: bool = False) -> dict:
        """One request object in, one response object out. Never raises
        for request-level failures — the typed-response contract."""
        try:
            req = wire.validate_request(obj)
        except wire.WireError as e:
            self.stats.record_bad_frame()
            return wire.error_response(
                obj.get("request_id") if isinstance(obj, dict) else None,
                e.code, str(e),
            )
        self.stats.record_request(http=http)
        t0 = time.monotonic()
        rid, tenant = req["request_id"], req["tenant"]
        try:
            if req["version"] is not None:
                name = req["version"]
                is_canary = name == self.store.candidate
                if name not in self.store.names():
                    return wire.error_response(
                        rid, "bad_frame", f"unknown version {name!r}"
                    )
            else:
                name, is_canary = self.store.route(tenant, rid)
        except RuntimeError as e:
            return wire.error_response(rid, "closed", str(e))
        canary_active = self.store.candidate is not None
        if is_canary:
            self.stats.record_canary_request()
            with self._lock:
                self._cand_ordinal += 1
                ordinal = self._cand_ordinal
            extra = max(
                (s for at, s in self._canary_regs if ordinal >= at),
                default=0.0,
            )
            if extra > 0:
                time.sleep(extra)  # front:canary:regress@K~S (sustained)
        eng = self.engine(name)
        cause = self.qos.admit(
            tenant, eng.batcher.depth(), eng.batcher.max_queue
        )
        if cause is not None:
            self.stats.record_shed()
            self.tenant_stats.record_shed(tenant, cause)
            return wire.error_response(
                rid, "shed",
                f"request shed by tenant QoS ({cause}); "
                f"priority={self.qos.priority(tenant)}",
            )

        done = threading.Event()
        box: list = []

        def _cb(result):
            box.append(result)
            done.set()

        error: Optional[tuple] = None
        try:
            eng.batcher.submit(
                np.asarray(req["obs"], np.float32), _cb
            )
        except ServeOverload as e:
            self.stats.record_overload()
            error = ("overload", str(e))
        except ServeClosed as e:
            error = ("closed", str(e))
        if error is None:
            remaining = self.timeout_s - (time.monotonic() - t0)
            if not done.wait(max(0.0, remaining)):
                self.stats.record_timeout()
                error = ("timeout", f"no response within {self.timeout_s}s")
            else:
                result = box[0]
                if isinstance(result, BaseException):
                    self.stats.record_error()
                    error = ("dispatch", f"{result!r}")
        latency = time.monotonic() - t0
        if canary_active:
            self.gate.record(is_canary, latency, error=error is not None)
            self._apply_verdict()
        if error is not None:
            self.tenant_stats.record_error(tenant)
            return wire.error_response(rid, *error)
        action = result
        if getattr(eng, "sac", False):
            # SAC serve head: the engine returned [mean | log_std]; the
            # per-client sampling key lives HERE, derived from
            # (tenant, request_id) — docs/SERVING.md 'SAC serve head'.
            action = eng.sample(action, tenant=tenant, request_id=rid)
        self.tenant_stats.record_served(tenant)
        self.stats.record_wire_latency(latency)
        return {
            "request_id": rid,
            "action": np.asarray(action, np.float32).reshape(-1).tolist(),
            "version": name,
        }

    def _apply_verdict(self) -> None:
        verdict = self.gate.verdict()
        if verdict == "rollback":
            self.rollback()
        elif verdict == "promote":
            with self._lock:
                has_candidate = self.store.candidate is not None
            if has_candidate:
                self.promote()

    # --- listeners ---

    def start(self) -> "FrontServer":
        front = self

        class _FrameHandler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                if front._fault_accept is not None:
                    front._fault_accept.tick()  # front:accept:*@K
                while True:
                    try:
                        obj = wire.read_frame(sock)
                    except wire.WireError as e:
                        # Lost framing: answer once, drop THIS connection.
                        front.stats.record_bad_frame()
                        try:
                            wire.send_frame(
                                sock,
                                wire.error_response(None, e.code, str(e)),
                            )
                        except OSError:
                            pass
                        return
                    except OSError:
                        return  # peer reset — nothing to answer
                    if obj is None:
                        return  # clean EOF
                    try:
                        if front._fault_frame is not None:
                            front._fault_frame.tick()  # front:frame:corrupt@K
                        resp = front.handle_request(obj)
                    except InjectedFault as e:
                        front.stats.record_bad_frame()
                        resp = wire.error_response(
                            obj.get("request_id"), "bad_frame",
                            f"corrupt frame: {e!r}",
                        )
                    except Exception as e:
                        # Belt-and-braces: the acceptor NEVER dies for a
                        # request (handle_request already types known
                        # failures).
                        resp = wire.error_response(
                            obj.get("request_id"), "dispatch", f"{e!r}"
                        )
                    try:
                        wire.send_frame(sock, resp)
                    except OSError:
                        return  # client went away mid-response

        class _TCP(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._tcp = _TCP(("127.0.0.1", self._req_port), _FrameHandler)
        self.port = self._tcp.server_address[1]
        t = threading.Thread(
            target=self._tcp.serve_forever, daemon=True, name="front-tcp"
        )
        t.start()
        self._threads.append(t)

        if self._req_http_port is not None:
            class _HttpHandler(BaseHTTPRequestHandler):
                def log_message(self, *args):  # quiet: metrics, not stderr
                    pass

                def _send(self, status: int, obj: dict) -> None:
                    body = json.dumps(obj).encode("utf-8")
                    self.send_response(status)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

                def do_POST(self):
                    try:
                        if self.path.rstrip("/") not in ("/act", ""):
                            self._send(404, {"error": "bad_frame",
                                             "message": "POST /act"})
                            return
                        try:
                            n = int(self.headers.get("Content-Length", 0))
                            if n > wire.MAX_FRAME:
                                raise wire.WireError(
                                    "bad_frame", f"body {n}B > {wire.MAX_FRAME}B"
                                )
                            obj = json.loads(self.rfile.read(n))
                            if not isinstance(obj, dict):
                                raise wire.WireError(
                                    "bad_frame", "body must be a JSON object"
                                )
                        except (wire.WireError, ValueError,
                                UnicodeDecodeError) as e:
                            front.stats.record_bad_frame()
                            self._send(400, wire.error_response(
                                None, "bad_frame", f"{e}"))
                            return
                        resp = front.handle_request(obj, http=True)
                        self._send(
                            _HTTP_STATUS.get(resp.get("error"), 200), resp
                        )
                    except OSError:
                        pass  # client disconnected mid-response

            self._http = ThreadingHTTPServer(
                ("127.0.0.1", self._req_http_port), _HttpHandler
            )
            self.http_port = self._http.server_address[1]
            t = threading.Thread(
                target=self._http.serve_forever, daemon=True,
                name="front-http",
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        for srv in (self._tcp, self._http):
            if srv is not None:
                srv.shutdown()
                srv.server_close()
        for t in self._threads:
            t.join(timeout=_STOP_JOIN_TIMEOUT_S)
        self._threads = []
        with self._lock:
            engines = list(self._engines.values())
            self._engines.clear()
        for eng in engines:
            eng.close(timeout=_ENGINE_CLOSE_TIMEOUT_S)

    # --- observability ---

    def snapshot(self) -> dict:
        """front_* + tenant_* families (metrics.py) for the train JSONL
        record and serve_bench digests."""
        out = self.stats.snapshot()
        out.update(self.tenant_stats.snapshot())
        return out
