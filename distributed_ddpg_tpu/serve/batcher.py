"""Dynamic request batcher for the policy-inference service
(docs/SERVING.md; the TorchBeast `max_batch`/`max_latency_ms` dispatch
discipline, PAPERS.md arXiv 1910.03552).

Clients enqueue single observations; one dispatcher thread collects them
into batches and dispatches whichever trigger fires FIRST:

  - the batch reached `max_batch` rows (dispatch immediately, never wait
    out the latency window on a full batch), or
  - the OLDEST pending request has waited `max_latency_s` (dispatch the
    partial batch — a lone late-night request must not wait forever for
    company).

Contracts the tier-1 tests pin (tests/test_serve.py):

  - Bounded queue with typed backpressure: at most `max_queue` requests
    may be pending; `submit` past that raises `ServeOverload` (the caller
    decides — an actor client degrades to its local act() path, an RPC
    front would shed the request).
  - Flush-on-shutdown loses nothing: `close()` stops admissions, then the
    dispatcher drains every pending request (partial batches dispatch
    immediately — no deadline wait during shutdown) before the thread
    exits. Every accepted request gets exactly one completion callback.
  - A failing batch apply fails typed: every request of that batch
    completes with a `ServeDispatchError` (cause attached), the batcher
    thread survives, and later batches serve normally — one poisoned
    batch must not kill the service.

Fault injection (faults.py): `serve:batcher:stall@k` sleeps the k-th
dispatch before collection (clients time out and fall back locally);
`serve:dispatch:crash@k` raises inside the k-th batch apply (the
typed-failure path above).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from distributed_ddpg_tpu import trace


class ServeOverload(RuntimeError):
    """The batcher's bounded request queue is full — typed backpressure.
    The service is shedding load, not broken: retry later or degrade."""


class ServeClosed(RuntimeError):
    """submit() after close(): the service is shutting down (or its
    dispatcher died). Callers degrade exactly as for ServeOverload."""


class ServeDispatchError(RuntimeError):
    """The batch apply for this request's batch raised; the original
    exception rides along as __cause__."""


class ServeTimeout(RuntimeError):
    """A blocking client gave up waiting for its response (client-side
    deadline — the request may still complete later; its callback fires
    into an abandoned ticket)."""


class _Pending:
    __slots__ = ("obs", "callback", "t_enq")

    def __init__(self, obs, callback, t_enq: float):
        self.obs = obs
        self.callback = callback
        self.t_enq = t_enq


class Batcher:
    """One dispatcher thread + a bounded pending deque. `apply_fn` maps a
    stacked [n, obs_dim] f32 batch to [n, act_dim] actions (the
    InferenceServer provides it; n <= max_batch)."""

    def __init__(
        self,
        apply_fn: Callable[[np.ndarray], np.ndarray],
        max_batch: int,
        max_latency_s: float,
        max_queue: int,
        stats=None,
        fault_batcher=None,
        fault_dispatch=None,
        name: str = "serve-batcher",
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self._apply = apply_fn
        self.max_batch = int(max_batch)
        self.max_latency_s = float(max_latency_s)
        self.max_queue = int(max_queue)
        self.stats = stats
        self._fault_batcher = fault_batcher
        self._fault_dispatch = fault_dispatch
        self._name = name
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle ---

    def start(self) -> "Batcher":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=self._name
        )
        self._thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Stop admissions, flush every pending request, join. Idempotent."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)

    @property
    def closed(self) -> bool:
        return self._closed

    def depth(self) -> int:
        with self._cv:
            return len(self._q)

    # --- submission ---

    def submit(self, obs: np.ndarray, callback: Callable) -> None:
        """Enqueue one observation row. `callback(result)` fires exactly
        once from the dispatcher thread: an [act_dim] f32 action row on
        success, an Exception instance (ServeDispatchError / ServeClosed)
        on failure. Raises ServeOverload / ServeClosed when the request
        was NOT accepted (no callback will fire)."""
        p = _Pending(obs, callback, time.monotonic())
        with self._cv:
            if self._closed:
                raise ServeClosed("inference batcher is closed")
            if len(self._q) >= self.max_queue:
                if self.stats is not None:
                    self.stats.record_overload()
                trace.instant("serve_overload", depth=len(self._q))
                raise ServeOverload(
                    f"serve request queue full ({self.max_queue} pending)"
                )
            self._q.append(p)
            if self.stats is not None:
                self.stats.record_request(len(self._q))
            self._cv.notify_all()

    # --- dispatch loop ---

    def _collect_locked(self) -> List[_Pending]:
        n = min(len(self._q), self.max_batch)
        return [self._q.popleft() for _ in range(n)]

    def _run(self) -> None:
        try:
            while True:
                with self._cv:
                    while not self._q and not self._closed:
                        self._cv.wait(0.05)
                    if not self._q and self._closed:
                        return
                    # Deadline from the OLDEST pending request; a full
                    # batch or shutdown (flush: no deadline wait) cuts
                    # the wait short.
                    deadline = self._q[0].t_enq + self.max_latency_s
                    while len(self._q) < self.max_batch and not self._closed:
                        now = time.monotonic()
                        if now >= deadline:
                            break
                        self._cv.wait(min(deadline - now, 0.05))
                    batch = self._collect_locked()
                if batch:
                    self._dispatch(batch)
        except BaseException as e:  # dispatcher machinery died: fail loudly
            self._die(e)

    def _dispatch(self, batch: List[_Pending]) -> None:
        try:
            if self._fault_batcher is not None:
                # serve:batcher:stall@k — sleeps here; the requests are
                # already collected, so their responses arrive LATE and
                # blocking clients hit their timeout fallback.
                self._fault_batcher.tick()
            # Inside the try: a malformed observation (wrong obs_dim from
            # a misbehaving client) must fail THIS batch typed, not kill
            # the dispatcher — "one poisoned batch must not kill the
            # service" (module docstring).
            obs = np.stack([p.obs for p in batch]).astype(
                np.float32, copy=False
            )
            with trace.span("serve_dispatch", rows=len(batch)):
                if self._fault_dispatch is not None:
                    self._fault_dispatch.tick()  # serve:dispatch:crash@k
                actions = np.asarray(self._apply(obs))
        except BaseException as e:
            if self.stats is not None:
                self.stats.record_error()
            trace.instant("serve_dispatch_error", rows=len(batch))
            err = ServeDispatchError(
                f"inference batch of {len(batch)} failed: {e!r}"
            )
            err.__cause__ = e
            for p in batch:
                self._complete(p, err)
            return
        now = time.monotonic()
        if self.stats is not None:
            self.stats.record_batch(
                len(batch), [now - p.t_enq for p in batch]
            )
        for i, p in enumerate(batch):
            self._complete(p, actions[i])

    @staticmethod
    def _complete(p: _Pending, result) -> None:
        try:
            p.callback(result)
        except Exception:
            # A client that died mid-wait must not take the service down.
            pass

    def _die(self, exc: BaseException) -> None:
        """The dispatch loop itself crashed (not a batch apply — those are
        caught per-batch). Mark closed so submits raise typed, and fail
        every pending request: a client blocked on a dead service must get
        its error, not a hang."""
        err = ServeClosed(f"inference batcher thread died: {exc!r}")
        err.__cause__ = exc
        with self._cv:
            self._closed = True
            pending = list(self._q)
            self._q.clear()
        for p in pending:
            self._complete(p, err)
