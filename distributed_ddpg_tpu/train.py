"""Training driver + CLI — the reference's `train.py` equivalent
(SURVEY.md §2 #1, §3.1), re-shaped for the TPU topology.

Where the reference launches {ps|worker} roles over a TF ClusterSpec and
syncs through gRPC (SURVEY.md §3.1), this driver runs ONE learner process
(holding the sharded mesh learner) plus N actor subprocesses (ActorPool) —
params flow through shared memory, gradients through XLA collectives, and
the only CLI distinction left is `--backend {native,jax_tpu}`
(BASELINE.json:5): `native` is the pure-CPU numpy baseline, `jax_tpu` the
sharded JAX path.

Usage:
    python -m distributed_ddpg_tpu.train --env_id=Pendulum-v1 \
        --backend=jax_tpu --num_actors=4 --total_env_steps=100000
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import Dict, Optional

import numpy as np

from distributed_ddpg_tpu import checkpoint as ckpt_lib
from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs import make, spec_of
from distributed_ddpg_tpu.metrics import (
    GuardrailStats,
    MeshStats,
    MetricsLogger,
    PhaseTimers,
    PodStats,
    Timer,
)
from distributed_ddpg_tpu.ops import support_auto
from distributed_ddpg_tpu.ops.noise import OUNoise
from distributed_ddpg_tpu.replay import make_replay

# Exit-code contract: the constants — and the full per-code rationale —
# live in distributed_ddpg_tpu/exits.py (docs/RESILIENCE.md exit-code
# matrix). Re-exported here because train is the historical import site
# (tests, chaos children, operator scripts all say
# `from distributed_ddpg_tpu.train import EXIT_...`).
from distributed_ddpg_tpu.exits import (  # noqa: F401  (re-export)
    EXIT_NUMERIC,
    EXIT_POD_DEGRADED,
    EXIT_POD_SHRINK,
    EXIT_PREEMPTED,
)

# Shutdown reap bound for the async eval thread: evals run whole episodes,
# so teardown grants them real time to finish, but a wedged env must not
# hold the trainer's exit hostage — the thread is daemonized, so past this
# bound we abandon it and let interpreter exit reap it.
_EVAL_JOIN_S = 60.0


def _enable_faulthandler() -> None:
    """Stack dumps on demand (kill -USR1 <pid>) and on hard faults — a
    wedged driver must be debuggable without a debugger attached. Called
    from train() (CLI and ladder entries) and from bench.py's phase
    bootstrap (its subprocesses never enter train())."""
    import faulthandler
    import signal

    faulthandler.enable()
    if hasattr(signal, "SIGUSR1"):
        faulthandler.register(signal.SIGUSR1)


def train(config: DDPGConfig) -> Dict[str, float]:
    _enable_faulthandler()
    if config.backend == "native":
        return train_native(config)
    # Breadcrumb BEFORE the first XLA-backend touch: on this class of host
    # a wedged accelerator tunnel makes backend init hang with no output
    # at all (observed live — runs/r4_tpu_probe.log), and the stall
    # watchdog only arms later. One stderr line turns a silent hang into a
    # diagnosable one.
    import jax

    plat = jax.config.jax_platforms or "default"
    hint = (
        ""
        if plat == "cpu"
        else (
            "; a hang here usually means the accelerator tunnel is "
            "unreachable — set JAX_PLATFORMS=cpu to bypass"
        )
    )
    print(
        f"[train] initializing JAX backend (jax_platforms={plat}){hint}",
        file=sys.stderr,
        flush=True,
    )
    if config.backend == "jax_ondevice":
        return train_ondevice(config)
    return train_jax(config)


# ---------------------------------------------------------------------------
# --backend native: the measured CPU baseline (BASELINE.md)
# ---------------------------------------------------------------------------


def train_native(config: DDPGConfig) -> Dict[str, float]:
    from distributed_ddpg_tpu.learner import init_train_state
    from distributed_ddpg_tpu.native_backend import NativeLearner
    from distributed_ddpg_tpu.replay.nstep import NStepAccumulator

    env = make(config.env_id, seed=config.seed)
    spec = spec_of(env)
    # Param init is the only JAX use on the native path; pin it to the host
    # CPU so the baseline never touches (or waits on) an accelerator.
    import jax

    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        state = init_train_state(config, spec.obs_dim, spec.act_dim, config.seed)
    learner = NativeLearner(config, state, spec.action_scale, spec.action_offset)
    replay = make_replay(config, spec.obs_dim, spec.act_dim)
    noise = OUNoise(
        (spec.act_dim,), config.ou_theta, config.ou_sigma, dt=config.ou_dt,
        seed=config.seed + 1,
    )
    nstep = NStepAccumulator(config.n_step, config.gamma)
    log = MetricsLogger(config.log_path, tb_dir=config.tb_dir)
    learn_timer = Timer()
    learn_steps = 0
    metrics: Dict[str, float] = {}
    ep_return, ep_returns = 0.0, []

    # learner.act is the deterministic policy (no OU noise) — the same
    # policy surface the jax path evaluates, so the two backends' eval
    # curves are directly comparable (the quality gate, BASELINE.md).
    eval_policy = learner.act

    obs, _ = env.reset(seed=config.seed)
    for step in range(1, config.total_env_steps + 1):
        action = learner.act(obs)[0] + noise() * spec.action_scale
        action = np.clip(action, spec.action_low, spec.action_high).astype(np.float32)
        next_obs, reward, terminated, truncated, _ = env.step(action)
        ep_return += reward
        for tr in nstep.push(obs[None], action[None], [reward], [terminated], next_obs[None]):
            replay.add(*tr)
        obs = next_obs
        if terminated or truncated:
            obs, _ = env.reset()
            noise.reset()
            nstep.reset()
            ep_returns.append(ep_return)
            ep_return = 0.0
        if (
            len(replay) >= max(config.replay_min_size, config.batch_size)
            and step % config.train_every == 0
        ):
            sample = replay.sample(config.batch_size)
            indices = sample.pop("indices")
            m = learner.step(sample)
            td = m.pop("td_errors")
            if config.prioritized:
                replay.update_priorities(indices, td)
            metrics = m
            learn_steps += 1
            learn_timer.tick()
        if step % max(1, config.eval_every) == 0:
            log.log(
                "train", step,
                learner_steps=learn_steps,
                learner_steps_per_sec=learn_timer.rate(),
                buffer_fill=len(replay),
                episode_return=(
                    float(np.mean(ep_returns)) if ep_returns else None
                ),
                **metrics,
            )
            ep_returns = []
            if learn_steps:  # past warmup: policy is being trained
                # Inline eval is off-path work: exclude its wall time from
                # the learner rate (the jax path runs evals on a background
                # thread for the same reason) so the reported baseline
                # steps/sec measures learning, not evaluation.
                t_eval = time.time()
                ret = _eval_numpy(eval_policy, config, spec)
                learn_timer.exclude(time.time() - t_eval)
                log.log("eval", step, eval_return=ret)
    rate = learn_timer.rate()
    final_return = _eval_numpy(eval_policy, config, spec)
    log.log(
        "final", config.total_env_steps,
        learner_steps_per_sec=rate, final_return=final_return,
    )
    log.close()
    return {
        "learner_steps_per_sec": rate,
        "learner_steps": learn_steps,
        "final_return": final_return,
    }


# ---------------------------------------------------------------------------
# --backend jax_ondevice: env + replay + learner fused in one XLA program
# ---------------------------------------------------------------------------


def train_ondevice(config: DDPGConfig) -> Dict[str, float]:
    import jax

    from distributed_ddpg_tpu.actors.policy import NumpyPolicy, actor_head_dim, flatten_params, param_layout
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG
    from distributed_ddpg_tpu.parallel import multihost

    multihost.initialize()
    trainer = OnDeviceDDPG(config)
    log = MetricsLogger(config.log_path, tb_dir=config.tb_dir)

    # Resume: the checkpoint contract matches the other backends (TrainState
    # + replay contents + env-step offset), via a thin adapter for the
    # carry-resident replay ring.
    class _ReplayView:
        def state_dict(self):
            return trainer.replay_state_dict()

        def load_state_dict(self, d):
            trainer.load_replay_state(d)

    env_steps_offset = 0
    last_ckpt = 0
    if (
        config.resume
        and config.checkpoint_dir
        and ckpt_lib.latest_step(config.checkpoint_dir) is not None
    ):
        restored, step, env_steps_offset = ckpt_lib.restore(
            config.checkpoint_dir,
            jax.device_get(trainer.state),
            _ReplayView(),
            config=config,
        )
        trainer.load_train_state(restored)
        trainer._learn_steps = step
        last_ckpt = step
        print(
            f"resumed from {config.checkpoint_dir} at learner step {step}, "
            f"env step {env_steps_offset}"
        )

    spec = _jax_env_spec(trainer)
    eval_policy = NumpyPolicy(
        param_layout(
            spec.obs_dim,
            actor_head_dim(spec.act_dim, config.sac),
            tuple(config.actor_hidden),
        ),
        spec.action_scale,
        spec.action_offset,
        gaussian=config.sac,
    )
    profile_cm = (
        jax.profiler.trace(config.profile_dir)
        if config.profile_dir
        else contextlib.nullcontext()
    )
    env_timer, learn_timer = Timer(), Timer()
    last_eval = 0
    eval_return = None

    def env_steps() -> int:
        return env_steps_offset + trainer.env_steps

    # Episode stats are per-chunk and sparse (an episode boundary may fall in
    # any chunk); aggregate across chunks between log events.
    episodes_acc, return_acc = 0, []

    # superstep_beats > 1: B chunks per dispatch (ondevice.run_superstep),
    # one stats device_get per superstep instead of per chunk. The log
    # cadence below becomes a crossing test so a B that doesn't divide
    # the 10-chunk stride still logs on every stride crossed.
    beats = max(1, trainer.superstep_beats)
    rows_per_dispatch = trainer.chunk_size * trainer.num_envs * beats
    log_stride = trainer.chunk_size * trainer.num_envs * 10
    with profile_cm:
        while env_steps() < config.total_env_steps:
            before = trainer.learn_steps
            stats = (
                trainer.run_superstep() if beats > 1 else trainer.run_chunk()
            )
            host = trainer.finalize_stats(stats)
            env_timer.tick(rows_per_dispatch)
            learn_timer.tick(trainer.learn_steps - before)
            episodes_acc += host.pop("episodes", 0)
            if "episode_return" in host:
                return_acc.append(host.pop("episode_return"))
            log_now = (
                trainer.env_steps // log_stride
                != (trainer.env_steps - rows_per_dispatch) // log_stride
            )
            if env_steps() - last_eval >= config.eval_every:
                eval_policy.load_flat(flatten_params(trainer.actor_params_to_host()))
                eval_return = _eval_numpy(eval_policy, config, spec)
                last_eval = env_steps()
                log.log("eval", env_steps(), eval_return=eval_return)
            if log_now:
                log.log(
                    "train", env_steps(),
                    learner_steps=trainer.learn_steps,
                    env_steps_per_sec=env_timer.rate(),
                    learner_steps_per_sec=learn_timer.rate(),
                    episodes=episodes_acc,
                    episode_return=(
                        float(np.mean(return_acc)) if return_acc else None
                    ),
                    **host,
                )
                episodes_acc, return_acc = 0, []
            if (
                config.checkpoint_dir
                and trainer.learn_steps - last_ckpt >= config.checkpoint_every
            ):
                ckpt_lib.save(
                    config.checkpoint_dir, trainer.learn_steps,
                    jax.device_get(trainer.state), _ReplayView(), config,
                    env_steps=env_steps(),
                    keep=config.checkpoint_keep,
                )
                last_ckpt = trainer.learn_steps

    eval_policy.load_flat(flatten_params(trainer.actor_params_to_host()))
    final_return = _eval_numpy(eval_policy, config, spec)
    rate = env_timer.rate()
    log.log(
        "final", env_steps(),
        learner_steps=trainer.learn_steps,
        env_steps_per_sec=rate,
        learner_steps_per_sec=learn_timer.rate(),
        final_return=final_return,
    )
    log.close()
    return {
        "env_steps_per_sec": rate,
        "learner_steps_per_sec": learn_timer.rate(),
        "learner_steps": trainer.learn_steps,
        "final_return": final_return,
    }


def _jax_env_spec(trainer):
    from distributed_ddpg_tpu.envs.registry import EnvSpec

    env = trainer.env
    return EnvSpec(
        obs_dim=env.obs_dim,
        act_dim=env.act_dim,
        action_low=np.asarray(env.action_low, np.float32),
        action_high=np.asarray(env.action_high, np.float32),
    )


# ---------------------------------------------------------------------------
# --backend jax_tpu: async actors + sharded mesh learner
# ---------------------------------------------------------------------------


def train_jax(config: DDPGConfig) -> Dict[str, float]:
    # Flight recorder (trace.py): armed for the whole device lifetime so
    # the watchdog's stall path below can ship the last-N-seconds
    # timeline with its stack dump. Exported on clean exit and on demand
    # (SIGUSR2 — the stack-dump sibling of _enable_faulthandler's
    # SIGUSR1, for peeking at a LIVE run's timeline without killing it).
    trace_path = ""
    if config.trace_dir:
        trace.configure(capacity=config.trace_events)
        trace_path = os.path.join(config.trace_dir, "trace.json")
        trace.install_signal_export(trace_path)

    # Stall watchdog (watchdog.py): covers the WHOLE device lifetime of
    # the impl below — backend/PJRT init (resolve_learner_chunk's
    # platform probe and ShardedLearner), the first params d2h at
    # pool.start, every loop iteration, and teardown — any of which is
    # an unbounded blocking call that a wedged device/tunnel turns into
    # a silent hang. The beat counter advances at each supervised
    # milestone; the wrapper guarantees the watchdog dies with the call
    # (a leaked watchdog would os._exit a process that already
    # recovered from an ordinary exception).
    _beat_n = [0]

    def _beat() -> None:
        _beat_n[0] += 1

    watchdog = None
    if config.watchdog_s > 0:
        from distributed_ddpg_tpu.watchdog import Watchdog

        watchdog = Watchdog(
            config.watchdog_s,
            progress=lambda: _beat_n[0],
            # Stall artifacts land next to the trace when tracing is on,
            # else next to checkpoints, else the cwd — a stall must always
            # leave its structured report somewhere findable.
            stall_dir=(config.trace_dir or config.checkpoint_dir or "."),
        ).start()

    def _grant(extra_s: float) -> None:
        if watchdog is not None:
            watchdog.grant(extra_s)

    try:
        return _train_jax_impl(config, _beat, _grant)
    finally:
        if watchdog is not None:
            watchdog.stop()
        if trace_path:
            try:
                n = trace.export(trace_path)
                print(
                    f"[trace] {n} events -> {trace_path} "
                    "(load in ui.perfetto.dev)",
                    file=sys.stderr,
                )
            except Exception as e:
                # Diagnostics must never turn a finished run into a
                # failure (or mask an in-flight exception): a full disk
                # at export time loses the trace, not the run.
                print(f"[trace] export failed: {e!r}",
                      file=sys.stderr, flush=True)
            finally:
                trace.disable()


def _train_jax_impl(config: DDPGConfig, _beat, _grant=lambda extra_s: None) -> Dict[str, float]:
    import jax

    from distributed_ddpg_tpu.actors.policy import NumpyPolicy, actor_head_dim, flatten_params, param_layout
    from distributed_ddpg_tpu.actors.pool import ActorPool
    from distributed_ddpg_tpu.parallel import multihost
    from distributed_ddpg_tpu.parallel.learner import (
        ShardedLearner,
        resolve_learner_chunk,
    )
    from distributed_ddpg_tpu.parallel.prefetch import ChunkPrefetcher

    from distributed_ddpg_tpu.replay.device import (
        DevicePrioritizedReplay,
        DeviceReplay,
    )
    from distributed_ddpg_tpu.types import pack_batch_np

    # The JAX runtime's own heartbeat killer must stay SLOWER than the
    # pod layer's worst-case detection (deadline + grace), or a peer
    # death during a granted window LOG(FATAL)s survivors before the
    # clean abort (docs/RESILIENCE.md pod rows). Derived here so the
    # contract holds with default config, not only when an operator
    # remembers the POD_RUNTIME_HEARTBEAT_TIMEOUT_S override.
    is_multi = multihost.initialize(
        runtime_heartbeat_timeout_s=(
            config.pod_collective_timeout_s + config.pod_startup_grace_s
            + 120.0
            if config.pod_collective_timeout_s > 0
            else None
        )
    )
    # --- chaos harness + preemption (docs/RESILIENCE.md) ---
    # The fault plan is parsed once; each recoverable component gets its
    # own call-site injector. SIGTERM flips a flag the loop polls at chunk
    # boundaries: the run takes ONE emergency checkpoint off the hot loop
    # and returns with summary["preempted"] set (main() exits
    # EXIT_PREEMPTED so drivers can tell "resumable" from "crashed").
    fault_plan = config.fault_plan()
    ckpt_fault = fault_plan.site("ckpt", "write") if fault_plan else None
    preempt = threading.Event()
    emergency_ckpt = [0]

    # --- telemetry plane (obs/; docs/OBSERVABILITY.md §4) ---
    # The health state machine is a process singleton (the watchdog and
    # multihost flip it from their own threads without plumbing); reset
    # here so back-to-back runs in one process (tests, notebooks) don't
    # inherit a previous run's latched `draining`.
    from distributed_ddpg_tpu.obs import ObsExporter, PodAggregator, health

    health.get().reset()
    obs_server: Optional[ObsExporter] = None

    # --- numerical-health guardrails (guardrails.py; docs/RESILIENCE.md) ---
    # The learner's chunk programs carry the on-device probe; this side
    # holds the host half: per-chunk health-word reads, the rolling
    # anomaly window that triggers rollback-repair, bad-row -> ingest-
    # source attribution, and the LR cooldown. All trigger inputs (health
    # counters, learn_steps) are replicated/identical across processes,
    # so a pod takes every rollback on the same chunk.
    guard_on = config.guardrails
    gstats = GuardrailStats()
    guard_window: list = []            # (learn_steps at read, anomaly count)
    guard_src_offenses: Dict[int, int] = {}
    numeric_failed = [False]
    lr_backoff_since = [-1]            # learn_steps at LR backoff; -1 = none
    # numeric:replay:inf@k (faults.py): poison the k-th ingested row's
    # reward to +inf at drain time — the deterministic bad-replay-row
    # chaos vector (device-replay path; ordinals are per process).
    numeric_replay_at = fault_plan.numeric_replay_rows() if fault_plan else ()
    ingested_rows = [0]

    # --- pod resilience (parallel/multihost.py; docs/RESILIENCE.md) ---
    # Multi-process only: arm the collective deadline (a hung DCN
    # collective surfaces as PodPeerLost within pod_collective_timeout_s
    # instead of blocking forever) and run the one-time startup barrier
    # with its own generous grace — startup skew under box load must be
    # absorbed here, not read as a dead peer by the per-beat deadline.
    # Single-process runs never configure the deadline, so every guarded
    # call short-circuits to a direct call (zero overhead).
    pod_stats = PodStats(seed=config.seed)
    pod_lost: list = [None]
    # Shrink-ready flag (EXIT_POD_SHRINK=78): set on a pod abort when a
    # complete replay slice set survives under checkpoint_dir — the
    # driver may relaunch SMALLER instead of waiting for the lost host.
    pod_shrink_ready = [False]

    def _slices_adoptable() -> bool:
        return bool(
            config.checkpoint_dir
            and config.replay_sharding == "sharded"
            and ckpt_lib.latest_complete_slice_step(config.checkpoint_dir)
            is not None
        )

    def _pod_degraded_early(e) -> Dict[str, float]:
        """Peer loss BEFORE the training stack exists (startup barrier /
        resume election): nothing to checkpoint, but the exit contract
        still applies — main()/the pod harness must see pod_degraded and
        exit EXIT_POD_DEGRADED (76), not a generic traceback the driver
        would misread as 'crash: diagnose' (docs/RESILIENCE.md)."""
        pod_lost[0] = e
        pod_stats.record_abort()
        # A prior incarnation may have left an adoptable slice set: a
        # bootstrap loss is still shrink-recoverable then (exit 78).
        pod_shrink_ready[0] = _slices_adoptable()
        print(
            f"[train] pod peer lost during pod bootstrap: {e}; exiting "
            f"{EXIT_POD_SHRINK if pod_shrink_ready[0] else EXIT_POD_DEGRADED}",
            file=sys.stderr, flush=True,
        )
        return {
            "learner_steps_per_sec": 0.0,
            "learner_steps": 0,
            "final_return": None,
            "param_checksum": 0.0,
            "preempted": False,
            "pod_degraded": True,
            "pod_shrink_ready": pod_shrink_ready[0],
            **pod_stats.snapshot(),
        }

    if is_multi:
        multihost.configure_pod(
            config.pod_collective_timeout_s, stats=pod_stats
        )
        try:
            multihost.startup_barrier(config.pod_startup_grace_s)
            # Clock-alignment handshake (docs/OBSERVABILITY.md §4): one
            # wall-clock allgather right after the barrier, while every
            # process is provably at the same program point. Each host
            # records its offset from host 0 into the flight recorder's
            # metadata so `tools.runs merge-trace` can fuse the per-host
            # Chrome traces onto one timeline without trusting NTP.
            clocks = multihost.clock_handshake()
            if clocks is not None:
                trace.set_meta(
                    process_index=jax.process_index(),
                    process_count=jax.process_count(),
                    clock_offset_ms=clocks["offset_ms"][jax.process_index()],
                    pod_wall_ms=clocks["wall_ms"],
                )
        except multihost.PodPeerLost as e:
            multihost.configure_pod(0.0)
            return _pod_degraded_early(e)

    def _grant_all(extra_s: float) -> None:
        """Extend BOTH stall detectors across a known-long window (first
        chunk XLA compile, support-expansion recompile): the watchdog and
        the pod collective deadline must agree that a compiling pod is
        not a wedged or dead one. The pod side gets pod_startup_grace_s —
        only the compile SKEW between processes can delay a collective,
        and the worst-case peer-loss detection latency stays the
        documented `pod_collective_timeout_s + grace` bound."""
        _grant(extra_s)
        if is_multi:
            multihost.grant(config.pod_startup_grace_s)

    env = make(config.env_id, seed=config.seed)
    spec = spec_of(env)
    chunk = resolve_learner_chunk(config)
    min_fill = max(config.replay_min_size, config.batch_size)
    n_proc = jax.process_count()
    if (
        config.host_replay
        and config.distributional
        and config.v_support_auto
        and n_proc > 1
    ):
        # Fail FAST, before mesh/learner construction: host replay is
        # process-LOCAL (each process ingests its own actors), so the
        # auto-support warmup sizing and every data-corroboration check
        # would derive DIFFERENT bounds per replica — different compiled
        # Bellman targets on each process, a silent mesh fork. Device
        # replay is replicated (lockstep sync_ship), which is what makes
        # the decisions replica-identical.
        raise ValueError(
            "v_min/v_max=auto with --host_replay is not supported "
            "multi-process: per-process replay statistics would fork the "
            "replicas' compiled programs. Use the device replay path "
            "(default) or concrete v_min/v_max."
        )
    if (
        config.replay_sharding == "sharded"
        and config.distributional
        and config.v_support_auto
        and n_proc > 1
    ):
        # Same fail-fast discipline: the auto-support reward sampler reads
        # replay rows host-side (reward_sample), which in sharded mode is
        # an eager cross-shard gather — not routed through the lockstep
        # lane, so multi-process it could interleave with queued beats.
        raise ValueError(
            "v_min/v_max=auto with replay_sharding='sharded' is not "
            "supported multi-process: the support sizer's host-side "
            "reward reads are cross-shard gathers outside the lockstep "
            "lane. Use replicated replay or concrete v_min/v_max."
        )
    if (
        config.max_learn_ratio > 0.0
        and config.max_ingest_ratio > 0.0
        and chunk > (1.0 + config.max_learn_ratio * n_proc) * min_fill
    ):
        # With BOTH gates armed the first chunk must fit the combined
        # initial allowance: EACH process's ingest caps its local env steps
        # at W = max(replay_min, batch), the learner gate compares against
        # the global sum (n_proc * W at most initially), so it needs
        # chunk <= (1 + learn_ratio * n_proc) * W — otherwise neither
        # counter ever advances. (The config-level product >= 1 check can't
        # see the resolved chunk or process count, so the full condition
        # lives here.)
        raise ValueError(
            f"learner chunk {chunk} exceeds the initial gate allowance "
            f"(1 + max_learn_ratio * {n_proc}) * {min_fill} = "
            f"{(1.0 + config.max_learn_ratio * n_proc) * min_fill:.0f}: "
            "the run would livelock at startup. Lower learner_chunk or "
            "raise replay_min_size."
        )
    # SIGTERM handler: installed after the fail-fast config checks above
    # (an early ValueError must not leak the handler — its restore lives
    # in the teardown finally below) and before the first long-running
    # stage, so preemption covers learner construction and warmup too.
    import signal

    def _on_sigterm(*_):
        preempt.set()
        # /healthz must flip to `draining` on the FIRST scrape after the
        # signal — the supervisor that sent SIGTERM reads it as "ack,
        # winding down", distinct from degraded-but-recoverable.
        health.get().drain("preempted (SIGTERM)")
        print(
            "[train] SIGTERM: finishing the in-flight chunk, taking an "
            f"emergency checkpoint, exiting {EXIT_PREEMPTED} (resumable)",
            file=sys.stderr, flush=True,
        )

    prev_sigterm = None
    try:
        prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not on the main thread (embedded callers): no handler

    # --- unified transfer scheduler (transfer/; docs/TRANSFER.md) ---
    # One dispatch thread owns replay-ingest super-blocks, prefetch chunk
    # h2d, learner d2h accounting, and (multi-host) the lockstep ingest
    # collective's background beats. Off under strict_sync: scheduler
    # dispatch timing would make the metrics stream host-scheduling-
    # dependent, breaking the bit-identical-two-runs contract. Created
    # after the fail-fast config checks so an early ValueError cannot
    # leak the dispatch thread.
    transfer_sched = None
    if config.transfer_scheduler and not config.strict_sync:
        from distributed_ddpg_tpu.transfer import TransferScheduler

        transfer_sched = TransferScheduler(
            fault=(
                fault_plan.site("transfer", "dispatch")
                if fault_plan else None
            ),
            # Pod deadline on the lockstep lane (docs/RESILIENCE.md):
            # every multi-host collective beat is bounded, so an
            # in-flight beat whose peer died FAILS its ticket with
            # PodPeerLost instead of wedging the lane.
            lockstep_timeout_s=(
                config.pod_collective_timeout_s if is_multi else 0.0
            ),
        ).start()

    learner = ShardedLearner(
        config,
        spec.obs_dim,
        spec.act_dim,
        spec.action_scale,
        spec.action_offset,
        chunk_size=chunk,
        replay_sharding=config.replay_sharding,
    )
    _beat()  # backend init + learner construction survived
    # Replay lives ON DEVICE (zero h2d in the steady state) for both
    # uniform and prioritized modes (replay/device.py; the PER priority
    # vector is device-resident too). config.host_replay forces the host
    # buffer + prefetch pipeline — the fallback for buffers beyond HBM.
    use_device_replay = not config.host_replay
    if use_device_replay:
        # Async ingest shipping (docs/INGEST.md): single-process only
        # (multi-host rows leave via the lockstep sync_ship collective)
        # and never under strict_sync — the shipper thread would make
        # row-landing timing (hence the sampled stream) a function of
        # host scheduling instead of the config.
        replay_kwargs = dict(
            mesh=learner.mesh,
            block_size=1024,
            async_ship=(
                config.ingest_async and not is_multi and not config.strict_sync
            ),
            max_coalesce=config.ingest_coalesce,
            fault=(
                fault_plan.site("shipper", "ship") if fault_plan else None
            ),
            # Transfer-scheduler policies (docs/TRANSFER.md): scheduled
            # ingest work items, adaptive coalesce cap, pooled staging
            # buffers, and (multi-host) background sync_ship beats. ALL
            # gated on the scheduler actually running: strict_sync and
            # transfer_scheduler=False must recover the PR-1 pipeline
            # verbatim (the adaptive cap is wall-clock-driven, so letting
            # it run under strict_sync would break the bit-identical-
            # metrics contract).
            scheduler=transfer_sched,
            adaptive_coalesce=(
                transfer_sched is not None
                and config.ingest_coalesce_adaptive
            ),
            host_pool=(
                transfer_sched is not None and config.transfer_host_pool
            ),
            background_sync=config.sync_ship_background,
            # Guardrail bad-row attribution: map storage positions back
            # to the actor slot that produced them (guardrails.py).
            track_sources=(
                guard_on and config.guardrail_source_offenses > 0
            ),
            # Placement (docs/REPLAY_SHARDING.md): replicated (parity
            # oracle) or partitioned over the mesh's data axis.
            replay_sharding=config.replay_sharding,
        )
        device_replay = (
            DevicePrioritizedReplay(
                config.replay_capacity, spec.obs_dim, spec.act_dim,
                alpha=config.per_alpha, eps=config.per_eps, **replay_kwargs,
            )
            if config.prioritized
            else DeviceReplay(
                config.replay_capacity, spec.obs_dim, spec.act_dim,
                **replay_kwargs,
            )
        )
    else:
        device_replay = None
    replay = None if use_device_replay else make_replay(config, spec.obs_dim, spec.act_dim)
    # Checkpointable replay object. SHARDED replay spans processes — no
    # single writer can snapshot a multi-host ring — so its contents are
    # omitted from checkpoints' learner tree and persisted instead as
    # ALL-WRITER slices (docs/REPLAY_SHARDING.md): every shard owner
    # writes its position-indexed slice + digest sidecar next to the
    # checkpoint at the same cadence step, and a restore at ANY process
    # count M merges a verified complete set and reshards it to M
    # (replay/device.py merge_slice_states). Single-process sharded runs
    # take the same path so the wire format never depends on the process
    # count — the elastic shrink/grow contract (docs/RESILIENCE.md).
    sharded_multi = is_multi and config.replay_sharding == "sharded"
    slice_writer = use_device_replay and config.replay_sharding == "sharded"
    slice_fault = (
        fault_plan.slice_site(jax.process_index()) if fault_plan else None
    )
    if slice_writer and jax.process_index() == 0:
        print(
            "[replay] sharded mode: replay contents are "
            "omitted from checkpoints' learner tree; every process "
            "writes its replay slice (docs/REPLAY_SHARDING.md)",
            file=sys.stderr, flush=True,
        )

    def ckpt_replay():
        if slice_writer:
            return None
        return device_replay if use_device_replay else replay

    def write_replay_slices(step: int) -> None:
        """All-writer replay persistence: this process's slice of the
        sharded ring lands next to the learner checkpoint (atomic write +
        digest sidecar — checkpoint.write_replay_slice). A failed slice
        write costs this step's slice-set completeness, never the run:
        adoption falls back to the newest older complete set."""
        if not (slice_writer and config.checkpoint_dir
                and device_replay is not None):
            return
        try:
            ckpt_lib.write_replay_slice(
                config.checkpoint_dir, step,
                jax.process_index(), jax.process_count(),
                device_replay.slice_state_dict(), fault=slice_fault,
            )
        except Exception as e:
            print(
                f"[pod] replay slice write at step {step} failed "
                f"({e!r}); the step's slice set stays incomplete",
                file=sys.stderr, flush=True,
            )
    if config.strict_sync:
        # Lockstep debug mode (config.strict_sync): inline deterministic
        # actors — same surface, no processes, no races to win.
        from distributed_ddpg_tpu.actors.sync_pool import SyncActorPool

        pool = SyncActorPool(config, spec)
    else:
        pool = ActorPool(config, spec)
    # --- resume (SURVEY.md §3.5/§5: learner restart = checkpoint restore;
    # unlike the reference, replay contents come back too). The saved config
    # is validated first; env-step progress carries over so the TOTAL budget
    # (total_env_steps) spans crashes instead of restarting from zero. ---
    learn_steps = 0
    env_steps_offset = 0
    # Per-process emergency-checkpoint directory (pod abort): process 0
    # owns config.checkpoint_dir exactly as before; any OTHER survivor of
    # a pod abort writes into a proc<k> subdirectory, so a shared
    # filesystem never races two writers on the same step_N while
    # per-host local disks still each get a valid emergency checkpoint.
    pod_ckpt_dir = config.checkpoint_dir
    if is_multi and config.checkpoint_dir and jax.process_index() != 0:
        pod_ckpt_dir = os.path.join(
            config.checkpoint_dir, f"proc{jax.process_index()}"
        )
    resume_dir = config.checkpoint_dir
    resume_step: Optional[int] = None
    do_resume = False
    if config.resume and config.checkpoint_dir:
        if is_multi:
            # Coordinated resume (docs/RESILIENCE.md pod rows): gather
            # each process's manifest-valid steps (main dir + its own pod
            # emergency dir) and restore the greatest COMMON step — a
            # step newer on only some processes would fork the pod. This
            # is a collective: ALL processes take this path whether or
            # not they see checkpoints locally (a conditional collective
            # would deadlock the ones that do).
            main_steps = set(ckpt_lib.valid_steps(config.checkpoint_dir))
            own_steps = (
                set(ckpt_lib.valid_steps(pod_ckpt_dir))
                if pod_ckpt_dir != config.checkpoint_dir
                else set()
            )
            try:
                elected = multihost.elect_resume_step(main_steps | own_steps)
            except multihost.PodPeerLost as e:
                # A peer died before the pod even agreed on a resume
                # step: same exit contract as a mid-run loss, minus the
                # emergency checkpoint (no new progress exists yet). The
                # already-built pieces (SIGTERM handler, replay shipper,
                # transfer scheduler) sit ABOVE the main try/finally, so
                # they are torn down here — an embedded caller must get
                # its SIGTERM handler back (the installed one only sets a
                # dead run's preempt flag).
                if prev_sigterm is not None:
                    try:
                        signal.signal(signal.SIGTERM, prev_sigterm)
                    except (ValueError, TypeError):
                        pass
                if use_device_replay and device_replay is not None:
                    device_replay.close()
                if transfer_sched is not None:
                    transfer_sched.close()
                multihost.configure_pod(0.0)
                return _pod_degraded_early(e)
            if elected >= 0:
                do_resume = True
                resume_step = elected
                resume_dir = (
                    config.checkpoint_dir
                    if elected in main_steps
                    else pod_ckpt_dir
                )
                pod_stats.record_resume_elected(elected)
                trace.instant("pod_resume_elected", step=elected)
                print(
                    f"[pod] resume election: step {elected} is the newest "
                    "checkpoint valid on every process"
                )
        elif ckpt_lib.latest_step(config.checkpoint_dir) is not None:
            do_resume = True
    ckpt_meta: Dict[str, object] = {}
    if do_resume:
        restored, step, env_steps_offset = ckpt_lib.restore(
            resume_dir,
            learner.state,
            ckpt_replay(),
            step=resume_step,
            config=config,
            meta_out=ckpt_meta,
        )
        learner.state = jax.device_put(restored, learner._state_sharding)
        learn_steps = step
        if config.distributional and config.v_support_auto:
            # The RESOLVED support bounds ride the checkpoint: the restored
            # critic's logits are only meaningful over the atom values they
            # were trained against — re-deriving from reward statistics
            # cannot recover mean_q-driven expansions. Old checkpoints
            # without the field fall back to warmup re-derivation below.
            if "v_bounds" in ckpt_meta:
                learner.set_value_bounds(*ckpt_meta["v_bounds"])
                print(
                    "auto C51 support restored from checkpoint: "
                    f"[{learner.config.v_min:.1f}, {learner.config.v_max:.1f}]"
                )
        # Resumed progress counts against the uniform-warmup budget
        # (pool._spawn) — no random-action re-injection mid-training.
        pool.env_steps_offset = env_steps_offset
        print(
            f"resumed from {resume_dir} at learner step {step}, "
            f"env step {env_steps_offset}"
        )

    # --- replay slice adoption (elastic shrink/grow; docs/RESILIENCE.md
    # state machine, docs/REPLAY_SHARDING.md all-writer format) ---
    # A sharded-replay resume restored NO replay through the learner tree
    # (ckpt_replay() is None); the experience lives in the all-writer
    # slice sets instead. Adopt the newest complete, digest-verified set
    # at or below the restored learner step — possibly written by a
    # DIFFERENT process count n_prev: merge is position-driven, the load
    # reshards to today's count M. M < n_prev is a SHRINK (a peer's last
    # verified slice is adopted by the survivors; the run continues
    # degraded), M > n_prev is a GROW back toward full strength. The
    # election keeps adoption pod-atomic: either every process adopts the
    # same step or nobody does (a forked replay distribution is worse
    # than an empty one).
    if (
        do_resume
        and slice_writer
        and device_replay is not None
        and not ckpt_meta.get("ckpt_has_replay")
    ):
        sstep = ckpt_lib.latest_complete_slice_step(
            config.checkpoint_dir, at_or_below=learn_steps
        )
        if is_multi:
            try:
                elected_slice = multihost.elect_slice_step(sstep)
            except multihost.PodPeerLost as e:
                if prev_sigterm is not None:
                    try:
                        signal.signal(signal.SIGTERM, prev_sigterm)
                    except (ValueError, TypeError):
                        pass
                device_replay.close()
                if transfer_sched is not None:
                    transfer_sched.close()
                multihost.configure_pod(0.0)
                return _pod_degraded_early(e)
            sstep = elected_slice if elected_slice >= 0 else None
        if sstep is not None:
            from distributed_ddpg_tpu.replay.device import merge_slice_states

            slices = ckpt_lib.load_replay_slices(
                config.checkpoint_dir, sstep
            )
            device_replay.load_state_dict(merge_slice_states(slices))
            n_prev = len(slices)
            nprocs = jax.process_count()
            pod_stats.record_slice_adopted(sstep)
            trace.instant("pod_slice_adopted", step=sstep)
            print(
                f"[pod] adopted replay slices from step {sstep} "
                f"(written by {n_prev} process(es), resharded to "
                f"{nprocs})",
                file=sys.stderr, flush=True,
            )
            if nprocs < n_prev:
                pod_stats.record_shrink()
                print(
                    f"[pod] SHRINK: running at {nprocs}/{n_prev} "
                    "processes with the lost peer's replay adopted — "
                    "state degraded until a grow (docs/RESILIENCE.md)",
                    file=sys.stderr, flush=True,
                )
            elif nprocs > n_prev:
                pod_stats.record_grow()
                print(
                    f"[pod] GROW: resharded {n_prev}-writer replay to "
                    f"{nprocs} processes — state healthy",
                    file=sys.stderr, flush=True,
                )
        else:
            print(
                "[pod] no verified replay slice set to adopt at or below "
                f"step {learn_steps}; the buffer resumes empty "
                "(docs/REPLAY_SHARDING.md)",
                file=sys.stderr, flush=True,
            )

    # --- on-device vectorized actors (actors/device_pool.py;
    # docs/DEVICE_ACTORS.md) ---
    # config.actor_backend='device': rollouts run as jitted lax.scan
    # chunks over device_actor_envs vmapped JAX envs and scatter straight
    # into DeviceReplay's HBM ring (insert_device_rows) — no host staging,
    # no transfer-scheduler ingest class. Param refresh is a device-side
    # pointer swap from the learner's LIVE params (set_params — re-swapped
    # every chunk because the learner's dispatch donates the old state).
    # Built AFTER the resume block so the uniform-warmup budget nets out
    # restored progress. The host pool above still runs its num_actors
    # workers (0 = device-only run) and both sources feed the same ring.
    device_pool = None
    if config.actor_backend == "device":
        from distributed_ddpg_tpu.actors.device_pool import DeviceActorPool

        device_pool = DeviceActorPool(
            config,
            mesh=learner.mesh,
            fault=(
                fault_plan.site("devactor", "rollout") if fault_plan else None
            ),
            warmup_offset=env_steps_offset,
        )
        device_pool.set_params(learner.state.actor_params)
        if "devactor_carry" in ckpt_meta:
            # Rollout-state resume (docs/DEVICE_ACTORS.md): restore the
            # pool's env carry + OU state so a resumed device-actor run
            # CONTINUES its episodes instead of restarting E fresh ones
            # (shape-validated; a changed E/env falls back to fresh).
            device_pool.load_carry_state(ckpt_meta["devactor_carry"])
        _beat()  # rollout-program construction survived

    # --- fused training megastep (parallel/megastep.py; docs/FUSED_BEAT.md) ---
    # config.fused_beat: compile rollout + ring scatter + sample + the K
    # learner updates into ONE jitted program per steady-state iteration —
    # the host dispatches a single beat instead of three programs, with
    # zero host round-trips inside it. 'auto' fuses whenever the device-
    # actor + device-replay legs exist, the ratio gates are free-running
    # (a fused beat has a FIXED rollout:learn ratio the gates could not
    # throttle), and the Pallas megakernel is inactive (no slot for it
    # inside a larger program); 'on' forces it (config validation already
    # rejected impossible compositions). Guardrails thread THROUGH the
    # fused program (note_fused_health), so guardrails=True keeps the
    # fast path. Warmup below still uses the standalone rollout dispatch:
    # beats need the learner leg, which warmup by definition lacks.
    megastep = None
    if (
        device_pool is not None
        and use_device_replay
        and config.fused_beat != "off"
        and (
            config.fused_beat == "on"
            or (
                not learner.fused_chunk_active
                and config.max_ingest_ratio == 0.0
                and config.max_learn_ratio == 0.0
            )
        )
    ):
        if config.superstep_beats > 1:
            # Compile-once multi-beat superstep (parallel/superstep.py):
            # B fused beats compose inside one donated-carry fori_loop —
            # one dispatch and ONE host sync point per B iterations.
            # FusedSuperstep is run_beat-shaped (train loop drives it
            # through the same after_chunk), so everything downstream —
            # fused_fields(), guardrail monitor, checkpoint cadence —
            # sees a beat that happens to advance B chunks.
            from distributed_ddpg_tpu.parallel.superstep import FusedSuperstep

            megastep = FusedSuperstep(
                config, learner, device_pool, device_replay
            )
        else:
            from distributed_ddpg_tpu.parallel.megastep import FusedMegastep

            megastep = FusedMegastep(
                config, learner, device_pool, device_replay
            )
        _beat()  # beat-program construction survived

    # Learner d2h pulls ride the scheduler's inline d2h class: absolute
    # priority (no queueing on the hot path), full transfer_* accounting.
    learner.transfer = transfer_sched

    # --- batched policy-inference service (serve/; docs/SERVING.md) ---
    # config.serve_actors: one InferenceServer in this process serves
    # mu(s) to the whole worker fleet through a dynamic batcher
    # (serve_max_batch / serve_max_latency_ms dispatch). Params refresh
    # from the SAME shared-memory broadcast buffer the workers poll
    # (pool.param_source()), batch applies ride the transfer scheduler's
    # `serve` class (byte-fair with ingest/prefetch, never ahead of
    # lockstep), and workers degrade to their local act() mirror when the
    # served path cannot answer (the failure contract the serve chaos
    # tests pin).
    serve_server = None
    serve_front = None
    if config.serve_actors:
        from distributed_ddpg_tpu.serve import InferenceServer, ServeFront

        serve_server = InferenceServer(
            pool.layout,
            spec.action_scale,
            spec.action_offset,
            max_batch=config.serve_max_batch,
            max_latency_s=config.serve_max_latency_ms / 1000.0,
            max_queue=config.serve_queue,
            backend=config.serve_backend,
            param_source=pool.param_source(),
            scheduler=transfer_sched,
            seed=config.seed,
            fault_batcher=(
                fault_plan.site("serve", "batcher") if fault_plan else None
            ),
            fault_dispatch=(
                fault_plan.site("serve", "dispatch") if fault_plan else None
            ),
            # jax backend under TP: serve over the learner's mesh so the
            # policy kernels stay 'model'-sharded at serve time too
            # (parallel/partition.py rule tables; docs/MESH.md). Gated on
            # model_axis > 1 — at 1 the specs are fully replicated and a
            # mesh-wide serve dispatch would only queue behind learner
            # chunks on every device for zero HBM benefit; the
            # single-device apply keeps serving off the training streams.
            mesh=(
                learner.mesh
                if config.serve_backend == "jax" and config.model_axis > 1
                else None
            ),
            # SAC serve head (docs/SERVING.md): the batch apply returns
            # [mean | log_std] rows and each client's action is sampled
            # server-side with a (seed, tenant, request_id) key —
            # serve_actors + sac is a supported pairing since PR 20.
            sac=config.sac,
            log_std_min=config.sac_log_std_min,
            log_std_max=config.sac_log_std_max,
        ).start()
        serve_front = ServeFront(
            serve_server, *pool.serve_channels()
        ).start()

    # --- network serving front (serve/front/; docs/SERVING.md §front) ---
    # front_port/front_http_port > 0: external framed-TCP + HTTP/JSON
    # ingress with versioned snapshots (canary promote) and per-tenant
    # QoS. Each active version runs its own InferenceServer engine fed by
    # the same layout; the learner's live params publish as version
    # "live-0" so the front serves from step one, and later snapshots
    # publish/promote through front_server's API (tools, tests). A bind
    # failure downgrades to a warning — ingress must never kill the run
    # it fronts (the obs/ exporter discipline).
    front_server = None
    if config.serve_actors and (config.front_port or config.front_http_port):
        from distributed_ddpg_tpu.actors.policy import flatten_params
        from distributed_ddpg_tpu.serve.front import FrontServer

        def _make_front_engine():
            return InferenceServer(
                pool.layout,
                spec.action_scale,
                spec.action_offset,
                max_batch=config.serve_max_batch,
                max_latency_s=config.serve_max_latency_ms / 1000.0,
                max_queue=config.serve_queue,
                backend=config.serve_backend,
                seed=config.seed,
                sac=config.sac,
                log_std_min=config.sac_log_std_min,
                log_std_max=config.sac_log_std_max,
            )

        try:
            front_server = FrontServer(
                _make_front_engine,
                port=config.front_port,
                http_port=config.front_http_port or None,
                timeout_s=config.front_timeout_s,
                canary_fraction=config.front_canary_fraction,
                canary_min_requests=config.front_canary_min_requests,
                canary_threshold=config.front_canary_threshold,
                tenants=config.front_tenants,
                default_priority=config.front_default_priority,
                shed_start=config.front_shed_start,
                seed=config.seed,
                fault_accept=(
                    fault_plan.site("front", "accept") if fault_plan else None
                ),
                fault_frame=(
                    fault_plan.site("front", "frame") if fault_plan else None
                ),
                canary_regressions=(
                    fault_plan.front_canary_regressions()
                    if fault_plan
                    else ()
                ),
            )
            front_server.publish(
                "live-0", flatten_params(learner.actor_params_to_host())
            )
            front_server.start()
            print(
                f"[front] serving ingress on tcp:{front_server.port} "
                f"http:{front_server.http_port or '-'} (stable=live-0)",
                file=sys.stderr, flush=True,
            )
        except OSError as e:
            front_server = None
            print(f"[front] ingress disabled (bind failed: {e})",
                  file=sys.stderr, flush=True)

    pool.start(learner.actor_params_to_host())
    _beat()  # first params d2h survived (an observed wedge point)
    log = MetricsLogger(config.log_path, tb_dir=config.tb_dir)

    # --- live telemetry ingress (obs/exporter.py; docs/OBSERVABILITY.md
    # §4) --- config.obs_port > 0: a stdlib HTTP thread serves /metrics
    # (Prometheus text of the latest record per kind + run counters),
    # /healthz (the typed state machine scrapers gate canaries on), and
    # /trace (on-demand flight-recorder export). Started after the logger
    # so the very first scrape already sees the header record; a bind
    # failure (port taken) downgrades to a warning — telemetry must never
    # kill the run it observes.
    if config.obs_port > 0:
        try:
            obs_server = ObsExporter(
                config.obs_port,
                health=health.get(),
                latest_fn=log.latest,
                counters_fn=lambda: {
                    "t_unix_base": log.t_unix_base,
                    "process_index": jax.process_index(),
                    "process_count": jax.process_count(),
                    "preempt": int(preempt.is_set()),
                },
                trace_dir=(config.trace_dir or config.checkpoint_dir or "."),
            ).start()
            print(
                f"[obs] telemetry ingress on :{obs_server.port} "
                "(/metrics /healthz /trace)",
                file=sys.stderr, flush=True,
            )
        except OSError as e:
            obs_server = None
            print(f"[obs] exporter disabled (bind failed: {e})",
                  file=sys.stderr, flush=True)
    if serve_server is not None:
        # Live degraded probe: /healthz reads the serve queue AS OF the
        # scrape, not the last log cadence (serve/server.py overloaded).
        health.get().register_probe("serve_overloaded",
                                    serve_server.overloaded)

    learn_timer, env_timer = Timer(), Timer()
    phases = PhaseTimers()
    saver = ckpt_lib.AsyncSaver()
    last_ckpt = learn_steps

    def recovery_fields() -> Dict[str, int]:
        """Cumulative fault-history counters for every train/final record
        (ISSUE: actor_respawns / actor_quarantined / ckpt_write_retries /
        emergency_ckpt) — `tools.runs summarize` renders them as the run's
        recovery digest."""
        return {
            **pool.recovery_counters(),
            "ckpt_write_retries": saver.write_retries,
            "emergency_ckpt": emergency_ckpt[0],
        }
    eval_policy = NumpyPolicy(
        param_layout(
            spec.obs_dim,
            actor_head_dim(spec.act_dim, config.sac),
            tuple(config.actor_hidden),
        ),
        spec.action_scale,
        spec.action_offset,
        gaussian=config.sac,
    )

    # Periodic eval runs in a background thread on a PARAM SNAPSHOT
    # (SURVEY.md §5; VERDICT.md round-1 Weak #7: inline eval stalled the
    # learner for whole CPU episodes). Only the tiny flat-param copy happens
    # on the hot loop; if an eval is still running when the next cadence
    # fires, the new one is skipped — eval is a diagnostic, the learner has
    # priority.
    eval_thread: Dict[str, object] = {"t": None}

    def start_eval(at_step: int) -> None:
        t = eval_thread["t"]
        if t is not None and t.is_alive():
            return
        with phases.phase("eval_snapshot"):
            flat = flatten_params(learner.actor_params_to_host())

        def _run():
            with trace.span("eval_rollout", step=at_step):
                policy = NumpyPolicy(
                    param_layout(
                        spec.obs_dim,
                        actor_head_dim(spec.act_dim, config.sac),
                        tuple(config.actor_hidden),
                    ),
                    spec.action_scale,
                    spec.action_offset,
                    gaussian=config.sac,
                )
                policy.load_flat(flat)
                log.log(
                    "eval", at_step,
                    eval_return=_eval_numpy(policy, config, spec),
                )

        if config.strict_sync:
            # Lockstep mode: eval runs synchronously so the metrics stream
            # (content AND order) is a pure function of the config.
            _run()
            return
        t = threading.Thread(target=_run, name="eval-worker", daemon=True)
        t.start()
        eval_thread["t"] = t

    profile_cm = (
        jax.profiler.trace(config.profile_dir)
        if config.profile_dir
        else contextlib.nullcontext()
    )

    # One lock serializes every host-replay access: the prefetch thread's
    # sampling vs this thread's inserts and priority updates (SURVEY.md §5
    # 'Race detection' row — the host buffer is the only shared mutable
    # state; everything device-side is functional). The device-replay path
    # has no shared host state at all.
    replay_lock = threading.Lock()

    # --- background lockstep sync_ship (docs/TRANSFER.md) ---
    # With the scheduler attached on a multi-host run, the per-chunk
    # sync_ship collective is issued as a BACKGROUND beat on the lockstep
    # lane (pending counts snapshot at issue time) and the learner only
    # gates its NEXT collective-bearing dispatch on the beat's enqueue —
    # the DCN wait overlaps chunk compute instead of blocking the loop.
    # Warmup keeps synchronous semantics: its loop condition reads the
    # replicated buffer fill, which must reflect the beat on every
    # process at the same iteration or the lockstep loop counts fork.
    bg_sync = (
        transfer_sched is not None
        and is_multi
        and use_device_replay
        and config.sync_ship_background
    )
    pending_beat: Dict[str, object] = {"t": None}
    # Globally-agreed env-step budget cache (multi-host: re-gathered every
    # 10th loop iteration). A cell, not a loop local, so devactor_step's
    # ingest gate can read the replica-identical value from after_chunk.
    cached_global = [0]

    def wait_beat() -> None:
        """Gate: resolve the outstanding background beat (if any) before
        the next collective-bearing dispatch / replica-state read. The
        residual non-overlapped cost lands in t_sync_ship_wait_*. The
        wait is bounded by the CONFIGURED pod deadline (multihost.
        wait_beat_ticket), and a timeout surfaces as typed PodPeerLost —
        the clean-abort path — not a raw TimeoutError."""
        t = pending_beat["t"]
        if t is not None:
            pending_beat["t"] = None
            with phases.phase("sync_ship_wait"):
                multihost.wait_beat_ticket(t)

    def transfer_fields() -> Dict[str, float]:
        """transfer_* observability for the JSONL records: scheduler
        counters + the replay-owned adaptive-coalesce/pool gauges."""
        if transfer_sched is None:
            return {}
        out = dict(transfer_sched.snapshot())
        if use_device_replay and device_replay is not None:
            out.update(device_replay.transfer_snapshot())
        return out

    def pod_fields() -> Dict[str, float]:
        """pod_* resilience counters (metrics.PodStats; docs/RESILIENCE.md
        pod rows) for every train/final record on multi-process runs —
        peer losses, coordinated aborts, the elected resume step, and the
        collective-deadline near-miss/slack telemetry. Single-process
        records stay clean — EXCEPT when elastic events (slice adoption,
        shrink/grow) happened: a pod shrunk to one process must still
        surface its degraded state (docs/RESILIENCE.md)."""
        return (
            pod_stats.snapshot()
            if is_multi or pod_stats.elastic_events()
            else {}
        )

    def guardrail_fields() -> Dict[str, int]:
        """guardrail_* numerical-health counters (metrics.GuardrailStats;
        docs/RESILIENCE.md 'Numerical health') for every train/final
        record when guardrails are armed. Records stay clean otherwise."""
        return gstats.snapshot() if guard_on else {}

    def serve_fields() -> Dict[str, float]:
        """serve_* inference-service counters (metrics.ServeStats;
        docs/SERVING.md) for every train/final record when serving is
        armed — request/batch totals, batch-fill, latency tails, queue
        depth, and the workers' local-act fallback count."""
        if serve_server is None:
            return {}
        out = {**serve_server.snapshot(), **pool.serve_counters()}
        if front_server is not None:
            # front_* + tenant_* ride the same record (metrics.FrontStats
            # / TenantStats; docs/SERVING.md 'Network front').
            out.update(front_server.snapshot())
        return out

    def devactor_fields() -> Dict[str, float]:
        """devactor_* observability (metrics.DevActorStats;
        docs/DEVICE_ACTORS.md) for every train/final record when the
        device-actor backend is armed — interval rows/s, per-chunk
        dispatch tails, episode stats, and the bounded-restart counter.
        Records stay clean on the host backend."""
        return device_pool.snapshot() if device_pool is not None else {}

    def fused_fields() -> Dict[str, float]:
        """fused_* observability (metrics.FusedBeatStats;
        docs/FUSED_BEAT.md) for every train/final record when the fused
        megastep is active — interval beats, grad-steps/s, rows/s, and
        the per-beat dispatch tails. Records stay clean on the
        dispatch-per-phase loop."""
        return megastep.snapshot() if megastep is not None else {}

    mesh_stats = MeshStats(
        learner.mesh.shape["data"], learner.mesh.shape["model"]
    )

    def mesh_fields() -> Dict[str, float]:
        """mesh_* placement facts (metrics.MeshStats; docs/MESH.md) for
        every train/final record: mesh shape plus the measured per-device
        TrainState bytes — the /model_axis HBM claim as an observation of
        the live tree's sharding metadata (zero d2h)."""
        return mesh_stats.snapshot(jax.tree.leaves(learner.state))

    def _guard_quarantine_sources() -> None:
        """Bad-row -> ingest-source attribution: fetch the offending
        replay indices the probe captured (the rare-path d2h), map them
        to the actor slots that produced them, and quarantine slots past
        the repeat-offender threshold through the pool's breaker
        machinery (probing un-quarantines a recovered slot later)."""
        if not use_device_replay or config.guardrail_source_offenses <= 0:
            return
        idx = learner.bad_indices()
        if not len(idx):
            return
        srcs = device_replay.sources_of(idx)
        for s in srcs:
            s = int(s)
            if s < 0:
                continue  # untracked: restored rows, padding, other procs
            guard_src_offenses[s] = guard_src_offenses.get(s, 0) + 1
            if guard_src_offenses[s] >= config.guardrail_source_offenses:
                guard_src_offenses[s] = 0  # a probed comeback re-counts
                if pool.quarantine_source(s, why="numeric"):
                    gstats.record_source_quarantine()

    def _numeric_abort(why: str) -> bool:
        """Rollback impossible (budget exhausted / nothing to restore):
        flag the documented EXIT_NUMERIC abort. Deliberately writes NO
        checkpoint — the live params are presumed poisoned, and the last
        retained pre-divergence checkpoint must stay the newest state a
        resume can find."""
        numeric_failed[0] = True
        trace.instant("numeric_abort", step=learn_steps)
        print(
            f"[guardrail] NUMERIC ABORT at learner step {learn_steps}: "
            f"{why}; exiting {EXIT_NUMERIC} (no checkpoint written — the "
            "last retained pre-divergence checkpoint stands)",
            file=sys.stderr, flush=True,
        )
        return True

    def _rollback_or_abort() -> bool:
        """Automatic rollback-repair (docs/RESILIENCE.md): restore the
        last manifest-valid checkpoint through the PR-4 fallback walk
        (pods elect the step through the PR-6 election so hosts never
        fork), reseed exploration so the repaired run draws a different
        batch stream, optionally back off the LRs for a cooldown, and
        quarantine the diverged-timeline checkpoints. Bounded by
        guardrail_max_rollbacks -> EXIT_NUMERIC. Returns True (the caller
        skips the rest of its chunk work) on both rollback and abort."""
        nonlocal learn_steps, last_ckpt, next_refresh, last_refresh_t
        if gstats.rollbacks >= config.guardrail_max_rollbacks:
            return _numeric_abort(
                f"rollback budget exhausted "
                f"({gstats.rollbacks}/{config.guardrail_max_rollbacks})"
            )
        if not config.checkpoint_dir:
            return _numeric_abort(
                "sustained divergence with no checkpoint_dir to roll "
                "back to"
            )
        wait_beat()  # no collective may be outstanding across the restore
        try:
            saver.wait()  # land (or surface) the in-flight cadence write
        except Exception as e:
            print(
                f"[guardrail] in-flight checkpoint write failed before "
                f"rollback ({e!r}); restoring from the last retained "
                "checkpoint",
                file=sys.stderr, flush=True,
            )
            saver.errors.clear()
        replay_obj = ckpt_replay()
        # Host-replay path: the prefetcher samples under replay_lock, so
        # the restore's load_state_dict must hold it too (the device
        # replay serializes on its own dispatch lock). Chunks already
        # prefetched from the pre-rollback buffer are stale-but-valid
        # replay data and may still be consumed.
        restore_lock = (
            contextlib.nullcontext() if use_device_replay else replay_lock
        )
        ckpt_meta: Dict[str, object] = {}
        try:
            if is_multi:
                # Coordinated rollback step (PR-6 election): every process
                # reaches this point on the same chunk (the trigger inputs
                # are replicated), gathers its manifest-valid steps, and
                # restores the greatest COMMON one. In bg_sync mode the
                # election rides the scheduler's lockstep lane like every
                # other host-initiated collective (docs/TRANSFER.md).
                steps_set = set(ckpt_lib.valid_steps(config.checkpoint_dir))

                def _elect() -> int:
                    return multihost.elect_resume_step(steps_set)

                elected = (
                    transfer_sched.run_ordered(
                        _elect, label="rollback_elect"
                    )
                    if bg_sync
                    else _elect()
                )
                if elected < 0:
                    return _numeric_abort(
                        "no manifest-valid checkpoint is common to every "
                        "process"
                    )
                with restore_lock:
                    restored, step, _env = ckpt_lib.restore(
                        config.checkpoint_dir, learner.state, replay_obj,
                        step=elected, config=config, meta_out=ckpt_meta,
                    )
            else:
                with restore_lock:
                    restored, step, _env = ckpt_lib.restore(
                        config.checkpoint_dir, learner.state, replay_obj,
                        step=None, config=config, meta_out=ckpt_meta,
                    )
        except (FileNotFoundError, RuntimeError) as e:
            return _numeric_abort(f"no restorable checkpoint ({e})")
        learner.state = jax.device_put(restored, learner._state_sharding)
        rolled_from = learn_steps
        learn_steps = step
        last_ckpt = step
        if (
            config.distributional and config.v_support_auto
            and "v_bounds" in ckpt_meta
        ):
            # The restored critic's logits are only meaningful over the
            # atom values it was trained against (resume-path rule).
            learner.set_value_bounds(*ckpt_meta["v_bounds"])
        learner.reset_guard()
        guard_window.clear()
        gstats.record_rollback(step)
        # Reseed exploration: restoring state alone would replay the
        # IDENTICAL sample stream into the identical divergence.
        learner.reseed(0x6A4D + gstats.rollbacks)
        if config.guardrail_lr_backoff < 1.0:
            learner.set_lr_scale(config.guardrail_lr_backoff)
            lr_backoff_since[0] = learn_steps
        if jax.process_index() == 0:
            # Diverged-timeline checkpoints must not win a later resume
            # race (a crash before the next clean save would otherwise
            # restore exactly the state just rolled away from).
            ckpt_lib.discard_above(config.checkpoint_dir, step)
        with phases.phase("refresh"):
            pool.broadcast(learner.actor_params_to_host(), learn_steps)
        if device_pool is not None:
            # The restored state is a fresh tree; swap the rollout's live
            # param pointer so the repaired policy acts immediately.
            device_pool.set_params(learner.state.actor_params)
            if "devactor_carry" in ckpt_meta:
                # Roll the rollout state back with the learner: episodes
                # continue from the restored point, not from E resets.
                device_pool.load_carry_state(ckpt_meta["devactor_carry"])
        next_refresh = learn_steps + config.param_refresh_every
        last_refresh_t = time.perf_counter()
        # The rebuilt programs recompile at the next dispatch — same
        # allowance discipline as a support expansion.
        _grant_all(max(300.0, 2.0 * config.watchdog_s))
        trace.instant("rollback", step=step, rolled_from=rolled_from)
        print(
            f"[guardrail] ROLLBACK #{gstats.rollbacks}: restored "
            f"manifest-valid step {step} (diverged at ~{rolled_from}); "
            "exploration reseeded"
            + (
                f", LR x{config.guardrail_lr_backoff} until "
                f"{config.guardrail_lr_cooldown_steps} clean steps pass"
                if config.guardrail_lr_backoff < 1.0
                else ""
            ),
            file=sys.stderr, flush=True,
        )
        return True

    def _guardrail_monitor() -> bool:
        """Per-chunk health check: read the probe's health word (one tiny
        d2h — the only per-chunk sync guardrails add), difference it into
        the rolling anomaly window, attribute bad rows, and trigger
        rollback / LR-cooldown transitions. Returns True when this chunk's
        remaining work should be skipped (rollback or abort happened) —
        a replicated decision, so pods skip the same beats everywhere."""
        h = learner.poll_health()
        if h is None:
            return False
        delta = gstats.absorb(h)
        if delta["bad_rows"] > 0:
            _guard_quarantine_sources()
        if delta["anomalies"] > 0:
            # first_bad_beat: only present when a multi-beat superstep's
            # stacked health vector localized the first offending beat
            # (learner.poll_health); -1 / absent on scalar polls.
            first_bad = int(h.get("first_bad_beat", -1))
            trace.instant(
                "nan_batch", step=learn_steps,
                anomalies=delta["anomalies"],
                nonfinite=delta["nonfinite"], spikes=delta["spikes"],
                first_bad_beat=first_bad,
            )
            print(
                f"[guardrail] {delta['anomalies']} anomalous learner "
                f"step(s) in the chunk ending at {learn_steps} "
                f"(nonfinite {delta['nonfinite']}, z-spikes "
                f"{delta['spikes']}, bad replay rows {delta['bad_rows']})"
                + (
                    f", first bad beat {first_bad} of the superstep"
                    if first_bad >= 0
                    else ""
                )
                + " — update(s) dropped on device",
                file=sys.stderr, flush=True,
            )
            guard_window.append((learn_steps, delta["anomalies"]))
        # Effective window: never narrower than two sync points. Health
        # lands once per chunk stamped at the chunk's END (once per
        # SUPERSTEP — B chunks — when superstep_beats > 1), so a window
        # below that stride (TPU chunks auto-resolve to 800 vs the
        # 256-step default window) would prune every previous entry
        # immediately and the trigger could only ever see one poll.
        win = max(
            config.guardrail_rollback_window,
            2 * chunk * max(1, config.superstep_beats),
        )
        lo = learn_steps - win
        guard_window[:] = [(s, n) for s, n in guard_window if s > lo]
        handled = False
        if (
            config.guardrail_rollback_k > 0
            and sum(n for _, n in guard_window)
            >= config.guardrail_rollback_k
        ):
            handled = _rollback_or_abort()
        if (
            not handled
            and lr_backoff_since[0] >= 0
            and not guard_window
            and learn_steps - lr_backoff_since[0]
            >= config.guardrail_lr_cooldown_steps
        ):
            learner.set_lr_scale(1.0)
            lr_backoff_since[0] = -1
            gstats.record_lr_cooldown()
            trace.instant("lr_cooldown", step=learn_steps)
            _grant_all(max(300.0, 2.0 * config.watchdog_s))
            print(
                f"[guardrail] LR cooldown complete at step {learn_steps}:"
                " learning rates restored",
                file=sys.stderr, flush=True,
            )
        return handled

    def _poison_packed(packed):
        """numeric:replay:inf@k chaos (faults.py): the k-th ingested row
        (1-based, per process) lands with reward=+inf. Runs on the packed
        wire block just before add_packed, so the poisoned row takes the
        REAL ingest path into replay — the bad-row sample detector and
        its source attribution are exercised end to end."""
        base = ingested_rows[0]
        m = len(packed)
        reward_col = spec.obs_dim + spec.act_dim
        for at in numeric_replay_at:
            if base < at <= base + m:
                packed[at - base - 1, reward_col] = np.inf
                print(
                    f"[chaos] numeric:replay:inf — poisoned ingested row "
                    f"{at} (reward=+inf)",
                    file=sys.stderr, flush=True,
                )
        ingested_rows[0] = base + m
        return packed

    def drain() -> int:
        # Ingest rate limiter (config.max_ingest_ratio): when the budget is
        # exhausted, skip draining — transports fill and workers block,
        # throttling env stepping until the learner catches up. The budget
        # also CAPS each drain (max_rows): after a long gap (first-chunk
        # compile) the rings hold thousands of buffered steps, and draining
        # them all at once would blow straight past the ratio (and possibly
        # total_env_steps) in one call.
        if (
            use_device_replay
            and is_multi
            and device_replay.pending_rows >= 8 * device_replay.block_size
        ):
            # Backpressure: sync_ship only moves min-over-processes blocks,
            # so a host whose actors outpace the slowest host would grow
            # _pending without bound. Stop draining instead — the rings
            # fill and that host's workers block until the pod catches up.
            return 0
        max_rows = None
        if config.max_ingest_ratio > 0.0:
            allowed = (
                max(config.replay_min_size, config.batch_size)
                + config.max_ingest_ratio * learn_steps
            )
            max_rows = int(allowed) - env_steps()
            if max_rows <= 0:
                return 0
        if use_device_replay:
            moved = 0
            track = guard_on and config.guardrail_source_offenses > 0
            for wid, batch in pool.drain_batches(
                max_rows=max_rows, with_sources=True
            ):
                packed = pack_batch_np(batch)
                if numeric_replay_at:
                    packed = _poison_packed(packed)
                device_replay.add_packed(
                    packed, source=wid if track else -1
                )
                moved += len(batch["reward"])
            return moved
        with replay_lock:
            return pool.drain_into(replay, max_rows=max_rows)

    def ingest_once(force_ship: bool = False, sync_wait: bool = True) -> int:
        """One ingest beat: drain actor transports (timed), then — multi-host
        only — the UNCONDITIONAL lockstep sync_ship collective. Every site
        that ingests on the hot path must go through here: the drain gate
        uses process-LOCAL counters, so the collective must not be skippable
        on some processes (replay/device.py sync_ship). Single-process,
        add_packed only stages into the host ring when the async shipper is
        on — the device work happens off this thread (docs/INGEST.md).

        sync_wait=False (steady-state loop, bg_sync mode) issues the
        collective as a background beat and leaves the ticket pending;
        wait_beat() resolves it before the next dispatch. Exactly one
        beat is ever outstanding — each issue waits its predecessor."""
        with phases.phase("ingest"):
            moved = drain()
            env_timer.tick(moved)
        if use_device_replay and is_multi:
            wait_beat()  # at most one outstanding beat (no-op if none)
            if bg_sync and not sync_wait and not force_ship:
                pending_beat["t"] = device_replay.sync_ship_begin()
            else:
                # force / warmup: synchronous semantics (still routed
                # through the lockstep lane in bg mode — replay/device.py
                # sync_ship keeps the collective order identical).
                device_replay.sync_ship(force=force_ship)
        return moved

    def buffer_fill() -> int:
        return len(device_replay) if use_device_replay else len(replay)

    def host_env_steps() -> int:
        """Env steps from the HOST pool only (process-local on multi-host
        — each process drains its own workers)."""
        return env_steps_offset + pool.steps_received

    def env_steps() -> int:
        n = host_env_steps()
        if device_pool is not None:
            # Device-actor steps are GLOBAL production (the rollout is one
            # SPMD program over the whole mesh), identical on every
            # process — added once here, never summed across processes.
            n += device_pool.steps_done
        return n

    def devactor_step(budget_now: Optional[int] = None) -> int:
        """One device-actor rollout chunk (actors/device_pool.py), gated
        by the same ingest-ratio budget the host drain honors. The gate's
        inputs must be replica-identical on multi-host (every process must
        dispatch the same global rollout programs in the same order):
        learn_steps and devactor steps are lockstep, and the env-step
        basis is the caller-provided globally-agreed budget_now when
        available, else the cached global gather (multi-host) or the local
        count (single-process — exact)."""
        if device_pool is None:
            return 0
        if config.max_ingest_ratio > 0.0:
            allowed = min_fill + config.max_ingest_ratio * learn_steps
            basis = budget_now
            if basis is None:
                basis = cached_global[0] if is_multi else env_steps()
            # Any remaining allowance admits ONE chunk (bounded overshoot
            # of rows_per_chunk - 1, the host drain's one-queue-batch
            # semantics): an all-or-nothing gate would wedge warmup
            # whenever rows_per_chunk > min_fill — the allowance could
            # never open because learning hasn't started.
            if basis >= allowed:
                return 0
        if is_multi:
            # Ordering: a queued background sync_ship beat is a global
            # device program; the rollout dispatch must not race its
            # enqueue or per-process device-op order forks (the
            # docs/TRANSFER.md token protocol). No-op when none pending.
            wait_beat()
        with phases.phase("devactor"):
            rows = device_pool.run_chunk(device_replay)
        env_timer.tick(rows)
        return rows

    def global_env_steps() -> int:
        """SUM of env steps over processes, all-gathered so every process
        sees the identical number. The loop condition must be globally
        agreed — a process-local condition would let processes exit at
        different iterations and deadlock the rest on the next collective.
        (total_env_steps is therefore a GLOBAL budget on multi-host runs:
        64 actors across 4 hosts share it.) In bg_sync mode the gather
        runs on the scheduler's lockstep lane: with background sync_ship
        beats possibly queued, NO host-initiated collective may bypass
        the lane or the per-process collective order would fork
        (docs/TRANSFER.md)."""
        from distributed_ddpg_tpu.parallel.multihost import allgather_scalar

        def gather() -> int:
            # Host-pool steps are per-process (summed); device-actor steps
            # are already global (one SPMD rollout over the whole mesh,
            # the same count on every process) — added ONCE, not gathered.
            total = int(allgather_scalar(np.int64(host_env_steps())).sum())
            if device_pool is not None:
                total += device_pool.steps_done
            return total

        if bg_sync:
            return transfer_sched.run_ordered(
                gather, label="env_steps_allgather"
            )
        return gather()

    next_refresh = 0
    last_eval = 0
    last_refresh_t = 0.0
    last_log_t = 0.0
    # Fleet supervision cadence. Monitor must run on WALL CLOCK, not on
    # learner progress: with a rate cap armed, a fully-dead fleet freezes
    # learn_steps between log-cadence multiples, and a monitor called only
    # from the log gate would never run again — no respawns, run wedged
    # (observed live: crash+hang killed both workers during the first
    # compile; the learner sprinted to its cap and froze one chunk short
    # of the next 400-multiple).
    last_monitor_t = 0.0
    support_controller = support_auto.SupportController()

    # --- pod telemetry aggregation (obs/aggregate.py; docs/
    # OBSERVABILITY.md §4) --- multi-process only: on each log cadence
    # every process contributes a milli-scaled int64[4] snapshot (beat
    # time, ingest rate, transfer backlog, wall clock) over the SAME
    # uniform int64 allgather lane the env-step budget rides, and every
    # process computes the identical per-host spread + straggler verdict
    # from the gathered matrix. Rank 0 alone logs the `kind:"pod"`
    # record — the aggregation view is pod-global, one writer suffices.
    pod_agg = None
    if is_multi:
        pod_agg = PodAggregator(
            gather_fn=lambda vec: multihost.allgather_scalar(
                vec, label="pod_obs_gather"
            ),
            stats=pod_stats,
        )

    def after_chunk(out, indices, fused: bool = False,
                    beats: int = 1) -> None:
        # `beats`: how many fused beats the dispatch that produced `out`
        # advanced (a B-beat superstep passes B; everything else 1). All
        # step accounting scales by it; `out` is the FINAL beat's output,
        # which is exactly what B sequential after_chunk calls would have
        # left visible at this point.
        nonlocal learn_steps, last_ckpt, next_refresh, last_eval
        nonlocal last_refresh_t, last_log_t
        learn_steps += chunk * beats
        learn_timer.tick(chunk * beats)
        if device_pool is not None:
            # Device-actor param refresh: pointer swap to the LIVE params,
            # re-done every chunk because the dispatch above DONATED the
            # previous TrainState (the stale tree is deleted — dispatching
            # a rollout against it would raise). Free: no copy, no d2h.
            device_pool.set_params(learner.state.actor_params)
        if guard_on and _guardrail_monitor():
            # Rolled back (or numeric-aborted): this chunk's `out` is
            # moot, the rollback already rebroadcast params, and skipping
            # the rest — including the per-chunk sync_ship beat — is a
            # REPLICATED decision (identical health counters everywhere),
            # so a pod's collective schedule stays aligned.
            return
        # Device rollout BEFORE the ingest beat: in bg_sync mode
        # ingest_once issues a background lockstep beat, and enqueuing the
        # rollout first keeps the per-process device-op order a pure
        # function of the (lockstep) iteration count. A FUSED beat already
        # ran the rollout + insert inside its one program, so only the
        # host-row ingest beat (drains + the unconditional multi-host
        # lockstep/shard_exchange collective) remains.
        if not fused:
            devactor_step()
        else:
            # The beat's in-program rollout produced its rows without a
            # devactor_step dispatch; keep the shared actor-rate meter
            # (actor_steps_per_sec) fed so a healthy fused run never
            # reads as a stalled actor fleet.
            env_timer.tick(device_pool.rows_per_chunk * beats)
        ingest_once(sync_wait=False)

        if config.prioritized and not use_device_replay:
            # Host PER: priorities live in the CPU sum-tree; the device path
            # updates its priority vector inside the fused chunk instead.
            with phases.phase("prio_update"):
                _host_per_update(out, indices)

        # param_refresh_every is in LEARNER STEPS (config.py); refresh on
        # every crossing of a multiple (chunks advance `chunk` steps at a
        # time). The wall-clock floor (param_refresh_interval_s) bounds the
        # refresh's pipeline-sync + d2h cost to a fixed fraction of wall
        # time — without it a per-chunk broadcast serializes the device
        # pipeline (each one waits out the in-flight chunk).
        # strict_sync ignores the wall-clock floors on refresh and logging:
        # both would make the training schedule (which params act, which
        # chunks log) a function of host timing instead of the config,
        # breaking the bit-identical-two-runs contract.
        now = time.perf_counter()
        if learn_steps >= next_refresh and (
            config.strict_sync
            or now - last_refresh_t >= config.param_refresh_interval_s
        ):
            with phases.phase("refresh"):
                pool.broadcast(learner.actor_params_to_host(), learn_steps)
            next_refresh = learn_steps + config.param_refresh_every
            last_refresh_t = time.perf_counter()

        # Cadence = crossing a 50-chunk multiple, not landing on one: a
        # B-beat superstep advances chunk*B steps per call, and B need
        # not divide 50 — the `% == 0` form would skip every cadence
        # whose multiple falls strictly inside a superstep. For beats=1
        # the crossing test reduces to the exact `% == 0` it replaces.
        on_cadence = (
            learn_steps // (50 * chunk)
            != (learn_steps - chunk * beats) // (50 * chunk)
        )
        chunk_metrics = None
        support_metrics = {}
        if on_cadence and config.distributional and config.v_support_auto:
            # Replica-state read below (replay_data_bounds pulls reward
            # columns from the replicated storage): the outstanding
            # background beat must land first so every process reads the
            # identical buffer state at this cadence point.
            wait_beat()
            # Running expansion (ops/support_auto.py): mean_q drifting
            # toward a support edge means the critic is about to saturate
            # (projection clips, mean_q can never cross the edge) — push
            # that edge out geometrically. The check sits OUTSIDE the
            # wall-clock log gate below: the cadence and mean_q (pmean'd,
            # replicated) are identical on every process, so every replica
            # takes the same expansion on the same chunk — a per-process
            # wall-clock gate here would rebuild programs on some replicas
            # only and fork the mesh. Each expansion costs one XLA
            # recompile at the next dispatch, granted to the watchdog like
            # the initial compile.
            with phases.phase("sync"):
                chunk_metrics = learner.metrics_to_host(out)
            # data_bounds_fn: re-derive the rule-1 bound from the replay's
            # CURRENT rewards so a diverging critic can't drag the support
            # up (support_auto module docstring, seed-1 incident). The
            # reward column is replica-identical (replay is replicated /
            # lockstep-shipped across processes), so every replica still
            # takes the same decision and the mesh cannot fork.
            _support_source = device_replay if use_device_replay else replay
            grown = support_controller.check(
                learner.config.v_min,
                learner.config.v_max,
                chunk_metrics["mean_q"],
                learn_steps,
                data_bounds_fn=lambda: support_auto.replay_data_bounds(
                    _support_source, config.gamma, config.n_step
                ),
            )
            if grown is not None:
                learner.set_value_bounds(*grown)
                _grant_all(max(300.0, 2.0 * config.watchdog_s))
                print(
                    f"auto C51 support expanded to "
                    f"[{grown[0]:.1f}, {grown[1]:.1f}] "
                    f"(mean_q {chunk_metrics['mean_q']:.1f})"
                )
            support_metrics = dict(
                v_min=learner.config.v_min,
                v_max=learner.config.v_max,
                support_refusals=support_controller.refusals,
            )

        if on_cadence:
            # Reversible degraded conditions re-sampled every cadence
            # (obs/health.py note() both raises and clears): a pod that
            # shrank back to strength or a quarantine that lifted takes
            # /healthz back to `healthy` at the next cadence.
            health.get().note("pod_state_degraded", pod_stats.degraded)
            if guard_on:
                health.get().note(
                    "guardrail_quarantine", gstats.source_quarantines > 0
                )
        if on_cadence and pod_agg is not None:
            # Cross-host aggregation gather. Sits OUTSIDE the wall-clock
            # log gate below: that gate reads per-process wall time, so
            # processes disagree on it, and a collective issued under it
            # would fork the pod's collective order. Here the cadence
            # (replica-identical learn_steps) is the only gate. bg_sync
            # runs ride the scheduler's lockstep lane like every other
            # host-initiated collective (docs/TRANSFER.md).
            def _pod_collect():
                return pod_agg.collect(
                    beats=learn_steps // chunk,
                    ingest_rows=host_env_steps(),
                    transfer_backlog=(
                        sum(transfer_sched.queue_depths().values())
                        if transfer_sched is not None
                        else 0
                    ),
                )

            with phases.phase("pod_obs"):
                pod_record = (
                    transfer_sched.run_ordered(
                        _pod_collect, label="pod_obs_allgather"
                    )
                    if bg_sync
                    else _pod_collect()
                )
            if pod_record is not None and jax.process_index() == 0:
                log.log("pod", env_steps(), **pod_record)

        if on_cadence and (config.strict_sync or now - last_log_t >= 1.0):
            last_log_t = now
            pool.monitor()
            episodes = pool.episode_stats()
            mean_ret = (
                float(np.mean([e[1] for e in episodes])) if episodes else None
            )
            if chunk_metrics is None:
                with phases.phase("sync"):
                    chunk_metrics = learner.metrics_to_host(out)
            log.log(
                "train", env_steps(),
                learner_steps=learn_steps,
                learner_steps_per_sec=learn_timer.rate(),
                actor_steps_per_sec=env_timer.rate(),
                buffer_fill=buffer_fill(),
                episode_return=mean_ret,
                **pool.staleness(),
                **recovery_fields(),
                **chunk_metrics,
                **support_metrics,
                **phases.snapshot(),
                # Ingest pipeline observability (replay/device.py
                # IngestStats): rows/sec shipped to HBM, coalesce factor,
                # staging-queue depth, producer stall time.
                **(
                    device_replay.ingest_snapshot()
                    if use_device_replay
                    else {}
                ),
                # Transfer-scheduler observability (docs/TRANSFER.md):
                # per-class dispatches/bytes/tails, queue depths, the
                # adaptive-coalesce trajectory, restart count.
                **transfer_fields(),
                # Pod resilience (docs/RESILIENCE.md pod rows).
                **pod_fields(),
                # Numerical health (docs/RESILIENCE.md; guardrails.py).
                **guardrail_fields(),
                # Inference serving (docs/SERVING.md; serve/).
                **serve_fields(),
                # Device-actor rollouts (docs/DEVICE_ACTORS.md).
                **devactor_fields(),
                # Fused megastep beats (docs/FUSED_BEAT.md).
                **fused_fields(),
                # Mesh placement facts (docs/MESH.md).
                **mesh_fields(),
            )

        # Periodic eval (SURVEY.md §2 #1 'periodic eval & checkpoint'):
        # deterministic CPU rollout of a param snapshot in a background
        # thread (start_eval above) — the learner keeps dispatching.
        if config.eval_every and env_steps() - last_eval >= config.eval_every:
            start_eval(env_steps())
            last_eval = env_steps()

        if (
            config.checkpoint_dir
            and learn_steps - last_ckpt >= config.checkpoint_every
        ):
            with phases.phase("ckpt"):
                # Learner state is replicated across processes, so ONE
                # writer suffices for the orbax tree (and shared-FS
                # writes must not collide). Async: only the HBM->host
                # snapshot happens here; the disk write runs on the
                # saver's thread (checkpoint.py AsyncSaver).
                if jax.process_index() == 0:
                    saver.save_async(
                        config.checkpoint_dir, learn_steps, learner.state,
                        ckpt_replay(), config,
                        env_steps=env_steps(),
                        devactor_state=(
                            device_pool.carry_state_dict()
                            if device_pool is not None
                            else None
                        ),
                        v_bounds=(
                            (learner.config.v_min, learner.config.v_max)
                            if config.distributional and config.v_support_auto
                            else None
                        ),
                        keep=config.checkpoint_keep,
                        retries=config.ckpt_write_retries,
                        backoff_s=config.ckpt_retry_backoff_s,
                        fault=ckpt_fault,
                    )
                # Sharded replay is NOT replicated: every shard owner
                # writes its slice at the same cadence step (all-writer,
                # docs/REPLAY_SHARDING.md). learn_steps is lockstep-
                # identical, so the slice sets line up by construction.
                write_replay_slices(learn_steps)
            last_ckpt = learn_steps

    def _host_per_update(out, indices) -> None:
        tds = np.asarray(out.td_errors).reshape(-1)
        with replay_lock:
            replay.update_priorities(indices.reshape(-1), tds)
            frac = min(1.0, env_steps() / config.total_env_steps)
            replay.set_beta(
                config.per_beta
                + frac * (config.per_beta_final - config.per_beta)
            )

    def _emergency_checkpoint() -> None:
        # --- emergency checkpoint (preemption + pod-abort contract) ---
        # One save OFF the hot loop, then a normal teardown. The
        # in-flight cadence write (if any) lands first; its failure
        # must not cost the emergency save. Same-step dedupe: if the
        # cadence already wrote exactly learn_steps, that checkpoint
        # IS the resumable state. Ordinarily only process 0 writes
        # (state is replicated); on a POD abort every survivor writes
        # one — process 0 into checkpoint_dir, the rest into their
        # proc<k> subdir (pod_ckpt_dir) — so a relaunched pod can
        # elect a common step even when each host keeps its own disk.
        _beat()
        try:
            saver.wait()
        except Exception as e:
            print(
                f"[train] in-flight checkpoint write failed during "
                f"preemption ({e!r}); writing the emergency "
                "checkpoint anyway",
                file=sys.stderr, flush=True,
            )
            saver.errors.clear()
        i_write = jax.process_index() == 0 or pod_lost[0] is not None
        my_dir = (
            config.checkpoint_dir if jax.process_index() == 0 else pod_ckpt_dir
        )
        # Sharded replay: every process (not just the learner-tree
        # writer) persists its slice — into the SHARED dir, where the
        # per-proc filenames cannot collide. On a pod abort the dead
        # peer's slice is of course absent, so THIS step's set stays
        # incomplete; adoption falls back to the last cadence step where
        # all writers landed (docs/REPLAY_SHARDING.md).
        write_replay_slices(learn_steps)
        if config.checkpoint_dir and i_write:
            if ckpt_lib.latest_step(my_dir) != learn_steps:
                with phases.phase("ckpt"):
                    ckpt_lib.save(
                        my_dir, learn_steps,
                        learner.state,
                        ckpt_replay(),
                        config,
                        env_steps=env_steps(),
                        devactor_state=(
                            device_pool.carry_state_dict()
                            if device_pool is not None
                            else None
                        ),
                        v_bounds=(
                            (learner.config.v_min, learner.config.v_max)
                            if config.distributional
                            and config.v_support_auto
                            else None
                        ),
                        keep=config.checkpoint_keep,
                        retries=config.ckpt_write_retries,
                        backoff_s=config.ckpt_retry_backoff_s,
                        fault=ckpt_fault,
                    )
            emergency_ckpt[0] = 1
            trace.instant("emergency_ckpt", step=learn_steps)
            print(
                f"[train] emergency checkpoint at learner step "
                f"{learn_steps} (env step {env_steps()}) — resumable",
                file=sys.stderr, flush=True,
            )

    prefetch = None
    try:
        # --- warmup: fill replay to the learning threshold (min_fill) ---
        # The per-iteration _beat below keeps the watchdog quiet even when
        # ingest_once() moves nothing, so a total actor-side stall (workers
        # heartbeating but producing no experience — e.g. every env wedged)
        # would otherwise burn the whole wall-clock budget unseen. The
        # secondary deadline catches that: no rows for 10x watchdog_s is a
        # loud RuntimeError (normal teardown runs — the learner thread
        # itself is healthy here, unlike the device wedges the watchdog's
        # os._exit exists for).
        stall_deadline = (
            10.0 * config.watchdog_s if config.watchdog_s > 0 else 0.0
        )
        last_moved_t = time.monotonic()

        def _check_actor_stall(where: str) -> None:
            if stall_deadline and time.monotonic() - last_moved_t > stall_deadline:
                raise RuntimeError(
                    f"{where}: no experience ingested for "
                    f"{stall_deadline:.0f}s (10x watchdog_s) with the "
                    "learner thread healthy — actor-side stall; aborting "
                    "instead of burning the wall-clock budget"
                )

        warm_it = 0
        while buffer_fill() < min_fill and not preempt.is_set():
            # Lockstep warmup ingest: loop count is driven by the
            # globally-replicated buffer size and `warm_it` advances
            # identically everywhere, so every process calls sync_ship
            # (inside ingest_once) the same number of times. Periodic
            # force pads a block from sub-block trickles so slow actors
            # still cross the threshold.
            moved = ingest_once(force_ship=(warm_it % 20 == 19))
            moved += devactor_step()
            _beat()
            pool.monitor()
            if (
                use_device_replay
                and not is_multi
                and buffer_fill() + device_replay.pending_rows >= min_fill
            ):
                # NOT gated on `moved`: this check races the async
                # shipper — at the instant it ships a block, the rows are
                # already popped from the ring (pending drops) but the
                # insert hasn't updated size yet (fill unchanged), so the
                # sum transiently under-counts. With a drain cap
                # (max_ingest_ratio) the crossing iteration can be the
                # LAST one with moved > 0, and a moved-gated check that
                # lost the race would never re-fire: sub-block remainder
                # rows sit staged forever while drains return 0 — a
                # warmup livelock (observed: fill 1024 + pending 476
                # against min_fill 1500, wedged). Re-evaluating every
                # iteration self-heals; flush() is idempotent-cheap when
                # there is nothing staged, and the loop exits as soon as
                # the fill crosses, so at most one padded ship happens.
                device_replay.flush()
            if moved:
                last_moved_t = time.monotonic()
            else:
                _check_actor_stall("warmup")
                time.sleep(0.05)
            warm_it += 1

        trace.instant("warmup_done", buffer_fill=buffer_fill())
        if use_device_replay and is_multi and fault_plan:
            # Pod chaos site (pod:<proc>:kill|hang@beat): armed at the
            # warmup/steady boundary — a lockstep point — so `@beat`
            # counts STEADY-STATE beats (one per learner chunk, the same
            # ordinal on every process) instead of depending on how many
            # wall-clock-paced warmup iterations actor startup needed.
            device_replay.arm_pod_fault(
                fault_plan.pod_site(jax.process_index())
            )
        if (
            config.distributional and learner.config.v_support_auto
            and not preempt.is_set()  # partial warmup: no stats to size from
        ):
            # C51 auto-support (ops/support_auto.py): size [v_min, v_max]
            # from the warmup replay's (n-step) reward statistics. Gated on
            # the LEARNER's config: a resume that restored checkpointed
            # bounds above has already resolved them, and re-deriving would
            # reinterpret the restored critic. Must happen before the first
            # dispatch: jit is lazy, so the rebuild costs no extra compile.
            source = device_replay if use_device_replay else replay
            v_lo, v_hi = support_auto.replay_data_bounds(
                source, config.gamma, config.n_step
            )
            learner.set_value_bounds(v_lo, v_hi)
            print(
                f"auto C51 support: [{v_lo:.1f}, {v_hi:.1f}] from warmup "
                "reward statistics"
            )

        prefetch = None
        if not use_device_replay and not preempt.is_set():
            prefetch = ChunkPrefetcher(
                replay, learner.put_chunk, learner.global_batch, chunk,
                depth=config.prefetch_depth, lock=replay_lock,
                fault=(
                    fault_plan.site("prefetch", "sample")
                    if fault_plan else None
                ),
                # Single-process only: multi-host put_chunk is itself a
                # cross-process device op, and only the lockstep lane may
                # issue those off the learner thread (docs/TRANSFER.md).
                scheduler=(transfer_sched if not is_multi else None),
            ).start()

        # Rates below report the steady state, not compile/warmup time.
        learn_timer.reset()
        env_timer.reset()

        # The first dispatch includes the full XLA compile of the chunk
        # program (~20-40s single-chip; larger nets / multihost meshes can
        # take minutes) — grant the watchdog a one-time extra allowance so
        # a slow compile isn't killed as a false stall (same exit 70 as a
        # real wedge). Consumed by the first post-compile beat; steady-state
        # iterations run on the plain watchdog_s window.
        _grant_all(max(300.0, 2.0 * config.watchdog_s))

        with profile_cm:
            # Multi-host: the global budget is re-gathered every 10th
            # iteration, not every chunk — the cadence is deterministic in
            # the (lockstep) iteration count, so processes stay in step,
            # and the hot loop pays one budget collective per 10 chunks
            # instead of one per chunk. Overshoot is bounded by 10 chunks
            # of ingest — noise against BASELINE-scale budgets.
            it = 0
            last_budget = -1
            first_dispatch_done = False
            while not preempt.is_set() and not numeric_failed[0]:
                _beat()
                # Wall-clock fleet supervision (see last_monitor_t note):
                # every iteration reaches this, including the rate-capped
                # ingest spin below — a dead fleet respawns even when the
                # learner cannot advance.
                if time.monotonic() - last_monitor_t >= 1.0:
                    last_monitor_t = time.monotonic()
                    pool.monitor()
                if is_multi:
                    if it % 10 == 0:
                        cached_global[0] = global_env_steps()
                    budget_now = cached_global[0]
                else:
                    budget_now = env_steps()
                if budget_now >= config.total_env_steps and learn_steps > 0:
                    trace.instant(
                        "budget_met", env_steps=budget_now,
                        learn_steps=learn_steps,
                    )
                    # `learn_steps > 0` guards the degenerate exit where fast
                    # actors deliver the entire env-step budget during warmup
                    # (max_ingest_ratio=0 = free ingest): a run that has met
                    # replay_min_size must take at least one gradient chunk
                    # before the budget break is honored, or it would report
                    # success with learner_steps=0. One chunk later the break
                    # fires; learn_steps advances in lockstep on multi-host,
                    # so every process exits on the same iteration.
                    break
                # Actor-stall coverage for EVERY post-warmup path (the
                # per-iteration _beat keeps the watchdog quiet whether or
                # not env steps arrive): with the default max_learn_ratio=0
                # the loop below dispatches forever on stale replay if all
                # workers wedge, and with a cap it spins in the ingest
                # branch — either way env-step progress is the one signal
                # that actors are alive, so it drives the stall clock.
                # AFTER the budget break: a budget already met during
                # warmup is a finishing run, not a stall. The first
                # dispatch resets the clock below (its XLA compile gets
                # the same allowance the watchdog grant gives it — a
                # compile longer than the deadline must not read as a
                # stalled actor fleet).
                if budget_now > last_budget:
                    last_budget = budget_now
                    last_moved_t = time.monotonic()
                else:
                    _check_actor_stall("train loop")
                if config.max_learn_ratio > 0.0 and learn_steps > 0 and (
                    learn_steps + chunk
                    > max(config.replay_min_size, config.batch_size)
                    + config.max_learn_ratio * budget_now
                ):
                    # Learner-rate cap (config.max_learn_ratio): ahead of
                    # the allowance — ingest instead of dispatching until
                    # env steps catch up. The decision uses budget_now,
                    # which is globally agreed on multi-host, so every
                    # process skips the same iterations and the SPMD
                    # collective schedule stays aligned (same reasoning as
                    # the loop-exit condition above).
                    moved_now = ingest_once(sync_wait=False)
                    moved_now += devactor_step(budget_now)
                    if not moved_now:
                        time.sleep(0.002)
                    it += 1
                    continue
                # Dispatch gate (bg_sync): the previous background beat
                # must be ENQUEUED before the next chunk dispatch so the
                # per-process device-op order stays identical everywhere
                # (docs/TRANSFER.md token protocol). No-op otherwise.
                wait_beat()
                if use_device_replay:
                    if megastep is not None and config.superstep_beats > 1:
                        # Multi-beat superstep (docs/FUSED_BEAT.md): B
                        # fused beats as ONE fori_loop program. The PER
                        # beta anneal rides in as a host-precomputed
                        # float32[B] vector reproducing the per-beat
                        # sequential schedule (globally-agreed budget_now
                        # so replicas never fork; rows advance
                        # rows_per_chunk per in-loop beat).
                        betas = None
                        if config.prioritized:
                            from distributed_ddpg_tpu.parallel.superstep \
                                import per_beat_betas

                            betas = per_beat_betas(
                                config, budget_now, megastep.beats,
                                device_pool.rows_per_chunk,
                            )
                        with phases.phase("dispatch"):
                            out = megastep.run_superstep(betas=betas)
                        after_chunk(
                            out, None, fused=True, beats=megastep.beats
                        )
                    elif megastep is not None:
                        # Fused megastep (docs/FUSED_BEAT.md): rollout +
                        # scatter + sample + K updates in ONE program. The
                        # PER beta anneal rides in as a scalar exactly like
                        # the unfused dispatch (globally-agreed budget_now
                        # so replicas never fork).
                        beta = None
                        if config.prioritized:
                            frac = min(1.0, budget_now / config.total_env_steps)
                            beta = config.per_beta + frac * (
                                config.per_beta_final - config.per_beta
                            )
                        with phases.phase("dispatch"):
                            out = megastep.run_beat(beta=beta)
                        # NOT the shared after_chunk call below: the beat
                        # already ran the rollout+insert, and running
                        # after_chunk twice would double every cadence.
                        after_chunk(out, None, fused=True)
                    elif config.prioritized:
                        # beta anneal rides in as a scalar arg. It must be
                        # computed from a globally-identical value
                        # (budget_now — cached global on multi-host), NOT
                        # process-local env steps: beta feeds the replicated
                        # IS weights, so divergent betas would fork the
                        # replicas.
                        frac = min(1.0, budget_now / config.total_env_steps)
                        beta = config.per_beta + frac * (
                            config.per_beta_final - config.per_beta
                        )
                        with phases.phase("dispatch"):
                            out = learner.run_sample_chunk_per(
                                device_replay, beta
                            )
                        after_chunk(out, None)
                    else:
                        with phases.phase("dispatch"):
                            out = learner.run_sample_chunk(device_replay)
                        after_chunk(out, None)
                else:
                    with phases.phase("sample_wait"):
                        device_chunk, indices = prefetch.next()
                    with phases.phase("dispatch"):
                        out = learner.run_chunk_async(device_chunk)
                    after_chunk(out, indices)
                if not first_dispatch_done:
                    # The first dispatch blocks on the chunk program's XLA
                    # compile (minutes on big meshes); that time must not
                    # count against the actor-stall clock.
                    first_dispatch_done = True
                    last_moved_t = time.monotonic()
                it += 1

        if prefetch is not None:
            prefetch.stop()

        if preempt.is_set():
            _emergency_checkpoint()
    except multihost.PodPeerLost as e:
        # --- coordinated clean pod abort (docs/RESILIENCE.md pod rows) ---
        # A peer process died or hung mid-collective: every further
        # collective would block (or fork) the pod. Each survivor fails
        # the transfer scheduler's pending tickets (close() fails queued
        # work BEFORE the join — a queued lockstep beat must never fire
        # against a degraded pod), takes one emergency checkpoint through
        # the SIGTERM path's machinery, and exits EXIT_POD_DEGRADED so
        # the driver relaunches the whole pod; the resume election then
        # restores one common step everywhere.
        pod_lost[0] = e
        pod_stats.record_abort()
        preempt.set()  # downstream teardown follows the preemption shape
        _grant_all(max(300.0, 2.0 * config.watchdog_s))
        trace.instant("pod_abort", step=learn_steps)
        print(
            f"[train] pod peer lost: {e}; coordinated clean abort — "
            f"draining transfers, emergency checkpoint, exit "
            f"{EXIT_POD_DEGRADED}",
            file=sys.stderr, flush=True,
        )
        # The outstanding beat ticket (if any) is already failed or
        # failing under the same deadline — never re-wait it.
        pending_beat["t"] = None
        if prefetch is not None:
            try:
                prefetch.stop()
            except Exception:
                pass
        if transfer_sched is not None:
            transfer_sched.close()
        _emergency_checkpoint()
        # Shrink-readiness (docs/RESILIENCE.md state machine): with a
        # complete, digest-verified slice set on disk the dead peer's
        # replay is recoverable — exit EXIT_POD_SHRINK (78) so the
        # driver knows it may relaunch at N-1 instead of waiting for
        # the lost host. No set -> the existing 76 contract.
        pod_shrink_ready[0] = _slices_adoptable()
        if pod_shrink_ready[0]:
            print(
                f"[train] complete replay slice set on disk — "
                f"shrink-ready, exiting {EXIT_POD_SHRINK} (relaunch at "
                "any process count adopts it)",
                file=sys.stderr, flush=True,
            )
    finally:
        if prev_sigterm is not None:
            try:
                import signal as _signal

                _signal.signal(_signal.SIGTERM, prev_sigterm)
            except (ValueError, TypeError):
                pass
        _beat()  # each teardown stage gets a fresh watchdog allowance
        try:
            # Land the outstanding background sync_ship beat (every
            # process issued the same beats, so every process waits here)
            # before tearing down the machinery under it.
            wait_beat()
        except multihost.PodPeerLost as e:
            # A peer died between the loop's last gate and teardown:
            # record the degradation so the exit code still says 76, but
            # keep tearing down (the abort machinery already ran or the
            # run was otherwise complete).
            if pod_lost[0] is None:
                pod_lost[0] = e
                pod_stats.record_abort()
        except Exception:
            pass  # a failing beat must not mask the primary error
        pool.stop()
        _beat()
        if front_server is not None:
            # Network ingress first: stop accepting external traffic
            # before the in-process serving machinery flushes; in-flight
            # requests complete (FrontServer.stop closes every version
            # engine, each draining its batcher).
            front_server.stop()
        if serve_front is not None:
            # After the workers: no new requests can arrive. The front
            # stops first (nothing new enters the batcher), then the
            # server flushes — every accepted request completes before
            # the machinery under it (scheduler) is torn down.
            serve_front.stop()
        if serve_server is not None:
            serve_server.close()
        if use_device_replay and device_replay is not None:
            # Stop the async ingest shipper; add_packed falls back to
            # inline shipping for any teardown stragglers.
            device_replay.close()
        if transfer_sched is not None:
            # After the replay detaches: pending tickets fail loudly into
            # their waiters (a still-running prefetch worker dies with
            # TransferError instead of hanging).
            transfer_sched.close()
        # Land the in-flight checkpoint write (and surface its error, if
        # any) before callers read the directory back.
        saver.wait()
        _beat()
        t = eval_thread["t"]
        if t is not None:
            t.join(timeout=_EVAL_JOIN_S)
        if obs_server is not None and pod_lost[0] is None and not preempt.is_set():
            # Clean exits stop the ingress; a pod abort OR a preemption
            # deliberately keeps it serving — /healthz must answer through
            # the teardown window (pod_degraded_exit's rank-0 linger, the
            # post-SIGTERM checkpoint flush) so a supervisor can scrape
            # the draining verdict before the process disappears. The
            # server thread is a daemon; process exit reaps it.
            obs_server.stop()
        if is_multi:
            # Disarm the module-level pod deadline: a later single-process
            # train in the same interpreter must keep the zero-overhead
            # short-circuit path.
            multihost.configure_pod(0.0)

    # --- final eval with the trained policy (CPU, deterministic) ---
    # Skipped under preemption: the contract is "checkpoint and get out";
    # whole CPU eval episodes would hold the exit for seconds.
    _beat()
    if preempt.is_set() or numeric_failed[0]:
        # Preemption: "checkpoint and get out". Numeric abort: the params
        # are presumed poisoned — an eval would score garbage.
        final_return = None
    else:
        eval_policy.load_flat(flatten_params(learner.actor_params_to_host()))
        final_return = _eval_numpy(eval_policy, config, spec)
    rate = learn_timer.rate()
    # ONE serve/devactor snapshot shared by the final record and the
    # returned summary: both stats reset their interval reservoirs at
    # snapshot, so a second call would report zeroed tails.
    serve_final = serve_fields()
    devactor_final = devactor_fields()
    fused_final = fused_fields()
    log.log(
        "final", env_steps(),
        learner_steps=learn_steps,
        learner_steps_per_sec=rate,
        final_return=final_return,
        **recovery_fields(),
        # Ingest + replay-placement families (replay/device.py): short
        # runs can finish inside one log cadence, and the final record is
        # where tools.runs reads the placement facts (shard count,
        # bytes/row) regardless.
        **(
            device_replay.ingest_snapshot()
            if use_device_replay and device_replay is not None
            else {}
        ),
        **phases.snapshot(),
        **transfer_fields(),
        **pod_fields(),
        **guardrail_fields(),
        **serve_final,
        **devactor_final,
        **fused_final,
        **mesh_fields(),
    )
    log.close()
    # Checksum of the final actor params: lets determinism tests (and the
    # multi-host parity test — SPMD replicas must agree bit-for-bit)
    # compare end states without plumbing the whole state out.
    checksum = float(
        sum(
            np.abs(np.asarray(leaf)).sum()
            for leaf in jax.tree.leaves(learner.actor_params_to_host())
        )
    )
    return {
        "learner_steps_per_sec": rate,
        "learner_steps": learn_steps,
        "final_return": final_return,
        "param_checksum": checksum,
        # A pod abort reuses the preemption machinery but is its OWN
        # documented exit (76 vs 75) — report exactly one of the two.
        "preempted": preempt.is_set() and pod_lost[0] is None,
        "pod_degraded": pod_lost[0] is not None,
        # Elastic-shrink readiness: a pod abort with a complete replay
        # slice set on disk exits 78 (relaunch smaller adopts it), 76
        # otherwise (docs/RESILIENCE.md).
        "pod_shrink_ready": bool(pod_shrink_ready[0]),
        # Numeric-health abort (EXIT_NUMERIC=77): guardrails exhausted the
        # rollback budget or had nothing to restore.
        "numeric_failed": numeric_failed[0],
        **recovery_fields(),
        **pod_fields(),
        **guardrail_fields(),
        **serve_final,
        **devactor_final,
        **fused_final,
        # Dispatch-gating fact for tests/operators: True = the fused
        # megastep carried the steady-state loop (docs/FUSED_BEAT.md).
        "fused_beat_active": megastep is not None,
    }


def pod_degraded_exit(linger_s: float = 10.0, code: int = EXIT_POD_DEGRADED) -> None:
    """Exit `code` (EXIT_POD_DEGRADED, or EXIT_POD_SHRINK when the run
    reported pod_shrink_ready) the SAFE way after a coordinated pod abort
    (train_jax returned pod_degraded=True; emergency checkpoint and logs
    already landed).

    os._exit, not sys.exit, for the same reason the stall watchdog uses
    it: the abandoned collective thread is still blocked inside the
    transport, and normal interpreter teardown destroys the distributed
    runtime under it — the process then dies by std::terminate/SIGABRT
    instead of the documented code (observed on the gloo chaos harness).

    Process 0 lingers briefly first: it hosts the coordination service,
    and its exit closes every peer's error-polling RPC — which the XLA
    client answers with LOG(FATAL), terminating survivors still writing
    THEIR emergency checkpoints. The aborts start near-simultaneously
    (same missed collective), so a short linger lets the peers finish."""
    drain_for_pod_exit(code)
    try:
        import jax

        if jax.process_count() > 1 and jax.process_index() == 0:
            time.sleep(linger_s)
    except Exception:
        pass
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


def drain_for_pod_exit(code: int = EXIT_POD_DEGRADED) -> None:
    """Latch /healthz into `draining`, carrying the pod-abort verdict.

    The abort is terminal from here — the ingress (left serving through
    the linger window by train_jax's teardown) must answer a supervisor's
    scrape with "winding down, and THIS is why" (state=draining, the
    degraded reasons — e.g. pod_peer_lost — preserved in the snapshot),
    not a degraded-looking process it might still route around. drain()
    is first-wins, so a SIGTERM that already latched `preempted` keeps
    its attribution. Factored out of pod_degraded_exit so the linger
    contract is testable without os._exit (tests/test_obs.py)."""
    try:
        from distributed_ddpg_tpu.obs import health

        _state, reasons = health.get().state()
        health.get().drain(
            "; ".join(reasons) if reasons else f"pod abort (exit {code})"
        )
    except Exception:
        pass  # diagnostics must never block the documented exit


def _eval_numpy(policy, config: DDPGConfig, spec, episodes: Optional[int] = None) -> float:
    env = make(config.env_id, seed=config.seed + 777)
    returns = []
    for ep in range(episodes or config.eval_episodes):
        obs, _ = env.reset(seed=config.seed + 777 + ep)
        done, total = False, 0.0
        while not done:
            action = np.clip(policy(obs)[0], spec.action_low, spec.action_high)
            obs, r, terminated, truncated, _ = env.step(action)
            total += r
            done = terminated or truncated
        returns.append(total)
    return float(np.mean(returns))


def main(argv=None) -> None:
    from distributed_ddpg_tpu.platform_util import honor_jax_platforms

    honor_jax_platforms()
    config = DDPGConfig.from_flags(argv if argv is not None else sys.argv[1:])
    summary = train(config)
    print({k: round(v, 3) if isinstance(v, float) else v for k, v in summary.items()})
    if summary.get("pod_degraded"):
        pod_degraded_exit(
            code=(
                EXIT_POD_SHRINK
                if summary.get("pod_shrink_ready")
                else EXIT_POD_DEGRADED
            )
        )
    if summary.get("numeric_failed"):
        # Documented numeric-health abort: the guardrails could not repair
        # a sustained divergence. Distinct from 75/76 (those are "relaunch
        # and resume"): a driver should inspect the guardrail_* counters
        # before pouring more compute onto a diverging config.
        sys.exit(EXIT_NUMERIC)
    if summary.get("preempted"):
        # The documented "preempted, resumable" exit — a driver retries
        # the run with the same checkpoint_dir instead of diagnosing it.
        sys.exit(EXIT_PREEMPTED)


if __name__ == "__main__":
    main()
