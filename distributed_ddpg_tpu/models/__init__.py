from distributed_ddpg_tpu.models.mlp import (
    actor_apply,
    actor_init,
    critic_apply,
    critic_init,
    mlp_init,
)

__all__ = ["actor_init", "actor_apply", "critic_init", "critic_apply", "mlp_init"]
