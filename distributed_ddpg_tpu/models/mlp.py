"""Actor/critic MLPs as plain functional pytrees.

Capability parity with the reference's `actor_network.py` / `critic_network.py`
(SURVEY.md §2 #3/#4 — mount empty, spec from [PAPER]/[DRIVER] rows):

- Actor mu(s; theta): MLP with relu hiddens, tanh-squashed final layer scaled
  to the action bounds.
- Critic Q(s, a; phi): MLP where the action enters at the SECOND layer
  (classic DDPG, arXiv 1509.02971 §7).
- Init: hidden layers ~ U(-1/sqrt(fan_in), +1/sqrt(fan_in)); final layers
  ~ U(-3e-3, 3e-3) so initial policy outputs / Q values are near zero [PAPER].

Design notes (TPU-first, not a port):
- Params are plain pytrees (tuple of {"w","b"} dicts) — no framework objects —
  so the same tree feeds the jitted TPU path, the numpy `native` backend
  (bit-comparability oracle, BASELINE.json:5), and `jax.sharding` spec trees
  that mirror the structure 1:1 (parallel/mesh.py).
- All matmuls are batched [B, in] @ [in, out] so XLA tiles them onto the MXU;
  no per-example Python loops anywhere.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Tuple[Dict[str, Any], ...]

FINAL_INIT_SCALE = 3e-3


def _uniform(key, shape, bound, dtype):
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def _linear_init(key, in_dim: int, out_dim: int, final: bool, dtype) -> Dict[str, Any]:
    bound = FINAL_INIT_SCALE if final else 1.0 / math.sqrt(in_dim)
    kw, kb = jax.random.split(key)
    return {
        "w": _uniform(kw, (in_dim, out_dim), bound, dtype),
        "b": _uniform(kb, (out_dim,), bound, dtype),
    }


def mlp_init(key, dims: Sequence[int], dtype=jnp.float32) -> Params:
    """Init a chain of linear layers with sizes dims[0] -> ... -> dims[-1]."""
    n = len(dims) - 1
    keys = jax.random.split(key, n)
    return tuple(
        _linear_init(keys[i], dims[i], dims[i + 1], final=(i == n - 1), dtype=dtype)
        for i in range(n)
    )


def actor_init(key, obs_dim: int, act_dim: int, hidden: Sequence[int], dtype=jnp.float32) -> Params:
    return mlp_init(key, [obs_dim, *hidden, act_dim], dtype)


def _dense(x, layer, mm_dtype):
    """x @ w + b. With mm_dtype (mixed precision): inputs/weights cast to
    the matmul dtype (bf16 -> MXU native rate), accumulation and bias stay
    f32 (`preferred_element_type`), so activations remain f32 throughout —
    the standard TPU mixed-precision recipe. Master params are always f32."""
    if mm_dtype is None:
        return x @ layer["w"] + layer["b"]
    return (
        jnp.dot(
            x.astype(mm_dtype),
            layer["w"].astype(mm_dtype),
            preferred_element_type=jnp.float32,
        )
        + layer["b"]
    )


def actor_apply(params: Params, obs, action_scale, action_offset=0.0, mm_dtype=None) -> Any:
    """mu(s): relu hiddens, tanh output mapped onto the action box
    [offset - scale, offset + scale] (offset != 0 for asymmetric spaces)."""
    x = obs
    for layer in params[:-1]:
        x = jax.nn.relu(_dense(x, layer, mm_dtype))
    x = _dense(x, params[-1], mm_dtype)
    return jnp.tanh(x) * action_scale + action_offset


def actor_gaussian_apply(
    params: Params, obs, log_std_min: float, log_std_max: float, mm_dtype=None
):
    """SAC stochastic head: the final layer outputs [mean | log_std]
    (2*act_dim wide — build params with actor_init(act_dim=2*act_dim)).
    Returns RAW (mean, log_std); sampling + tanh squash + the log-prob
    correction live in ops/losses.py so this stays a pure network apply.
    log_std is soft-clamped onto [min, max] with a tanh map — a hard clip
    would zero its gradient exactly where autotuned-alpha training tends
    to push it."""
    x = obs
    for layer in params[:-1]:
        x = jax.nn.relu(_dense(x, layer, mm_dtype))
    x = _dense(x, params[-1], mm_dtype)
    mean, log_std_raw = jnp.split(x, 2, axis=-1)
    log_std = log_std_min + 0.5 * (log_std_max - log_std_min) * (
        jnp.tanh(log_std_raw) + 1.0
    )
    return mean, log_std


def critic_init(
    key,
    obs_dim: int,
    act_dim: int,
    hidden: Sequence[int],
    action_insert_layer: int = 1,
    num_outputs: int = 1,
    dtype=jnp.float32,
) -> Params:
    """Critic params. The layer at index `action_insert_layer` takes
    [features, action] concatenated as its input (classic DDPG).
    `num_outputs > 1` builds the categorical head for the D4PG
    distributional critic (arXiv 1804.08617)."""
    dims = [obs_dim, *hidden, num_outputs]
    n = len(dims) - 1
    if not 0 <= action_insert_layer < n:
        raise ValueError(
            f"action_insert_layer={action_insert_layer} out of range for a "
            f"{n}-layer critic (valid: 0..{n - 1})"
        )
    keys = jax.random.split(key, n)
    layers = []
    for i in range(n):
        in_dim = dims[i] + (act_dim if i == action_insert_layer else 0)
        layers.append(_linear_init(keys[i], in_dim, dims[i + 1], final=(i == n - 1), dtype=dtype))
    return tuple(layers)


def critic_apply(
    params: Params, obs, action, action_insert_layer: int = 1, mm_dtype=None
) -> Any:
    """Q(s, a) -> f32[B] (or f32[B, num_atoms] logits when distributional)."""
    x = obs
    n = len(params)
    for i, layer in enumerate(params):
        if i == action_insert_layer:
            x = jnp.concatenate([x, action], axis=-1)
        x = _dense(x, layer, mm_dtype)
        if i < n - 1:
            x = jax.nn.relu(x)
    if x.shape[-1] == 1:
        return jnp.squeeze(x, axis=-1)
    return x
