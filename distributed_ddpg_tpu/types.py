"""Core pytree types: transitions, batches, and the learner TrainState.

The reference keeps its state scattered across TF graph variables on the
parameter server (SURVEY.md §1 'Distribution/comm'); here everything the
learner owns is ONE explicit pytree so the whole train step — losses, Adam,
Polyak — jits into a single XLA program with no host round trips
(SURVEY.md §3.3/§3.4).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import numpy as np


class Batch(NamedTuple):
    """A replay minibatch. `discount` already folds gamma^n * (1 - done) for
    n-step returns (D4PG), so the TD target is `r + discount * Q'(s', mu'(s'))`."""

    obs: Any          # f32[B, obs_dim]
    action: Any       # f32[B, act_dim]
    reward: Any       # f32[B]     (n-step discounted sum)
    discount: Any     # f32[B]     (gamma^n * (1 - done))
    next_obs: Any     # f32[B, obs_dim]
    weight: Any       # f32[B]     (PER importance weights; ones if uniform)


class OptState(NamedTuple):
    """Adam state for one parameter tree (matches optax.adam semantics)."""

    mu: Any           # first moment
    nu: Any           # second moment
    count: Any        # i32 step counter


class TrainState(NamedTuple):
    """Everything owned by the learner, as one donated pytree."""

    actor_params: Any
    critic_params: Any
    target_actor_params: Any
    target_critic_params: Any
    actor_opt: OptState
    critic_opt: OptState
    step: Any         # i32


def batch_from_numpy(arrays: Dict[str, np.ndarray]) -> Batch:
    return Batch(
        obs=arrays["obs"],
        action=arrays["action"],
        reward=arrays["reward"],
        discount=arrays["discount"],
        next_obs=arrays["next_obs"],
        weight=arrays.get("weight", np.ones_like(arrays["reward"])),
    )
