"""Core pytree types: transitions, batches, and the learner TrainState.

The reference keeps its state scattered across TF graph variables on the
parameter server (SURVEY.md §1 'Distribution/comm'); here everything the
learner owns is ONE explicit pytree so the whole train step — losses, Adam,
Polyak — jits into a single XLA program with no host round trips
(SURVEY.md §3.3/§3.4).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import numpy as np


class Batch(NamedTuple):
    """A replay minibatch. `discount` already folds gamma^n * (1 - done) for
    n-step returns (D4PG), so the TD target is `r + discount * Q'(s', mu'(s'))`."""

    obs: Any          # f32[B, obs_dim]
    action: Any       # f32[B, act_dim]
    reward: Any       # f32[B]     (n-step discounted sum)
    discount: Any     # f32[B]     (gamma^n * (1 - done))
    next_obs: Any     # f32[B, obs_dim]
    weight: Any       # f32[B]     (PER importance weights; ones if uniform)


class OptState(NamedTuple):
    """Adam state for one parameter tree (matches optax.adam semantics)."""

    mu: Any           # first moment
    nu: Any           # second moment
    count: Any        # i32 step counter


class TrainState(NamedTuple):
    """Everything owned by the learner, as one donated pytree.

    log_alpha/alpha_opt exist only for the SAC family (learned entropy
    temperature). They default to None — which JAX treats as an EMPTY
    pytree node — so every non-SAC TrainState keeps its exact historical
    leaf structure: checkpoints, sharding-spec trees, and tree.maps all
    compose unchanged."""

    actor_params: Any
    critic_params: Any
    target_actor_params: Any
    target_critic_params: Any
    actor_opt: OptState
    critic_opt: OptState
    step: Any         # i32
    log_alpha: Any = None   # f32 scalar (SAC only)
    alpha_opt: Any = None   # OptState over log_alpha (SAC autotune only)


def batch_from_numpy(arrays: Dict[str, np.ndarray]) -> Batch:
    return Batch(
        obs=arrays["obs"],
        action=arrays["action"],
        reward=arrays["reward"],
        discount=arrays["discount"],
        next_obs=arrays["next_obs"],
        weight=arrays.get("weight", np.ones_like(arrays["reward"])),
    )


# --- packed-batch wire format -----------------------------------------------
#
# Host->device transfers pay a large per-array overhead (worst under a
# tunneled TPU: ~11ms/array vs ~1ms/MB of payload), so minibatches cross the
# boundary as ONE [..., B, D] f32 array with fields concatenated on the last
# axis in this fixed order; `unpack_batch` slices them apart inside jit,
# where the slices fuse into the consumers for free.

def packed_width(obs_dim: int, act_dim: int) -> int:
    return 2 * obs_dim + act_dim + 3


def pack_batch_np(arrays: Dict[str, np.ndarray]) -> np.ndarray:
    """[..., B, field] dict -> [..., B, D] packed f32 array (host side)."""
    reward = np.asarray(arrays["reward"], np.float32)[..., None]
    discount = np.asarray(arrays["discount"], np.float32)[..., None]
    weight = arrays.get("weight")
    weight = (
        np.ones_like(reward)
        if weight is None
        else np.asarray(weight, np.float32)[..., None]
    )
    return np.concatenate(
        [arrays["obs"], arrays["action"], reward, discount, arrays["next_obs"], weight],
        axis=-1,
        dtype=np.float32,
    )


def unpack_batch(packed, obs_dim: int, act_dim: int) -> Batch:
    """Inverse of pack_batch_np; works on jnp arrays inside jit."""
    o = obs_dim
    a = act_dim
    return Batch(
        obs=packed[..., :o],
        action=packed[..., o : o + a],
        reward=packed[..., o + a],
        discount=packed[..., o + a + 1],
        next_obs=packed[..., o + a + 2 : 2 * o + a + 2],
        weight=packed[..., 2 * o + a + 2],
    )
