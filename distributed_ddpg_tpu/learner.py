"""The fused learner step — the metric-defining hot loop (SURVEY.md §3.3).

One pure function performs, in a single traced XLA program:
  1. critic TD update (or D4PG categorical update),
  2. DPG actor update (against the pre-update critic, matching the
     reference's semantics where both gradients are computed from the same
     forward values before either apply),
  3. Adam for both nets,
  4. Polyak target updates (SURVEY.md §3.4).

The reference crosses the worker<->parameter-server gRPC boundary three times
per step (params pull, grads push, target assign — SURVEY.md §3.3). Here the
step compiles to one device program: zero host crossings; the only transfers
are the incoming minibatch (double-buffered via train.py's ChunkPrefetcher)
and the
outgoing per-sample TD errors for PER priority updates.

`axis_name` threads an explicit `jax.lax.psum` gradient AllReduce for the
shard_map/ICI path (parallel/learner.py); under plain jit+sharding the same
collective is inserted by XLA from the sharding annotations, and psum is a
no-op (axis_name=None).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.ops import losses
from distributed_ddpg_tpu.ops.optim import adam_update
from distributed_ddpg_tpu.ops.polyak import polyak_update
from distributed_ddpg_tpu.types import Batch, OptState, TrainState
from distributed_ddpg_tpu.models.mlp import actor_init, critic_init


class StepOutput(NamedTuple):
    state: TrainState
    td_errors: jnp.ndarray   # f32[B] — for PER priority updates
    metrics: dict


# The exact keys of StepOutput.metrics (the dict built in make_learner_step).
# Sharded wrappers build out-sharding/out-spec pytrees from this, so it must
# stay in lockstep with the metrics dict below — which is why it lives here.
METRIC_KEYS = (
    "critic_loss",
    "actor_loss",
    "mean_q",
    "td_abs_mean",
    "critic_grad_norm",
    "actor_grad_norm",
)


def _maybe_psum_mean(tree, axis_name: Optional[str]):
    if axis_name is None:
        return tree
    # lint: ok(collective-discipline): only called from inside the jitted
    # learner step — axis_name exists only when the pmap/shard_map builder
    # (parallel/) threads it, so this traces under a mesh, never eagerly
    return jax.lax.pmean(tree, axis_name)


def init_train_state(config: DDPGConfig, obs_dim: int, act_dim: int, seed: int) -> TrainState:
    """Build initial params + hard-copied targets (SURVEY.md §3.4) + Adam state."""
    key = jax.random.PRNGKey(seed)
    k_actor, k_critic = jax.random.split(key)
    num_outputs = config.num_atoms if config.distributional else 1
    # SAC's stochastic head emits [mean | log_std] — double-width output
    # (actor_head_dim is the single source of the width rule; the actor
    # pool sizes its shared-memory layout with the same helper).
    from distributed_ddpg_tpu.actors.policy import actor_head_dim

    actor_params = actor_init(
        k_actor,
        obs_dim,
        actor_head_dim(act_dim, config.sac),
        tuple(config.actor_hidden),
    )
    if config.twin_critic or config.sac:
        # TD3 ensemble: two independently-initialized critics stacked on a
        # leading axis — the TrainState SHAPE is unchanged (same tree, each
        # critic leaf just gains a [2, ...] dim), so checkpointing, Adam,
        # Polyak, and the mesh pspec trees all compose without new cases.
        k1, k2 = jax.random.split(k_critic)
        critic_params = jax.tree.map(
            lambda a, b: jnp.stack([a, b]),
            critic_init(
                k1, obs_dim, act_dim, tuple(config.critic_hidden),
                config.action_insert_layer, num_outputs,
            ),
            critic_init(
                k2, obs_dim, act_dim, tuple(config.critic_hidden),
                config.action_insert_layer, num_outputs,
            ),
        )
    else:
        critic_params = critic_init(
            k_critic,
            obs_dim,
            act_dim,
            tuple(config.critic_hidden),
            config.action_insert_layer,
            num_outputs,
        )
    return TrainState(
        actor_params=actor_params,
        critic_params=critic_params,
        target_actor_params=jax.tree.map(jnp.copy, actor_params),
        target_critic_params=jax.tree.map(jnp.copy, critic_params),
        actor_opt=OptState(
            mu=jax.tree.map(jnp.zeros_like, actor_params),
            nu=jax.tree.map(jnp.zeros_like, actor_params),
            count=jnp.zeros((), jnp.int32),
        ),
        critic_opt=OptState(
            mu=jax.tree.map(jnp.zeros_like, critic_params),
            nu=jax.tree.map(jnp.zeros_like, critic_params),
            count=jnp.zeros((), jnp.int32),
        ),
        step=jnp.zeros((), jnp.int32),
        # SAC entropy temperature: learned log(alpha) scalar + its own Adam
        # state (None = empty pytree nodes for every other family).
        log_alpha=(
            jnp.asarray(jnp.log(config.sac_alpha), jnp.float32)
            if config.sac
            else None
        ),
        alpha_opt=(
            OptState(
                mu=jnp.zeros((), jnp.float32),
                nu=jnp.zeros((), jnp.float32),
                count=jnp.zeros((), jnp.int32),
            )
            if (config.sac and config.sac_autotune)
            else None
        ),
    )


def make_learner_step(
    config: DDPGConfig,
    action_scale,
    axis_name: Optional[str] = None,
    action_offset=0.0,
):
    """Returns the pure (state, batch) -> StepOutput function. Not jitted here:
    callers wrap it in jit-with-shardings, shard_map, or call it under
    interpretation for tests (parallel/learner.py owns device placement)."""
    ail = config.action_insert_layer
    scale = jnp.asarray(action_scale, jnp.float32)
    offset = jnp.asarray(action_offset, jnp.float32)
    # Mixed precision: bf16 matmuls (MXU native rate) with f32 accumulation
    # and f32 master params/opt state. Default f32 keeps the native-backend
    # bit-comparability oracle exact (BASELINE.json:5).
    mm = jnp.bfloat16 if config.compute_dtype == "bfloat16" else None
    support = (
        losses.categorical_support(config.v_min, config.v_max, config.num_atoms)
        if config.distributional
        else None
    )
    # TD3 target-smoothing noise: keyed by fold_in(seed-derived base, step)
    # — no key threads through the step signature, the stream is
    # deterministic/replayable, and every data-parallel replica derives the
    # identical key (replicated state.step), so replicas cannot fork.
    td3_base_key = (
        jax.random.PRNGKey(config.seed ^ 0x7D3AF)
        if config.twin_critic
        else None
    )
    # SAC sampling noise: same fold_in(base, step) discipline as TD3 —
    # deterministic, replayable, replica-identical (then axis-folded per
    # shard so a global batch gets globally-unique draws).
    sac_base_key = (
        jax.random.PRNGKey(config.seed ^ 0x5AC0) if config.sac else None
    )

    def sac_step(state: TrainState, batch: Batch) -> StepOutput:
        """SAC: entropy-regularized twin-critic TD + reparameterized actor
        + (optionally) the learned temperature. Kept as its own body — the
        actor loss carries an aux (mean log-prob -> alpha update) that the
        shared branch structure below has no slot for."""
        key = jax.random.fold_in(sac_base_key, state.step)
        if axis_name is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
        k_next, k_cur = jax.random.split(key)
        alpha = jnp.exp(state.log_alpha)

        def critic_loss_fn(cp):
            return losses.sac_critic_loss(
                cp, state.actor_params, state.target_critic_params, batch,
                scale, k_next, alpha,
                config.sac_log_std_min, config.sac_log_std_max,
                ail, config.critic_l2, offset, mm,
            )

        (closs, td), cgrads = jax.value_and_grad(critic_loss_fn, has_aux=True)(
            state.critic_params
        )
        cgrads = _maybe_psum_mean(cgrads, axis_name)

        # Actor gradient against the pre-update critic (file convention).
        def actor_loss_fn(ap):
            return losses.sac_actor_loss(
                ap, state.critic_params, batch, scale, k_cur, alpha,
                config.sac_log_std_min, config.sac_log_std_max,
                ail, offset, mm,
            )

        (aloss, mean_lp), agrads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(state.actor_params)
        agrads = _maybe_psum_mean(agrads, axis_name)
        # Global mean log-prob so every shard's alpha update sees the same
        # scalar (replicas must not fork on log_alpha).
        mean_lp = _maybe_psum_mean(mean_lp, axis_name)

        new_critic, critic_opt = adam_update(
            state.critic_params, cgrads, state.critic_opt, config.critic_lr
        )
        new_actor, actor_opt = adam_update(
            state.actor_params, agrads, state.actor_opt, config.actor_lr
        )
        new_target_critic = polyak_update(
            new_critic, state.target_critic_params, config.tau
        )
        # SAC's math has no target actor; the slot still trails the actor
        # via the same polyak so the TrainState invariants (targets trail
        # params) and checkpoint shape stay uniform across families.
        new_target_actor = polyak_update(
            new_actor, state.target_actor_params, config.tau
        )

        if config.sac_autotune:
            # J(log_alpha) = -log_alpha * (E[log pi] + target_H);
            # d/dlog_alpha = -(E[log pi] + target_H), exact — no autodiff
            # needed for a scalar with a linear objective. The target
            # resolution (explicit value vs the env-unit-shifted -act_dim
            # heuristic) lives in losses.sac_target_entropy, shared with
            # the fused kernel wrapper. act_dim is static under jit from
            # the batch's action shape.
            tgt_h = losses.sac_target_entropy(
                config.target_entropy, batch.action.shape[-1], action_scale
            )
            alpha_grad = -(jax.lax.stop_gradient(mean_lp) + tgt_h)
            new_log_alpha, alpha_opt = adam_update(
                state.log_alpha, alpha_grad, state.alpha_opt, config.critic_lr
            )
        else:
            new_log_alpha, alpha_opt = state.log_alpha, state.alpha_opt

        # mean_q recovered exactly: aloss = E[alpha*lp - minQ]
        # => E[minQ] = alpha * mean_lp - aloss.
        metrics = dict(
            zip(
                METRIC_KEYS,
                (
                    closs,
                    aloss,
                    alpha * mean_lp - aloss,
                    jnp.mean(jnp.abs(td)),
                    optree_norm(cgrads),
                    optree_norm(agrads),
                ),
            )
        )
        metrics = _maybe_psum_mean(metrics, axis_name)
        new_state = TrainState(
            actor_params=new_actor,
            critic_params=new_critic,
            target_actor_params=new_target_actor,
            target_critic_params=new_target_critic,
            actor_opt=actor_opt,
            critic_opt=critic_opt,
            step=state.step + 1,
            log_alpha=new_log_alpha,
            alpha_opt=alpha_opt,
        )
        return StepOutput(state=new_state, td_errors=td, metrics=metrics)

    if config.sac:
        return sac_step

    def step(state: TrainState, batch: Batch) -> StepOutput:
        # --- critic update ---
        if config.twin_critic:
            noise_key = jax.random.fold_in(td3_base_key, state.step)
            if axis_name is not None:
                # Explicit shard_map mode: each shard smooths its OWN batch
                # slice — without this fold every shard would draw the
                # identical eps matrix and a global batch of B*D rows would
                # get only B unique perturbations.
                noise_key = jax.random.fold_in(
                    noise_key, jax.lax.axis_index(axis_name)
                )

            def critic_loss_fn(cp):
                return losses.td3_critic_loss(
                    cp,
                    state.target_actor_params,
                    state.target_critic_params,
                    batch,
                    scale,
                    noise_key,
                    config.target_noise,
                    config.target_noise_clip,
                    ail,
                    config.critic_l2,
                    offset,
                    mm,
                )
        elif config.distributional:
            def critic_loss_fn(cp):
                return losses.distributional_critic_loss(
                    cp,
                    state.target_actor_params,
                    state.target_critic_params,
                    batch,
                    scale,
                    support,
                    ail,
                    offset,
                    mm,
                )
        else:
            def critic_loss_fn(cp):
                return losses.critic_loss(
                    cp,
                    state.target_actor_params,
                    state.target_critic_params,
                    batch,
                    scale,
                    ail,
                    config.critic_l2,
                    offset,
                    mm,
                )

        (closs, td), cgrads = jax.value_and_grad(critic_loss_fn, has_aux=True)(
            state.critic_params
        )
        cgrads = _maybe_psum_mean(cgrads, axis_name)

        # --- actor update (pre-update critic: both grads from the same state) ---
        if config.twin_critic:
            def actor_loss_fn(ap):
                return losses.td3_actor_loss(
                    ap, state.critic_params, batch, scale, ail, offset, mm
                )
        elif config.distributional:
            def actor_loss_fn(ap):
                return losses.distributional_actor_loss(
                    ap, state.critic_params, batch, scale, support, ail, offset, mm
                )
        else:
            def actor_loss_fn(ap):
                return losses.actor_loss(
                    ap, state.critic_params, batch, scale, ail, offset, mm
                )

        if config.twin_critic and config.policy_delay > 1:
            # TD3 delayed updates: the critic steps every call; the actor
            # AND both target nets step once per policy_delay critic steps
            # (lax.cond — both branches return the same pytree structure,
            # so the step stays a single traced program). The actor
            # BACKWARD (and its gradient pmean) lives inside the update
            # branch so skipped steps pay only the cheap forward for the
            # aloss metric — not (d-1)/d of wasted bwd FLOPs per chunk.
            # The cond predicate is the replicated state.step, so every
            # replica takes the same branch and the collective schedule
            # stays aligned. actor_opt.count only advances on real
            # updates, keeping Adam bias correction honest; updates land
            # on critic steps 0, d, 2d, ... (pre-increment step).
            aloss = actor_loss_fn(state.actor_params)
            new_critic, critic_opt = adam_update(
                state.critic_params, cgrads, state.critic_opt, config.critic_lr
            )

            def _delayed_update(_):
                agrads = jax.grad(actor_loss_fn)(state.actor_params)
                agrads = _maybe_psum_mean(agrads, axis_name)
                na, aopt = adam_update(
                    state.actor_params, agrads, state.actor_opt, config.actor_lr
                )
                return (
                    na,
                    aopt,
                    polyak_update(na, state.target_actor_params, config.tau),
                    polyak_update(
                        new_critic, state.target_critic_params, config.tau
                    ),
                    optree_norm(agrads),
                )

            def _skip_update(_):
                # actor_grad_norm reads 0 on skip steps (no grad computed).
                return (
                    state.actor_params,
                    state.actor_opt,
                    state.target_actor_params,
                    state.target_critic_params,
                    jnp.zeros((), jnp.float32),
                )

            (
                new_actor, actor_opt, new_target_actor, new_target_critic,
                actor_grad_norm,
            ) = jax.lax.cond(
                state.step % config.policy_delay == 0,
                _delayed_update,
                _skip_update,
                operand=None,
            )
        elif config.fused_update:
            aloss, agrads = jax.value_and_grad(actor_loss_fn)(state.actor_params)
            agrads = _maybe_psum_mean(agrads, axis_name)
            actor_grad_norm = optree_norm(agrads)
            # Pallas kernel: Adam + Polyak in one VPU pass (ops/fused_update.py).
            from distributed_ddpg_tpu.ops.fused_update import fused_adam_polyak

            new_critic, critic_opt, new_target_critic = fused_adam_polyak(
                state.critic_params, cgrads, state.critic_opt,
                state.target_critic_params, config.critic_lr, config.tau,
            )
            new_actor, actor_opt, new_target_actor = fused_adam_polyak(
                state.actor_params, agrads, state.actor_opt,
                state.target_actor_params, config.actor_lr, config.tau,
            )
        else:
            aloss, agrads = jax.value_and_grad(actor_loss_fn)(state.actor_params)
            agrads = _maybe_psum_mean(agrads, axis_name)
            actor_grad_norm = optree_norm(agrads)
            new_critic, critic_opt = adam_update(
                state.critic_params, cgrads, state.critic_opt, config.critic_lr
            )
            new_actor, actor_opt = adam_update(
                state.actor_params, agrads, state.actor_opt, config.actor_lr
            )

            # --- Polyak target updates, fused in (SURVEY.md §3.4) ---
            new_target_actor = polyak_update(new_actor, state.target_actor_params, config.tau)
            new_target_critic = polyak_update(new_critic, state.target_critic_params, config.tau)

        metrics = dict(
            zip(
                METRIC_KEYS,
                (
                    closs,
                    aloss,
                    -aloss,
                    jnp.mean(jnp.abs(td)),
                    optree_norm(cgrads),
                    actor_grad_norm,
                ),
            )
        )
        # Under shard_map each shard sees only its batch slice; average the
        # scalar diagnostics so every shard reports the global value.
        metrics = _maybe_psum_mean(metrics, axis_name)
        new_state = TrainState(
            actor_params=new_actor,
            critic_params=new_critic,
            target_actor_params=new_target_actor,
            target_critic_params=new_target_critic,
            actor_opt=actor_opt,
            critic_opt=critic_opt,
            step=state.step + 1,
        )
        return StepOutput(state=new_state, td_errors=td, metrics=metrics)

    return step


def optree_norm(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in leaves))


def jit_learner_step(config: DDPGConfig, action_scale, donate: bool = True, action_offset=0.0):
    """Single-device jitted step with donated TrainState (no HBM copy of the
    params between steps)."""
    step = make_learner_step(config, action_scale, action_offset=action_offset)
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_act_fn(config: DDPGConfig, action_scale, action_offset=0.0):
    """Jitted deterministic policy for evaluation/acting on device.
    SAC evaluates on the distribution mode: tanh(mean) onto the box."""
    from distributed_ddpg_tpu.models.mlp import actor_apply, actor_gaussian_apply

    scale = jnp.asarray(action_scale, jnp.float32)
    offset = jnp.asarray(action_offset, jnp.float32)

    if config.sac:

        @jax.jit
        def act(actor_params, obs):
            mean, _ = actor_gaussian_apply(
                actor_params, obs, config.sac_log_std_min, config.sac_log_std_max
            )
            return jnp.tanh(mean) * scale + offset

        return act

    @jax.jit
    def act(actor_params, obs):
        return actor_apply(actor_params, obs, scale, offset)

    return act


def make_sample_fn(config: DDPGConfig, action_scale, action_offset=0.0):
    """Jitted stochastic SAC policy (exploration): a ~ pi(.|s)."""
    from distributed_ddpg_tpu.models.mlp import actor_gaussian_apply
    from distributed_ddpg_tpu.ops import losses as losses_lib

    scale = jnp.asarray(action_scale, jnp.float32)
    offset = jnp.asarray(action_offset, jnp.float32)

    @jax.jit
    def sample(actor_params, obs, key):
        mean, log_std = actor_gaussian_apply(
            actor_params, obs, config.sac_log_std_min, config.sac_log_std_max
        )
        action, _ = losses_lib.sac_sample(mean, log_std, key, scale, offset)
        return action

    return sample
