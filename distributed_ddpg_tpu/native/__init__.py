"""ctypes bindings for the C++ replay core (replay_core.cpp).

Build-on-demand: first import compiles the shared library with g++ -O3 into
the user cache dir (fingerprinted by source hash, so edits rebuild). Every
consumer must tolerate `load() is None` — the numpy implementations in
replay/sum_tree.py are the always-available fallback; a missing/failed
toolchain degrades performance, never correctness.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional

import numpy as np

from distributed_ddpg_tpu.replay.sum_tree import SumTree

_SRC = os.path.join(os.path.dirname(__file__), "replay_core.cpp")
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

_I64 = ctypes.POINTER(ctypes.c_int64)
_F64 = ctypes.POINTER(ctypes.c_double)


def _cache_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    # User-private dir (not a world-writable shared /tmp path: the .so is
    # loaded with CDLL, so a predictable shared path would let another local
    # user plant code that we then execute).
    cache_dir = os.environ.get("DDPG_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~/.cache"), "distributed_ddpg_tpu_native"
    )
    os.makedirs(cache_dir, mode=0o700, exist_ok=True)
    return os.path.join(cache_dir, f"replay_core_{digest}.so")


def _build(so_path: str) -> None:
    tmp = so_path + f".tmp{os.getpid()}"
    subprocess.run(
        ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-o", tmp, _SRC],
        check=True,
        capture_output=True,
    )
    os.replace(tmp, so_path)  # atomic: concurrent builders race benignly


def load() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native library; None if unavailable."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("DDPG_DISABLE_NATIVE"):
        return None
    try:
        so_path = _cache_path()
        if not os.path.exists(so_path):
            _build(so_path)
        lib = ctypes.CDLL(so_path)
        lib.st_set.argtypes = [_F64, ctypes.c_int64, _I64, _F64, ctypes.c_int64]
        lib.st_sample.argtypes = [_F64, ctypes.c_int64, _F64, _I64, ctypes.c_int64]
        lib.st_get.argtypes = [_F64, ctypes.c_int64, _I64, _F64, ctypes.c_int64]
        _VOID = ctypes.c_void_p
        _F32 = ctypes.POINTER(ctypes.c_float)
        _I = ctypes.c_int64
        lib.ring_init.argtypes = [_VOID]
        lib.ring_push.argtypes = [_VOID, _I, _I, _F32, _I]
        lib.ring_push.restype = _I
        lib.ring_pop.argtypes = [_VOID, _I, _I, _F32, _I]
        lib.ring_pop.restype = _I
        lib.ring_size.argtypes = [_VOID]
        lib.ring_size.restype = _I
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def available() -> bool:
    return load() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctype)


class NativeSumTree(SumTree):
    """replay.sum_tree.SumTree with the hot loops (set/get/sample) in C++.
    Layout, rounding, and stratified sampling are inherited — the numpy
    class stays the single source of those semantics (and the oracle)."""

    def __init__(self, capacity: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native replay core unavailable")
        super().__init__(capacity)
        self._lib = lib

    def set(self, indices, priorities) -> None:
        idx = np.ascontiguousarray(indices, np.int64)
        prio = np.ascontiguousarray(priorities, np.float64)
        self._lib.st_set(
            _ptr(self.tree, _F64), self.capacity, _ptr(idx, _I64),
            _ptr(prio, _F64), len(idx),
        )

    def get(self, indices) -> np.ndarray:
        idx = np.ascontiguousarray(indices, np.int64)
        out = np.empty(len(idx), np.float64)
        self._lib.st_get(
            _ptr(self.tree, _F64), self.capacity, _ptr(idx, _I64),
            _ptr(out, _F64), len(idx),
        )
        return out

    def sample(self, values) -> np.ndarray:
        v = np.ascontiguousarray(values, np.float64)
        out = np.empty(len(v), np.int64)
        self._lib.st_sample(
            _ptr(self.tree, _F64), self.capacity, _ptr(v, _F64),
            _ptr(out, _I64), len(v),
        )
        return out


def make_sum_tree(capacity: int):
    """NativeSumTree when the toolchain cooperates, numpy SumTree otherwise."""
    return NativeSumTree(capacity) if available() else SumTree(capacity)


class ShmRing:
    """SPSC f32-row ring over a shared-memory buffer (replay_core.cpp's
    ring_* functions). One producer process, one consumer process; the
    buffer itself comes from the caller (actors/pool.py uses an anonymous
    mp.Array so spawn-children inherit it without name management).

    Layout: 128-byte header (two cache-line-separated int64 counters owned
    by C++) + capacity*width f32 rows."""

    HEADER_BYTES = 128

    def __init__(self, buf, capacity: int, width: int, init: bool = False):
        lib = load()
        if lib is None:
            raise RuntimeError("native replay core unavailable")
        self._lib = lib
        self.capacity = int(capacity)
        self.width = int(width)
        # Keep both the raw buffer and a flat uint8 view alive; the void*
        # passed to C++ points at the view's base.
        self._buf = buf
        self._view = np.frombuffer(buf, dtype=np.uint8)
        if len(self._view) < self.nbytes(capacity, width):
            raise ValueError(
                f"ring buffer too small: {len(self._view)} < "
                f"{self.nbytes(capacity, width)}"
            )
        self._ptr = ctypes.c_void_p(self._view.ctypes.data)
        if init:
            lib.ring_init(self._ptr)

    @staticmethod
    def nbytes(capacity: int, width: int) -> int:
        return ShmRing.HEADER_BYTES + 4 * capacity * width

    def push(self, rows: np.ndarray) -> int:
        """Append [n, width] f32 rows; returns rows accepted (ring may be
        full — caller keeps the rest)."""
        rows = np.ascontiguousarray(rows, np.float32)
        if rows.ndim != 2 or rows.shape[1] != self.width:
            raise ValueError(f"expected [n, {self.width}] rows, got {rows.shape}")
        return int(
            self._lib.ring_push(
                self._ptr, self.capacity, self.width,
                _ptr(rows, ctypes.POINTER(ctypes.c_float)), rows.shape[0],
            )
        )

    def pop(self, max_rows: int) -> np.ndarray:
        """Pop up to max_rows rows; returns an owned [n, width] f32 array."""
        out = np.empty((int(max_rows), self.width), np.float32)
        n = int(
            self._lib.ring_pop(
                self._ptr, self.capacity, self.width,
                _ptr(out, ctypes.POINTER(ctypes.c_float)), out.shape[0],
            )
        )
        # out[:n] alone would be a view pinning the full max_rows backing
        # allocation for as long as the caller holds the batch (callers ask
        # for the worst case, so that can be tens of MB per drain); copy
        # when the pop came back short so only n rows stay alive.
        return out[:n].copy() if n < max_rows else out

    def __len__(self) -> int:
        return int(self._lib.ring_size(self._ptr))
