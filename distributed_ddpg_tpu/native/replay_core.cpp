// Native replay core: sum-tree operations for prioritized replay.
//
// Role (SURVEY.md §2 note on native components): the reference is pure
// Python — its only native substrate is stock TensorFlow's C++ runtime. In
// this framework the device side is XLA-compiled; the remaining host-side
// hot path is the PER sum-tree, whose per-level numpy vectorization
// (replay/sum_tree.py) pays O(log C) full-array passes and np.unique calls
// per batch. These C routines do the same work cache-locally per item and
// are the backend behind native.NativeSumTree (ctypes; replay/sum_tree.py
// is the always-available fallback and correctness oracle).
//
// Memory contract: Python owns every buffer (numpy arrays) and passes raw
// pointers; these functions never allocate or free. The tree is the
// standard 1-indexed layout: leaves at [capacity, 2*capacity), internal
// node i = sum of children 2i and 2i+1. capacity is a power of two.

#include <atomic>
#include <cstdint>
#include <cstring>

// ---------------------------------------------------------------------------
// SPSC shared-memory transition ring (actors/pool.py "shm" transport).
//
// One ring per rollout worker: the worker process is the only producer, the
// learner process the only consumer, so a classic single-producer/single-
// consumer ring with monotonic head/tail counters needs no locks — just
// acquire/release ordering on the two counters (lock-free int64 atomics on
// every platform this runs on). Replaces mp.Queue pickling on the actor ->
// learner path: rows are fixed-width f32 transitions memcpy'd in place.
//
// Layout of the shared block (Python allocates it, both sides mmap it):
//   [0,   64): int64 head — rows ever pushed (producer-written)
//   [64, 128): int64 tail — rows ever popped (consumer-written)
//   [128, ..): f32 data[capacity][width], slot = counter % capacity
// ---------------------------------------------------------------------------

namespace {

struct RingHeader {
    alignas(64) std::atomic<int64_t> head;
    alignas(64) std::atomic<int64_t> tail;
};
static_assert(sizeof(RingHeader) == 128, "header must match Python offset");

inline RingHeader* hdr(void* shm) { return static_cast<RingHeader*>(shm); }

inline float* data(void* shm) {
    return reinterpret_cast<float*>(static_cast<char*>(shm) + 128);
}

// Rows [counter, counter+n) occupy ring slots counter % capacity onward,
// splitting at the wrap point.
inline void rows_in(float* ring, int64_t capacity, int64_t width,
                    int64_t counter, const float* src, int64_t n) {
    int64_t slot = counter % capacity;
    int64_t first = n < capacity - slot ? n : capacity - slot;
    std::memcpy(ring + slot * width, src, first * width * sizeof(float));
    if (n > first)
        std::memcpy(ring, src + first * width,
                    (n - first) * width * sizeof(float));
}

inline void rows_out(const float* ring, int64_t capacity, int64_t width,
                     int64_t counter, float* dst, int64_t n) {
    int64_t slot = counter % capacity;
    int64_t first = n < capacity - slot ? n : capacity - slot;
    std::memcpy(dst, ring + slot * width, first * width * sizeof(float));
    if (n > first)
        std::memcpy(dst + first * width, ring,
                    (n - first) * width * sizeof(float));
}

}  // namespace

extern "C" {

void ring_init(void* shm) {
    hdr(shm)->head.store(0, std::memory_order_relaxed);
    hdr(shm)->tail.store(0, std::memory_order_relaxed);
}

// Producer: append up to n rows; returns rows accepted (may be < n when the
// ring is near full — the caller keeps the remainder).
int64_t ring_push(void* shm, int64_t capacity, int64_t width,
                  const float* rows, int64_t n) {
    RingHeader* h = hdr(shm);
    int64_t head = h->head.load(std::memory_order_relaxed);
    int64_t tail = h->tail.load(std::memory_order_acquire);
    int64_t free_rows = capacity - (head - tail);
    int64_t take = n < free_rows ? n : free_rows;
    if (take <= 0) return 0;
    rows_in(data(shm), capacity, width, head, rows, take);
    h->head.store(head + take, std::memory_order_release);
    return take;
}

// Consumer: pop up to max_rows rows into out; returns rows popped.
int64_t ring_pop(void* shm, int64_t capacity, int64_t width, float* out,
                 int64_t max_rows) {
    RingHeader* h = hdr(shm);
    int64_t tail = h->tail.load(std::memory_order_relaxed);
    int64_t head = h->head.load(std::memory_order_acquire);
    int64_t avail = head - tail;
    int64_t take = avail < max_rows ? avail : max_rows;
    if (take <= 0) return 0;
    rows_out(data(shm), capacity, width, tail, out, take);
    h->tail.store(tail + take, std::memory_order_release);
    return take;
}

int64_t ring_size(void* shm) {
    RingHeader* h = hdr(shm);
    return h->head.load(std::memory_order_acquire) -
           h->tail.load(std::memory_order_acquire);
}

}  // extern "C"

extern "C" {


// Set leaf priorities and repair ancestor sums. Each item walks its leaf's
// root path; parents are recomputed from both children, so duplicate
// indices and shared ancestors converge to correct sums.
void st_set(double* tree, int64_t capacity, const int64_t* indices,
            const double* priorities, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t node = capacity + indices[i];
        tree[node] = priorities[i];
        node >>= 1;
        while (node >= 1) {
            tree[node] = tree[2 * node] + tree[2 * node + 1];
            node >>= 1;
        }
    }
}

// Descend the tree for each value in [0, total); writes leaf indices.
void st_sample(const double* tree, int64_t capacity, const double* values,
               int64_t* out_indices, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        double v = values[i];
        int64_t node = 1;
        while (node < capacity) {
            int64_t left = 2 * node;
            double left_sum = tree[left];
            if (v < left_sum) {
                node = left;
            } else {
                v -= left_sum;
                node = left + 1;
            }
        }
        out_indices[i] = node - capacity;
    }
}

// Gather leaf priorities.
void st_get(const double* tree, int64_t capacity, const int64_t* indices,
            double* out, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        out[i] = tree[capacity + indices[i]];
    }
}

}  // extern "C"
