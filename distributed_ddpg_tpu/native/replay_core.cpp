// Native replay core: sum-tree operations for prioritized replay.
//
// Role (SURVEY.md §2 note on native components): the reference is pure
// Python — its only native substrate is stock TensorFlow's C++ runtime. In
// this framework the device side is XLA-compiled; the remaining host-side
// hot path is the PER sum-tree, whose per-level numpy vectorization
// (replay/sum_tree.py) pays O(log C) full-array passes and np.unique calls
// per batch. These C routines do the same work cache-locally per item and
// are the backend behind native.NativeSumTree (ctypes; replay/sum_tree.py
// is the always-available fallback and correctness oracle).
//
// Memory contract: Python owns every buffer (numpy arrays) and passes raw
// pointers; these functions never allocate or free. The tree is the
// standard 1-indexed layout: leaves at [capacity, 2*capacity), internal
// node i = sum of children 2i and 2i+1. capacity is a power of two.

#include <cstdint>

extern "C" {


// Set leaf priorities and repair ancestor sums. Each item walks its leaf's
// root path; parents are recomputed from both children, so duplicate
// indices and shared ancestors converge to correct sums.
void st_set(double* tree, int64_t capacity, const int64_t* indices,
            const double* priorities, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t node = capacity + indices[i];
        tree[node] = priorities[i];
        node >>= 1;
        while (node >= 1) {
            tree[node] = tree[2 * node] + tree[2 * node + 1];
            node >>= 1;
        }
    }
}

// Descend the tree for each value in [0, total); writes leaf indices.
void st_sample(const double* tree, int64_t capacity, const double* values,
               int64_t* out_indices, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        double v = values[i];
        int64_t node = 1;
        while (node < capacity) {
            int64_t left = 2 * node;
            double left_sum = tree[left];
            if (v < left_sum) {
                node = left;
            } else {
                v -= left_sum;
                node = left + 1;
            }
        }
        out_indices[i] = node - capacity;
    }
}

// Gather leaf priorities.
void st_get(const double* tree, int64_t capacity, const int64_t* indices,
            double* out, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        out[i] = tree[capacity + indices[i]];
    }
}

}  // extern "C"
