"""Fully on-device training: env physics, exploration, replay, and learner
in ONE compiled XLA program per chunk (`--backend=jax_ondevice`).

This is the TPU-native end state of SURVEY.md §7's 'hard part (a)' (feeding
a 20x-faster learner): for envs with JAX dynamics (envs/jax_envs.py) there
is nothing left to feed — E vectorized envs, the OU noise process, the
device-resident replay ring, and the fused learner step all live in the
same `lax.scan`, so a K-iteration chunk runs K*E env steps and K gradient
steps with ZERO host<->device transfers inside the chunk (only scalar
metrics come out). The reference's topology (SURVEY.md §1: N worker
processes + parameter server over gRPC) needs a process boundary because
TF-1.x envs and learners can't fuse; on TPU the boundary itself was the
bottleneck, so this backend removes it rather than reimplementing it.

Semantics per scan iteration:
  1. OU noise update (theta/sigma/dt from config) on device, per env;
  2. a = clip(mu(s) + scale * ou, bounds) for all E envs (one MXU matmul);
  3. vmapped env.step with auto-reset; the stored transition bootstraps on
     the PRE-reset observation (jax_envs.StepOut.boot_obs);
  4. scatter the E packed transitions into the replay ring (mod-capacity);
  5. one learner step on a uniform sample of `batch_size` rows (gated off
     until `replay_min_size` rows exist — lax.cond, so warmup needs no
     separate compiled program).

The E envs play the role of the reference's N async actors (config reuses
`num_actors` for E); the effective replay ratio is E env steps per gradient
step. Data-parallelism: the minibatch AND the env batch shard over the
mesh's 'data' axis (envs replicate if E doesn't divide it); params follow
parallel/mesh.state_pspec (replicated, or TP-sharded when model_axis > 1).

Termination contract: `jax_envs.StepOut.terminated` distinguishes TRUE
termination (absorbing state — bootstrap discount 0) from time-limit
truncation (done without terminated — bootstrapping continues), and the
scan body folds it into the stored discount column as
`gamma * (1 - terminated)`. JaxPendulum only truncates (discounts are
always gamma); JaxMountainCar truly terminates at the goal and exercises
the split end to end (tests/test_ondevice.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs.jax_envs import make_jax_env
from distributed_ddpg_tpu.learner import (
    METRIC_KEYS,
    init_train_state,
    make_learner_step,
)
from distributed_ddpg_tpu.ops.exploration import vector_env_step
from distributed_ddpg_tpu.parallel import mesh as mesh_lib
from distributed_ddpg_tpu.types import TrainState, packed_width, unpack_batch


class Carry(NamedTuple):
    """Everything the on-device loop owns, as one donated pytree."""

    train: TrainState
    env_state: object        # vmapped env state pytree, leading dim E
    obs: jnp.ndarray         # f32[E, obs_dim] current policy observations
    ou: jnp.ndarray          # f32[E, act_dim] OU noise state
    ep_ret: jnp.ndarray      # f32[E] running episode returns
    storage: jnp.ndarray     # f32[capacity, D] packed replay ring
    ptr: jnp.ndarray         # i32[]
    size: jnp.ndarray        # i32[]
    key: jnp.ndarray         # PRNG key


class ChunkStats(NamedTuple):
    metrics: dict            # mean learner metrics over the chunk (f32[])
    learn_steps: jnp.ndarray # i32[] learner steps actually taken (post-warmup)
    dones: jnp.ndarray       # bool[K, E] episode boundaries
    ep_returns: jnp.ndarray  # f32[K, E] episode return where done, else 0


class OnDeviceDDPG:
    def __init__(
        self,
        config: DDPGConfig,
        mesh: Optional[Mesh] = None,
        chunk_size: int = 64,
    ):
        if config.prioritized:
            raise ValueError(
                "jax_ondevice backend supports uniform replay only (PER "
                "priorities are host state; use --backend=jax_tpu)"
            )
        if config.n_step != 1:
            raise ValueError(
                "jax_ondevice backend stores 1-step transitions (n-step "
                "windows are a host-accumulator feature; use --backend=jax_tpu)"
            )
        if config.train_every != 1:
            raise ValueError(
                "jax_ondevice backend runs one learner step per vector env "
                "step (train_every is a host-loop knob; use --backend=jax_tpu)"
            )
        if config.resolved_warmup_uniform() >= config.replay_capacity:
            raise ValueError(
                "warmup_uniform_steps must be < replay_capacity on "
                "jax_ondevice: the warmup gate reads the ring-fill counter, "
                "which saturates at capacity — a larger budget would act "
                "uniformly forever"
            )
        self.config = config
        self.env = make_jax_env(config.env_id)
        self.num_envs = int(config.num_actors)
        self.chunk_size = int(chunk_size)
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(
            config.data_axis, config.model_axis
        )
        data_size = self.mesh.shape["data"]
        # Same per-device batch semantics as the sharded learner
        # (parallel/learner.py global_batch): scale_batch_with_data draws
        # batch_size rows per data-axis device, so throughput grows with
        # the mesh instead of slicing a fixed batch thinner.
        self.global_batch = (
            config.batch_size * data_size
            if config.scale_batch_with_data
            else config.batch_size
        )
        if self.global_batch % data_size:
            raise ValueError(
                f"batch_size={config.batch_size} not divisible by data axis "
                f"size {data_size}"
            )

        env = self.env
        E = self.num_envs
        obs_dim, act_dim = env.obs_dim, env.act_dim
        self.obs_dim, self.act_dim = obs_dim, act_dim
        width = packed_width(obs_dim, act_dim)
        scale = ((env.action_high - env.action_low) / 2.0).astype(np.float32)
        offset = ((env.action_high + env.action_low) / 2.0).astype(np.float32)
        self.action_scale, self.action_offset = scale, offset
        low = jnp.asarray(env.action_low)
        high = jnp.asarray(env.action_high)

        step_fn = make_learner_step(config, scale, action_offset=offset)
        cfg = config
        capacity = cfg.replay_capacity
        min_fill = max(cfg.replay_min_size, cfg.batch_size)

        # Envs shard over 'data' when divisible; replicate otherwise (their
        # per-step FLOPs are negligible — sharding them is a bonus, not a need).
        env_axis = "data" if E % data_size == 0 else None
        env_spec = P(env_axis)

        warmup_uniform = cfg.resolved_warmup_uniform()

        def env_step(carry: Carry):
            # Shared exploration + step + packed-rows body
            # (ops/exploration.vector_env_step — one implementation for
            # this monolith AND the device-actor pool). Uniform warmup
            # (config.warmup_uniform_steps) gates on the RING FILL here —
            # valid because __init__ rejects warmup >= capacity (size
            # saturates there); worker.py parity: auto resolves > 0 only
            # for SAC, but an explicit budget means the same thing on
            # every backend.
            key, ou, action, out, rows = vector_env_step(
                cfg, env, E, carry.train.actor_params, carry.env_state,
                carry.obs, carry.ou, carry.key, scale, offset, low, high,
                warmup_active=(
                    carry.size < warmup_uniform
                    if warmup_uniform > 0
                    else None
                ),
            )
            idx = (carry.ptr + jnp.arange(E, dtype=jnp.int32)) % capacity
            storage = carry.storage.at[idx].set(rows)
            ep_ret = carry.ep_ret + out.reward
            done_returns = jnp.where(out.done, ep_ret, 0.0)
            return (
                Carry(
                    train=carry.train,
                    env_state=out.state,
                    obs=out.obs,
                    ou=ou,
                    ep_ret=jnp.where(out.done, 0.0, ep_ret),
                    storage=storage,
                    ptr=(carry.ptr + E) % capacity,
                    size=jnp.minimum(carry.size + E, capacity),
                    key=key,
                ),
                out.done,
                done_returns,
            )

        zero_metrics = {k: jnp.zeros((), jnp.float32) for k in METRIC_KEYS}

        global_batch = self.global_batch

        def learn_step(carry: Carry):
            key, k_sample = jax.random.split(carry.key)
            idx = jax.random.randint(
                k_sample, (global_batch,), 0, jnp.maximum(carry.size, 1)
            )
            packed = jax.lax.with_sharding_constraint(
                carry.storage[idx], NamedSharding(self.mesh, P("data", None))
            )
            out = step_fn(carry.train, unpack_batch(packed, obs_dim, act_dim))
            return carry._replace(train=out.state, key=key), out.metrics

        def maybe_learn(carry: Carry):
            return jax.lax.cond(
                carry.size >= min_fill,
                lambda c: learn_step(c) + (jnp.int32(1),),
                lambda c: (c, zero_metrics, jnp.int32(0)),
                carry,
            )

        def chunk(carry: Carry):
            def body(c, _):
                c, done, done_ret = env_step(c)
                c, metrics, learned = maybe_learn(c)
                return c, (metrics, learned, done, done_ret)

            carry, (ms, learned, dones, ep_returns) = jax.lax.scan(
                body, carry, None, length=self.chunk_size
            )
            n = jnp.sum(learned)
            # Mean over the iterations that actually learned (0-safe).
            metrics = jax.tree.map(
                lambda x: jnp.sum(x) / jnp.maximum(n, 1).astype(jnp.float32), ms
            )
            return carry, ChunkStats(
                metrics=metrics,
                learn_steps=n,
                dones=dones,
                ep_returns=ep_returns,
            )

        # --- shardings over the whole carry ---
        state = init_train_state(config, obs_dim, act_dim, config.seed)
        state_spec = mesh_lib.state_pspec(state, self.mesh)
        key = jax.random.PRNGKey(config.seed)
        k_init, k_run = jax.random.split(key)
        env_state = jax.vmap(env.init)(jax.random.split(k_init, E))
        carry = Carry(
            train=state,
            env_state=env_state,
            obs=jax.vmap(env.observe)(env_state),
            ou=jnp.zeros((E, act_dim), jnp.float32),
            ep_ret=jnp.zeros((E,), jnp.float32),
            storage=jnp.zeros((capacity, width), jnp.float32),
            ptr=jnp.zeros((), jnp.int32),
            size=jnp.zeros((), jnp.int32),
            key=k_run,
        )
        carry_spec = Carry(
            train=state_spec,
            env_state=jax.tree.map(lambda _: env_spec, env_state),
            obs=P(env_axis, None),
            ou=P(env_axis, None),
            ep_ret=P(env_axis),
            storage=P(None, None),
            ptr=P(),
            size=P(),
            key=P(),
        )
        self._carry_sharding = mesh_lib.to_named(self.mesh, carry_spec)
        stats_spec = ChunkStats(
            metrics={k: P() for k in METRIC_KEYS},
            learn_steps=P(),
            dones=P(None, env_axis),
            ep_returns=P(None, env_axis),
        )
        self._chunk = jax.jit(
            chunk,
            in_shardings=(self._carry_sharding,),
            out_shardings=(
                self._carry_sharding,
                mesh_lib.to_named(self.mesh, stats_spec),
            ),
            donate_argnums=(0,),
        )
        # --- compile-once multi-chunk superstep (config.superstep_beats;
        # parallel/superstep.py is the jax_tpu sibling) --- B chunk bodies
        # inside one donated-carry fori_loop: the ChunkStats rows stack
        # into a device-side [B, ...] carry, and finalize_stats pays ONE
        # device_get for the whole superstep. ALL B chunks run inside the
        # loop body (stats zero-initialized from eval_shape at trace
        # time): the body compiles as its own isolated computation with
        # the same codegen as the standalone chunk program — inlining the
        # first chunk instead lets XLA cross-optimize it with the loop
        # and diverge at ULP level (parallel/superstep.py, same finding).
        # Scope: exact parity is a SINGLE-device property; on a
        # multi-device mesh XLA schedules the collectives differently in
        # the loop body than in the standalone program, so SPMD runs
        # agree only to float32 tolerance (tests/test_superstep.py).
        self.superstep_beats = int(config.superstep_beats)
        self._superstep = None
        if self.superstep_beats > 1:
            B = self.superstep_beats

            def superstep(carry: Carry):
                stats_shapes = jax.eval_shape(chunk, carry)[1]
                stacked = jax.tree.map(
                    lambda s: jnp.zeros((B,) + s.shape, s.dtype),
                    stats_shapes,
                )

                def body(i, acc):
                    carry, stacked = acc
                    carry, s = chunk(carry)
                    stacked = jax.tree.map(
                        lambda a, x: a.at[i].set(x), stacked, s
                    )
                    return carry, stacked

                return jax.lax.fori_loop(0, B, body, (carry, stacked))

            stacked_spec = ChunkStats(
                metrics={k: P(None) for k in METRIC_KEYS},
                learn_steps=P(None),
                dones=P(None, None, env_axis),
                ep_returns=P(None, None, env_axis),
            )
            self._superstep = jax.jit(
                superstep,
                in_shardings=(self._carry_sharding,),
                out_shardings=(
                    self._carry_sharding,
                    mesh_lib.to_named(self.mesh, stacked_spec),
                ),
                donate_argnums=(0,),
            )
        self.carry: Carry = jax.device_put(carry, self._carry_sharding)
        self._env_steps = 0
        self._learn_steps = 0

    # --- driving ---

    def run_chunk(self) -> ChunkStats:
        """K scan iterations = K*E env steps + up-to-K learner steps."""
        self.carry, stats = self._chunk(self.carry)
        self._env_steps += self.chunk_size * self.num_envs
        return stats

    def run_superstep(self) -> ChunkStats:
        """B chunks as ONE fori_loop dispatch (superstep_beats > 1):
        B*K*E env steps + up-to-B*K learner steps, stats stacked [B, ...]
        on device — finalize_stats flattens them in the same single
        device_get a lone chunk pays."""
        self.carry, stats = self._superstep(self.carry)
        self._env_steps += (
            self.superstep_beats * self.chunk_size * self.num_envs
        )
        return stats

    def finalize_stats(self, stats: ChunkStats) -> dict:
        """Device stats -> host floats (one sync point per dispatch).
        Accepts a single chunk's stats OR a superstep's stacked [B, ...]
        rows (detected by learn_steps rank): stacked rows flatten so the
        episode accounting is identical to B sequential chunks, and the
        metric means re-weight by each chunk's learned-iteration count
        (each row is already a per-chunk mean; an unweighted mean would
        skew toward warmup chunks that learned less)."""
        host = jax.device_get(stats)
        ls = np.asarray(host.learn_steps)
        dones = np.asarray(host.dones)
        rets = np.asarray(host.ep_returns)
        if ls.ndim == 0:
            self._learn_steps += int(ls)
            out = {k: float(v) for k, v in host.metrics.items()}
        else:
            self._learn_steps += int(ls.sum())
            dones = dones.reshape((-1,) + dones.shape[2:])
            rets = rets.reshape((-1,) + rets.shape[2:])
            w = ls.astype(np.float64) / max(float(ls.sum()), 1.0)
            out = {
                k: float((np.asarray(v, np.float64) * w).sum())
                for k, v in host.metrics.items()
            }
        rets = rets[dones]
        out["episodes"] = int(dones.sum())
        if rets.size:
            out["episode_return"] = float(rets.mean())
        return out

    @property
    def env_steps(self) -> int:
        return self._env_steps

    @property
    def learn_steps(self) -> int:
        return self._learn_steps

    # --- host-side views (checkpoint / eval) ---

    @property
    def state(self) -> TrainState:
        return self.carry.train

    def actor_params_to_host(self):
        return jax.tree.map(np.asarray, jax.device_get(self.carry.train.actor_params))

    def load_train_state(self, state: TrainState) -> None:
        state = jax.device_put(state, self._carry_sharding.train)
        self.carry = self.carry._replace(train=state)

    def replay_state_dict(self) -> dict:
        n = int(jax.device_get(self.carry.size))
        storage = np.asarray(jax.device_get(self.carry.storage))
        return {
            "packed": storage[:n].copy(),
            "ptr": np.asarray(int(jax.device_get(self.carry.ptr))),
            "size": np.asarray(n),
        }

    def load_replay_state(self, state: dict) -> None:
        n = int(state["size"])
        storage = np.array(jax.device_get(self.carry.storage))
        storage[:n] = state["packed"]
        self.carry = self.carry._replace(
            storage=jax.device_put(
                jnp.asarray(storage), self._carry_sharding.storage
            ),
            ptr=jax.device_put(
                jnp.asarray(int(state["ptr"]) % self.config.replay_capacity, jnp.int32),
                self._carry_sharding.ptr,
            ),
            size=jax.device_put(
                jnp.asarray(n, jnp.int32), self._carry_sharding.size
            ),
        )


# ---------------------------------------------------------------------------
# program-contract analyzer hook (analysis/programs.py; docs/ANALYSIS.md
# "Layer 2")
# ---------------------------------------------------------------------------


def program_specs():
    """The fused env+replay+learner megastep as one traced program. The
    whole carry — train state, env states, the HBM ring — is donated; any
    leaf that stops aliasing doubles the RING in HBM, which is the
    costliest donation miss in the repo."""
    from distributed_ddpg_tpu.analysis.programs import (
        BuiltProgram,
        ProgramSpec,
        probe_config,
        probe_mesh,
    )

    def build():
        config = probe_config(num_actors=4, warmup_uniform_steps=8)
        od = OnDeviceDDPG(config, mesh=probe_mesh(), chunk_size=2)
        return BuiltProgram(od._chunk, (od.carry,), (0,))

    def build_superstep():
        # B=2: the smallest loop that actually iterates. The fori_loop's
        # donated carry includes the ring — aliasing must survive the
        # loop composition or the superstep doubles the RING in HBM.
        config = probe_config(
            num_actors=4, warmup_uniform_steps=8, superstep_beats=2
        )
        od = OnDeviceDDPG(config, mesh=probe_mesh(), chunk_size=2)
        return BuiltProgram(od._superstep, (od.carry,), (0,))

    return [
        ProgramSpec("ondevice.chunk", "ondevice.py", build),
        ProgramSpec("ondevice.superstep", "ondevice.py", build_superstep),
    ]
