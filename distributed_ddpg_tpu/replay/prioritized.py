"""Prioritized experience replay (SURVEY.md §2 #7; BASELINE.json:9).

Proportional PER (Schaul et al.) over the SoA ring storage of UniformReplay:
priorities p_i = (|td_i| + eps)^alpha in a sum-tree, stratified sampling,
importance weights w_i = (N * P(i))^-beta normalized by max w. beta anneals
host-side via `set_beta` (config.per_beta -> per_beta_final).

New transitions enter at the current max priority so every transition is
seen at least once. The learner returns per-sample TD errors from the jitted
step (learner.py StepOutput) and the host calls `update_priorities` — the
only extra device->host transfer PER costs.

Device-side siblings (replay/device.py): DevicePrioritizedReplay keeps the
priority vector in HBM and fuses this module's proportional draw into the
learner chunk (draw_per_indices); under replay_sharding='sharded' the
vector partitions over the mesh with the two-level sampler
make_sharded_per_draw — shard-local cumsums under a replicated top-level
over per-shard masses, i.e. exactly this sum-tree's root/subtree split
with the subtrees living on their owner devices (docs/REPLAY_SHARDING.md).
The host tree here remains the f64 reference the device parity tests
bound against.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from distributed_ddpg_tpu.replay.uniform import UniformReplay


class PrioritizedReplay(UniformReplay):
    def __init__(
        self,
        capacity: int,
        obs_dim: int,
        act_dim: int,
        alpha: float = 0.6,
        beta: float = 0.4,
        eps: float = 1e-6,
        seed: int = 0,
    ):
        super().__init__(capacity, obs_dim, act_dim, seed)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        # Imported lazily: distributed_ddpg_tpu.native imports
        # replay.sum_tree, so a module-level import here would close an
        # import cycle whenever `native` is imported first.
        from distributed_ddpg_tpu.native import make_sum_tree

        self._tree = make_sum_tree(capacity)  # C++ core, numpy fallback
        self._max_priority = 1.0

    def set_beta(self, beta: float) -> None:
        self.beta = float(beta)

    def add_batch(self, obs, action, reward, discount, next_obs) -> np.ndarray:
        idx = super().add_batch(obs, action, reward, discount, next_obs)
        self._tree.set(idx, np.full(len(idx), self._max_priority))
        return idx

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._tree.stratified_sample(batch_size, self._rng)
        # Ring slots beyond the current fill can only be sampled if their
        # priority is zero-mass; clip defensively anyway.
        idx = np.minimum(idx, self._size - 1)
        out = self.gather(idx)
        prios = self._tree.get(idx)
        probs = prios / max(self._tree.total, 1e-12)
        weights = (self._size * probs) ** (-self.beta)
        weights /= weights.max()
        out["weight"] = weights.astype(np.float32)
        out["indices"] = idx
        return out

    def update_priorities(self, indices, td_errors) -> None:
        prios = (np.abs(np.asarray(td_errors, np.float64)) + self.eps) ** self.alpha
        self._tree.set(np.asarray(indices), prios)
        self._max_priority = max(self._max_priority, float(prios.max(initial=0.0)))

    # --- checkpoint support ---

    def state_dict(self):
        state = super().state_dict()
        state["priorities"] = self._tree.get(np.arange(self._size)).copy()
        state["max_priority"] = np.asarray(self._max_priority)
        return state

    def load_state_dict(self, state) -> None:
        super().load_state_dict(state)
        if "priorities" in state:
            # Full tree REBUILD, not an in-place overlay: a restore to a
            # smaller fill than the live buffer's (guardrail rollback, or
            # an elastic-pod slice adoption staler than the ring —
            # docs/REPLAY_SHARDING.md) must zero the mass at every slot
            # beyond the restored size, or stratified_sample would keep
            # drawing rows the restored state never contained.
            prios = np.zeros(self.capacity, np.float64)
            prios[: self._size] = state["priorities"]
            self._tree.set(np.arange(self.capacity), prios)
            self._max_priority = float(state["max_priority"])
