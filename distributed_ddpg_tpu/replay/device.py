"""Device-resident replay: the buffer lives in HBM (SURVEY.md §7 'hard
parts (a)' taken to its conclusion; Podracer-style, PAPERS.md
arXiv 2104.06272).

The host-replay + per-chunk-transfer pipeline pays one h2d transfer per
learner chunk, and transfers that interleave with the execute stream
serialize against it (measured ~25ms/chunk through a tunneled TPU — 5x the
chunk's compute). At DDPG scale the WHOLE buffer fits HBM trivially
(1M transitions x 43 f32 = 172MB on a 16GB v5e), so this module keeps the
packed [capacity, D] ring in device memory:

  - `insert`: one jitted scatter (mod-capacity wraparound) of a packed
    [M, D] block; the only steady-state h2d traffic is fresh actor data,
    in bulk, ~1 transfer per thousands of env steps.
  - sampling: fused INTO the scanned learner chunk (parallel/learner.py
    sample_chunk path) — jax.random indices + gather per scan step, so a
    K-step chunk needs ZERO transfers in and only td/metrics out.

ptr/size/PRNG key live on device; nothing round-trips. Multi-host note:
storage is replicated over the mesh; insert blocks must be globally
identical SPMD inputs, so multi-host callers build the global block with
jax.make_array_from_process_local_data before insert (see
parallel/multihost.py docstring).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu.types import packed_width


class DeviceReplay:
    def __init__(
        self,
        capacity: int,
        obs_dim: int,
        act_dim: int,
        mesh: Optional[Mesh] = None,
        block_size: int = 4096,
        seed: int = 0,
    ):
        self.capacity = int(capacity)
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.block_size = int(block_size)
        self.width = packed_width(obs_dim, act_dim)
        self._mesh = mesh
        sharding = (
            NamedSharding(mesh, P(None, None)) if mesh is not None else None
        )
        scalar_sharding = NamedSharding(mesh, P()) if mesh is not None else None
        self.storage = jnp.zeros((self.capacity, self.width), jnp.float32)
        self.ptr = jnp.zeros((), jnp.int32)
        self.size = jnp.zeros((), jnp.int32)
        if sharding is not None:
            self.storage = jax.device_put(self.storage, sharding)
            self.ptr = jax.device_put(self.ptr, scalar_sharding)
            self.size = jax.device_put(self.size, scalar_sharding)
        self._pending = np.zeros((0, self.width), np.float32)

        donate = partial(
            jax.jit,
            donate_argnums=(0,),
            **(
                dict(
                    in_shardings=(sharding, sharding, scalar_sharding, scalar_sharding),
                    out_shardings=(sharding, scalar_sharding, scalar_sharding),
                )
                if sharding is not None
                else {}
            ),
        )

        @donate
        def _insert(storage, block, ptr, size):
            m = block.shape[0]
            idx = (ptr + jnp.arange(m, dtype=jnp.int32)) % self.capacity
            storage = storage.at[idx].set(block)
            new_ptr = (ptr + m) % self.capacity
            new_size = jnp.minimum(size + m, self.capacity)
            return storage, new_ptr, new_size

        self._insert = _insert

    def __len__(self) -> int:
        return int(jax.device_get(self.size))

    # --- host -> HBM ingestion ---

    def add_packed(self, block: np.ndarray) -> None:
        """Buffer packed [M, D] rows host-side; ship in fixed-size blocks
        (fixed shapes -> one compiled insert, no retrace churn)."""
        self._pending = np.concatenate([self._pending, block.astype(np.float32)])
        while len(self._pending) >= self.block_size:
            chunk, self._pending = (
                self._pending[: self.block_size],
                self._pending[self.block_size :],
            )
            self._ship(chunk)

    def flush(self, min_rows: int = 1) -> None:
        """Force pending rows out (padded by repetition to the block shape —
        only used at warmup / shutdown, so the tiny duplication bias is
        confined to the first/last block)."""
        n = len(self._pending)
        if n >= min_rows and n > 0:
            reps = -(-self.block_size // n)
            chunk = np.tile(self._pending, (reps, 1))[: self.block_size]
            self._pending = np.zeros((0, self.width), np.float32)
            self._ship(chunk)

    def _ship(self, chunk: np.ndarray) -> None:
        if self._mesh is not None:
            chunk = jax.device_put(
                chunk, NamedSharding(self._mesh, P(None, None))
            )
        self.storage, self.ptr, self.size = self._insert(
            self.storage, chunk, self.ptr, self.size
        )

    # --- state for the fused sampling learner path ---

    def device_state(self):
        return self.storage, self.size

    # --- checkpoint support (same contract as host buffers) ---

    def state_dict(self):
        n = len(self)
        storage = np.asarray(jax.device_get(self.storage))
        return {
            "packed": storage[:n].copy(),
            "ptr": np.asarray(int(jax.device_get(self.ptr))),
            "size": np.asarray(n),
        }

    def load_state_dict(self, state) -> None:
        n = int(state["size"])
        if n > self.capacity:
            raise ValueError(f"checkpointed size {n} exceeds capacity {self.capacity}")
        storage = np.array(jax.device_get(self.storage))  # writable copy
        storage[:n] = state["packed"]
        sharding = (
            NamedSharding(self._mesh, P(None, None)) if self._mesh is not None else None
        )
        self.storage = (
            jax.device_put(jnp.asarray(storage), sharding)
            if sharding is not None
            else jnp.asarray(storage)
        )
        self.ptr = jnp.asarray(int(state["ptr"]) % self.capacity, jnp.int32)
        self.size = jnp.asarray(n, jnp.int32)
        if self._mesh is not None:
            scalar = NamedSharding(self._mesh, P())
            self.ptr = jax.device_put(self.ptr, scalar)
            self.size = jax.device_put(self.size, scalar)
