"""Device-resident replay: the buffer lives in HBM (SURVEY.md §7 'hard
parts (a)' taken to its conclusion; Podracer-style, PAPERS.md
arXiv 2104.06272).

The host-replay + per-chunk-transfer pipeline pays one h2d transfer per
learner chunk, and transfers that interleave with the execute stream
serialize against it (measured ~25ms/chunk through a tunneled TPU — 5x the
chunk's compute). At DDPG scale the WHOLE buffer fits HBM trivially
(1M transitions x 43 f32 = 172MB on a 16GB v5e), so this module keeps the
packed [capacity, D] ring in device memory:

  - `insert`: one jitted scatter (mod-capacity wraparound) of a packed
    [M, D] block; the only steady-state h2d traffic is fresh actor data,
    in bulk, ~1 transfer per thousands of env steps.
  - sampling: fused INTO the scanned learner chunk (parallel/learner.py
    sample_chunk path) — jax.random indices + gather per scan step, so a
    K-step chunk needs ZERO transfers in and only td/metrics out.

ptr/size/PRNG key live on device; nothing round-trips.

Multi-host: storage is replicated over the (possibly process-spanning)
mesh, so every process must execute the IDENTICAL insert sequence on the
identical global block — per-process-local inserts would silently fork the
replicas. `add_packed` therefore only buffers host-side when
jax.process_count() > 1, and `sync_ship()` — which all processes must call
at the same point (train_jax: once per learner chunk) — ships
min-over-processes full blocks: each process contributes its local rows
via jax.make_array_from_process_local_data sharded over the mesh's 'data'
axis, and the jitted insert's replicated output sharding makes XLA
all-gather the block (ICI within host, DCN across) into every replica.
Single-process keeps the inline fast path; sync_ship degrades to flush.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu.types import packed_width


class DeviceReplay:
    def __init__(
        self,
        capacity: int,
        obs_dim: int,
        act_dim: int,
        mesh: Optional[Mesh] = None,
        block_size: int = 4096,
        seed: int = 0,
    ):
        self.capacity = int(capacity)
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.block_size = int(block_size)
        self.width = packed_width(obs_dim, act_dim)
        self._mesh = mesh
        sharding = (
            NamedSharding(mesh, P(None, None)) if mesh is not None else None
        )
        scalar_sharding = NamedSharding(mesh, P()) if mesh is not None else None
        self.storage = jnp.zeros((self.capacity, self.width), jnp.float32)
        self.ptr = jnp.zeros((), jnp.int32)
        self.size = jnp.zeros((), jnp.int32)
        if sharding is not None:
            self.storage = jax.device_put(self.storage, sharding)
            self.ptr = jax.device_put(self.ptr, scalar_sharding)
            self.size = jax.device_put(self.size, scalar_sharding)
        self._pending = np.zeros((0, self.width), np.float32)

        donate = partial(
            jax.jit,
            donate_argnums=(0,),
            **(
                dict(
                    in_shardings=(sharding, sharding, scalar_sharding, scalar_sharding),
                    out_shardings=(sharding, scalar_sharding, scalar_sharding),
                )
                if sharding is not None
                else {}
            ),
        )

        def _insert_impl(storage, block, ptr, size):
            m = block.shape[0]
            idx = (ptr + jnp.arange(m, dtype=jnp.int32)) % self.capacity
            storage = storage.at[idx].set(block)
            new_ptr = (ptr + m) % self.capacity
            new_size = jnp.minimum(size + m, self.capacity)
            return storage, new_ptr, new_size

        self._insert = donate(_insert_impl)

        # Multi-host ingest (see module docstring): a second compiled insert
        # whose block input is SHARDED over the data axis — each process
        # feeds its local rows, XLA all-gathers into the replicated storage.
        self._procs = jax.process_count() if mesh is not None else 1
        if self._procs > 1:
            global_rows = self._procs * self.block_size
            if global_rows % mesh.shape["data"]:
                raise ValueError(
                    f"block_size {self.block_size} x {self._procs} processes "
                    f"must divide evenly over data axis {mesh.shape['data']}"
                )
            self._block_sharding = NamedSharding(mesh, P("data", None))
            self._insert_global = jax.jit(
                _insert_impl,
                donate_argnums=(0,),
                in_shardings=(
                    sharding, self._block_sharding, scalar_sharding, scalar_sharding
                ),
                out_shardings=(sharding, scalar_sharding, scalar_sharding),
            )

    def __len__(self) -> int:
        return int(jax.device_get(self.size))

    def reward_sample(self, max_n: int = 100_000):
        """(reward, discount) columns, up to max_n rows, pulled to host —
        feeds the C51 auto-support sizing (ops/support_auto.initial_bounds;
        discount==0 marks terminal transitions, whose one-off rewards must
        not enter the persistent-reward bound).
        One bounded d2h outside the hot loop. Multi-process: REPLICATED
        storage only — _pending holds process-LOCAL un-shipped rows, and
        per-process bounds derived from them would compile different
        Bellman targets per replica (the replica fork this module's insert
        discipline exists to prevent). Single-process includes _pending so
        a just-warmed buffer is fully represented."""
        col = self.obs_dim + self.act_dim
        size = len(self)
        n = min(size, max_n)
        if n == size:
            cols = np.asarray(jax.device_get(self.storage[:n, col : col + 2]))
        else:
            # Evenly strided over the live region, not the [:n] prefix —
            # a 1M-ring prefix can be ~900k insertions stale, and the
            # round-5 corroboration gate would refuse legitimate
            # expansions against long-gone rewards. Deterministic stride:
            # replicas and strict_sync replays see identical samples.
            idx = np.linspace(0, size - 1, n).astype(np.int64)
            cols = np.asarray(
                jax.device_get(jnp.take(self.storage[:, col : col + 2],
                                        jnp.asarray(idx), axis=0))
            )
        if self._procs == 1 and len(self._pending):
            cols = np.concatenate([cols, self._pending[:max_n, col : col + 2]])
        return cols[:, 0], cols[:, 1]

    @property
    def pending_rows(self) -> int:
        """Host-side rows buffered but not yet shipped (multi-host: waiting
        for the lockstep sync_ship; callers use this for backpressure)."""
        return len(self._pending)

    # --- host -> HBM ingestion ---

    def add_packed(self, block: np.ndarray) -> None:
        """Buffer packed [M, D] rows host-side; ship in fixed-size blocks
        (fixed shapes -> one compiled insert, no retrace churn). Multi-host:
        buffers ONLY — rows leave via the lockstep sync_ship()."""
        self._pending = np.concatenate([self._pending, block.astype(np.float32)])
        if self._procs > 1:
            return
        while len(self._pending) >= self.block_size:
            chunk, self._pending = (
                self._pending[: self.block_size],
                self._pending[self.block_size :],
            )
            self._ship(chunk)

    def flush(self, min_rows: int = 1) -> None:
        """Force pending rows out (padded by repetition to the block shape —
        only used at warmup / shutdown, so the tiny duplication bias is
        confined to the first/last block). Single-process only; multi-host
        callers use sync_ship(force=True)."""
        if self._procs > 1:
            raise RuntimeError("flush() is per-process; use sync_ship() "
                               "in multi-host runs")
        n = len(self._pending)
        if n >= min_rows and n > 0:
            reps = -(-self.block_size // n)
            chunk = np.tile(self._pending, (reps, 1))[: self.block_size]
            self._pending = np.zeros((0, self.width), np.float32)
            self._ship(chunk)

    def sync_ship(self, force: bool = False) -> int:
        """Multi-host-safe ingest step. ALL processes must call this at the
        same point in their loop (train_jax: once per learner chunk) — it
        all-gathers pending counts and ships exactly min-over-processes
        full blocks, so every process executes the identical sequence of
        global device ops on a consistently-sharded block.

        force=True additionally pads one block from the remainders (only
        when every process holds >= 1 pending row) — warmup/shutdown use.
        Returns locally shipped real (unpadded) rows. Single-process it
        degrades to the add_packed/flush fast path."""
        if self._procs == 1:
            moved = 0
            while len(self._pending) >= self.block_size:
                chunk, self._pending = (
                    self._pending[: self.block_size],
                    self._pending[self.block_size :],
                )
                self._ship(chunk)
                moved += self.block_size
            if force and len(self._pending):
                moved += len(self._pending)
                self.flush()
            return moved

        from jax.experimental import multihost_utils

        counts = np.asarray(
            multihost_utils.process_allgather(np.int32(len(self._pending)))
        )
        m = int(counts.min())
        moved = 0
        for _ in range(m // self.block_size):
            chunk, self._pending = (
                self._pending[: self.block_size],
                self._pending[self.block_size :],
            )
            self._ship_global(chunk)
            moved += self.block_size
        if force and m % self.block_size:
            take = min(len(self._pending), self.block_size)
            chunk, self._pending = self._pending[:take], self._pending[take:]
            reps = -(-self.block_size // take)
            self._ship_global(np.tile(chunk, (reps, 1))[: self.block_size])
            moved += take
        return moved

    def _ship_global(self, local_rows: np.ndarray) -> None:
        block = jax.make_array_from_process_local_data(
            self._block_sharding,
            np.ascontiguousarray(local_rows, np.float32),
            (self._procs * self.block_size, self.width),
        )
        self.storage, self.ptr, self.size = self._insert_global(
            self.storage, block, self.ptr, self.size
        )

    def _ship(self, chunk: np.ndarray) -> None:
        if self._mesh is not None:
            chunk = jax.device_put(
                chunk, NamedSharding(self._mesh, P(None, None))
            )
        self.storage, self.ptr, self.size = self._insert(
            self.storage, chunk, self.ptr, self.size
        )

    # --- state for the fused sampling learner path ---

    def device_state(self):
        return self.storage, self.size

    # --- checkpoint support (same contract as host buffers) ---

    def state_dict(self):
        n = len(self)
        storage = np.asarray(jax.device_get(self.storage))
        return {
            "packed": storage[:n].copy(),
            "ptr": np.asarray(int(jax.device_get(self.ptr))),
            "size": np.asarray(n),
        }

    def load_state_dict(self, state) -> None:
        n = int(state["size"])
        if n > self.capacity:
            raise ValueError(f"checkpointed size {n} exceeds capacity {self.capacity}")
        storage = np.array(jax.device_get(self.storage))  # writable copy
        storage[:n] = state["packed"]
        sharding = (
            NamedSharding(self._mesh, P(None, None)) if self._mesh is not None else None
        )
        self.storage = (
            jax.device_put(jnp.asarray(storage), sharding)
            if sharding is not None
            else jnp.asarray(storage)
        )
        self.ptr = jnp.asarray(int(state["ptr"]) % self.capacity, jnp.int32)
        self.size = jnp.asarray(n, jnp.int32)
        if self._mesh is not None:
            scalar = NamedSharding(self._mesh, P())
            self.ptr = jax.device_put(self.ptr, scalar)
            self.size = jax.device_put(self.size, scalar)


def draw_per_indices(key, priorities, size, shape, beta):
    """Stratified proportional PER draw, fully on device (the TPU-native
    replacement for the host sum-tree walk, replay/prioritized.py): one
    cumsum over the priority vector + a vectorized searchsorted — O(cap)
    memory-bandwidth + O(n log cap) compare ops, no branchy tree descent.

    shape = (K, B): K scan steps of B samples, stratified within each B
    (mirroring SumTree.stratified_sample). Returns (idx[K,B], weights[K,B])
    with IS weights w = (size * p/total)^-beta normalized per B-batch by
    its max (exactly the host formula).

    f32 cumsum note: with ~1e6 priorities the running total's f32 ulp is
    ~0.06 at total ~1e6, so individual sample boundaries can shift by
    O(ulp/total) probability mass — negligible against PER's own eps floor;
    the host tree keeps f64 and the parity test bounds the difference."""
    k, b = shape
    cum = jnp.cumsum(priorities)
    total = cum[-1]
    u = (jnp.arange(b, dtype=jnp.float32)[None, :]
         + jax.random.uniform(key, (k, b))) / b * total
    idx = jnp.searchsorted(cum, u.reshape(-1), side="right").reshape(k, b)
    idx = jnp.minimum(idx.astype(jnp.int32), jnp.maximum(size - 1, 0))
    probs = priorities[idx] / jnp.maximum(total, 1e-12)
    weights = (size.astype(jnp.float32) * jnp.maximum(probs, 1e-12)) ** (-beta)
    weights = weights / jnp.max(weights, axis=-1, keepdims=True)
    return idx, weights


class DevicePrioritizedReplay(DeviceReplay):
    """Proportional PER with priorities resident in HBM (SURVEY.md §7 hard
    part (a) applied to PER; VERDICT.md round-1 Missing #4).

    The host PrioritizedReplay keeps a sum-tree on CPU, which forces the
    flagship path back to host sampling + per-chunk h2d transfers. Here the
    priority vector is a replicated f32[capacity] device array:

      - inserts stamp new rows with the running max priority (same
        every-transition-seen-once rule as the host buffer) inside a jitted
        scatter chained onto the storage insert;
      - sampling is draw_per_indices fused INTO the learner chunk
        (ShardedLearner.run_sample_chunk on a prioritized replay) — zero
        h2d, zero d2h for priorities;
      - priority updates scatter (|td|+eps)^alpha for the chunk's sampled
        indices at chunk end — the same once-per-chunk cadence the host
        path has (update_priorities is called once per after_chunk).

    Multi-host: priorities/max_priority are replicated like storage, and
    every update is computed from replicated inputs (state, key, td), so
    replicas stay identical with no extra collectives."""

    def __init__(
        self,
        capacity: int,
        obs_dim: int,
        act_dim: int,
        mesh: Optional[Mesh] = None,
        block_size: int = 4096,
        seed: int = 0,
        alpha: float = 0.6,
        eps: float = 1e-6,
    ):
        super().__init__(capacity, obs_dim, act_dim, mesh=mesh,
                         block_size=block_size, seed=seed)
        self.alpha = float(alpha)
        self.eps = float(eps)
        vec_sharding = NamedSharding(mesh, P(None)) if mesh is not None else None
        scalar_sharding = NamedSharding(mesh, P()) if mesh is not None else None
        self.priorities = jnp.zeros((self.capacity,), jnp.float32)
        self.max_priority = jnp.ones((), jnp.float32)
        if vec_sharding is not None:
            self.priorities = jax.device_put(self.priorities, vec_sharding)
            self.max_priority = jax.device_put(self.max_priority, scalar_sharding)

        def make_stamp(m: int):
            def stamp(prios, maxp, old_ptr):
                idx = (old_ptr + jnp.arange(m, dtype=jnp.int32)) % self.capacity
                return prios.at[idx].set(maxp)

            kwargs = (
                dict(
                    in_shardings=(vec_sharding, scalar_sharding, scalar_sharding),
                    out_shardings=vec_sharding,
                )
                if vec_sharding is not None
                else {}
            )
            return jax.jit(stamp, donate_argnums=(0,), **kwargs)

        self._stamp_local = make_stamp(self.block_size)
        if self._procs > 1:
            self._stamp_global = make_stamp(self._procs * self.block_size)

    def _ship(self, chunk: np.ndarray) -> None:
        old_ptr = self.ptr  # not donated by _insert; still valid after
        super()._ship(chunk)
        self.priorities = self._stamp_local(
            self.priorities, self.max_priority, old_ptr
        )

    def _ship_global(self, local_rows: np.ndarray) -> None:
        old_ptr = self.ptr
        super()._ship_global(local_rows)
        self.priorities = self._stamp_global(
            self.priorities, self.max_priority, old_ptr
        )

    # --- state for the fused PER sampling learner path ---

    def per_state(self):
        return self.storage, self.size, self.priorities, self.max_priority

    def set_per_state(self, priorities, max_priority) -> None:
        """Install the updated priority vector returned by the learner's
        fused chunk (both already carry the replicated sharding)."""
        self.priorities = priorities
        self.max_priority = max_priority

    # --- checkpoint support ---

    def state_dict(self):
        state = super().state_dict()
        n = int(state["size"])
        prios = np.asarray(jax.device_get(self.priorities))
        state["priorities"] = prios[:n].copy()
        state["max_priority"] = np.asarray(
            float(jax.device_get(self.max_priority))
        )
        return state

    def load_state_dict(self, state) -> None:
        super().load_state_dict(state)
        if "priorities" in state:
            n = int(state["size"])
            prios = np.array(jax.device_get(self.priorities))
            prios[:n] = state["priorities"]
            vec_sharding = (
                NamedSharding(self._mesh, P(None)) if self._mesh is not None else None
            )
            scalar = (
                NamedSharding(self._mesh, P()) if self._mesh is not None else None
            )
            self.priorities = jnp.asarray(prios)
            self.max_priority = jnp.asarray(
                float(state["max_priority"]), jnp.float32
            )
            if vec_sharding is not None:
                self.priorities = jax.device_put(self.priorities, vec_sharding)
                self.max_priority = jax.device_put(self.max_priority, scalar)
