"""Device-resident replay: the buffer lives in HBM (SURVEY.md §7 'hard
parts (a)' taken to its conclusion; Podracer-style, PAPERS.md
arXiv 2104.06272).

The host-replay + per-chunk-transfer pipeline pays one h2d transfer per
learner chunk, and transfers that interleave with the execute stream
serialize against it (measured ~25ms/chunk through a tunneled TPU — 5x the
chunk's compute). At DDPG scale the WHOLE buffer fits HBM trivially
(1M transitions x 43 f32 = 172MB on a 16GB v5e), so this module keeps the
packed [capacity, D] ring in device memory:

  - `insert`: one jitted scatter (mod-capacity wraparound) of a packed
    [M, D] block; the only steady-state h2d traffic is fresh actor data,
    in bulk, ~1 transfer per thousands of env steps.
  - sampling: fused INTO the scanned learner chunk (parallel/learner.py
    sample_chunk path) — jax.random indices + gather per scan step, so a
    K-step chunk needs ZERO transfers in and only td/metrics out.

ptr/size/PRNG key live on device; nothing round-trips.

Ingest pipeline (docs/INGEST.md): pending actor rows stage in a
preallocated host ring (replay/staging.py — one memcpy per push, killing
the seed's O(n^2) np.concatenate), ship as COALESCED super-blocks (up to
max_coalesce staged blocks fold into one device_put + one jitted scatter
per device call, power-of-two group sizes so the compiled-insert cache
stays O(log max_coalesce)), and — single-process, async_ship=True — move
on a background shipper thread so dispatch overlaps learner compute. The
coalesced scatter writes rows at exactly the positions the seed's serial
one-block-at-a-time sequence would have (multi-host groups are transposed
on device to interleave per-process blocks the way serial shipping did),
so storage/ptr/size stay bit-identical — tests/test_ingest_pipeline.py
and the multihost harness assert it.

Multi-host: storage is replicated over the (possibly process-spanning)
mesh, so every process must execute the IDENTICAL insert sequence on the
identical global block — per-process-local inserts would silently fork the
replicas. `add_packed` therefore only buffers host-side when
jax.process_count() > 1, and `sync_ship()` — which all processes must call
at the same point (train_jax: once per learner chunk) — ships
min-over-processes full blocks: each process contributes its local rows
via jax.make_array_from_process_local_data sharded over the mesh's 'data'
axis, and the jitted insert's replicated output sharding makes XLA
all-gather the block (ICI within host, DCN across) into every replica.
Single-process keeps the inline fast path; sync_ship degrades to flush.

Sharded placement (replay_sharding='sharded'; docs/REPLAY_SHARDING.md):
everything above keeps the storage REPLICATED — aggregate replay capacity
equals ONE device's HBM and every ingested row is copied to all N
replicas. Sharded mode partitions the SAME logical ring over the mesh's
'data' axis with strided ownership: logical position p lives on shard
p % N at local slot p // N (NamedSharding P('data', None) over a permuted
physical layout), so per-device storage is capacity/N rows (~N× aggregate
capacity at fixed HBM) and a staged ship device_puts each row ONLY to its
owner shard (~1/N landed ingest bytes — ReplayShardStats measures it from
the addressable shards). The ring SEMANTICS are unchanged: ptr/size, the
insert-position sequence, and every logical row's contents are
bit-identical to replicated mode (the sharded-vs-replicated parity oracle
in tests/test_replay_sharding.py pins it), which is what lets replicated
mode stay the correctness reference the way serial ingest anchored the
coalesced path. Sampling gathers each device's owned rows back into the
global minibatch inside the jitted learner chunk (parallel/learner.py's
masked-gather + psum index exchange). Alignment invariants: capacity and
block_size divide by N, and every insert moves a multiple of N rows, so
ptr % N == 0 always holds and per-shard groups stay exactly even.
Multi-host sharded beats ride the transfer scheduler's shard_exchange
lane (same strict-FIFO ordering + pod deadline as lockstep) and land via
an all-gather + owner-masked local scatter — per-device HBM stays 1/N,
while the DCN wire-byte 1/N (a true all-to-all lowering) is on the
native-TPU verification backlog (ROADMAP).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.metrics import IngestStats, ReplayShardStats
from distributed_ddpg_tpu.replay.staging import HostStagingRing
from distributed_ddpg_tpu.transfer import AdaptiveCoalesce, HostBufferPool
from distributed_ddpg_tpu.types import packed_width


class IngestError(RuntimeError):
    """The background ingest shipper thread died; the original exception
    rides along as __cause__ (mirrors ChunkPrefetcher's 'prefetch thread
    died' surfacing discipline)."""


class ReplayUsageError(RuntimeError):
    """The caller used a device-replay entry point outside its supported
    mode (per-process drain in a pod, single-writer checkpoint of a
    sharded buffer, ...). Distinct from IngestError — nothing died; the
    call itself is wrong, and recovery is a config/callsite change, never
    a restart."""


class _IngestShipper:
    """Single-process background shipper: moves staged full blocks to HBM
    off the producer's critical path, mirroring ChunkPrefetcher's
    daemon-thread discipline. The bounded double buffer is the staging
    ring itself: a full ring blocks producers inside add_packed (stall
    time is counted in IngestStats), which is the backpressure that keeps
    host memory bounded while dispatch overlaps learner compute."""

    def __init__(self, replay: "DeviceReplay"):
        self._replay = replay
        self._stop = threading.Event()
        self.exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ingest-ship"
        )

    def start(self) -> "_IngestShipper":
        self._thread.start()
        return self

    def _run(self) -> None:
        r = self._replay
        try:
            while not self._stop.is_set():
                with r._staging:
                    while (
                        len(r._ring) < r.block_size
                        and not self._stop.is_set()
                    ):
                        r._staging.wait(0.1)
                if self._stop.is_set():
                    return
                r._drain_ring()
        except BaseException as e:  # surface in the producer's next call
            self.exc = e
            with r._staging:
                r._staging.notify_all()  # unblock backpressure waiters

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._replay._staging:
            self._replay._staging.notify_all()
        self._thread.join(timeout=timeout)


class DeviceReplay:
    def __init__(
        self,
        capacity: int,
        obs_dim: int,
        act_dim: int,
        mesh: Optional[Mesh] = None,
        block_size: int = 4096,
        seed: int = 0,
        async_ship: bool = False,
        max_coalesce: int = 8,
        staging_blocks: int = 16,
        fault=None,
        scheduler=None,
        adaptive_coalesce: bool = False,
        host_pool: bool = False,
        background_sync: bool = False,
        pod_fault=None,
        track_sources: bool = False,
        replay_sharding: str = "replicated",
    ):
        self.capacity = int(capacity)
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.block_size = int(block_size)
        self.width = packed_width(obs_dim, act_dim)
        self._mesh = mesh
        if replay_sharding not in ("replicated", "sharded"):
            raise ValueError(
                f"replay_sharding must be 'replicated' or 'sharded', got "
                f"{replay_sharding!r}"
            )
        self.sharded = replay_sharding == "sharded"
        if self.sharded:
            # Strided ownership (module docstring): logical position p is
            # owned by shard p % N at local slot p // N. The alignment
            # invariants below keep ptr % N == 0 through every insert and
            # wrap, so per-shard ship groups are always exactly even.
            if mesh is None:
                raise ValueError(
                    "replay_sharding='sharded' partitions storage over a "
                    "mesh; construct the replay with one"
                )
            # 2D composition (docs/MESH.md): the ring partitions over the
            # 'data' axis only — under model_axis > 1 every storage spec
            # below names just 'data', so each shard's rows replicate
            # across the 'model' axis (per-device HBM is capacity /
            # data_axis) and the shard_map insert/gather bodies run
            # identically on every model replica.
            self._n_shards = int(mesh.shape["data"])
            if self.capacity % self._n_shards:
                raise ValueError(
                    f"replay_capacity {self.capacity} must divide evenly "
                    f"over {self._n_shards} shards (mod-capacity wraparound "
                    "must preserve the position's owner residue)"
                )
            if self.block_size % self._n_shards:
                raise ValueError(
                    f"block_size {self.block_size} must divide evenly over "
                    f"{self._n_shards} shards (each ship lands rows on "
                    "every owner in exactly even groups)"
                )
            self._shard_cap = self.capacity // self._n_shards
        else:
            self._n_shards = 1
            self._shard_cap = self.capacity
        sharding = (
            NamedSharding(mesh, P("data", None) if self.sharded else P(None, None))
            if mesh is not None
            else None
        )
        scalar_sharding = NamedSharding(mesh, P()) if mesh is not None else None
        self._storage_sharding = sharding
        self.storage = jnp.zeros((self.capacity, self.width), jnp.float32)
        self.ptr = jnp.zeros((), jnp.int32)
        self.size = jnp.zeros((), jnp.int32)
        if sharding is not None:
            self.storage = jax.device_put(self.storage, sharding)
            self.ptr = jax.device_put(self.ptr, scalar_sharding)
            self.size = jax.device_put(self.size, scalar_sharding)
        # Placement-layer observability (metrics.ReplayShardStats): landed
        # h2d bytes are MEASURED from each ship's addressable shards, so
        # the bytes-per-row A/B headline (docs/REPLAY_SHARDING.md) is an
        # observation of what this process actually moved.
        self._shard_stats = ReplayShardStats(seed=seed)

        # --- ingest pipeline state (docs/INGEST.md) ---
        # Staging ring + condition: producers push under it, the shipper /
        # sync paths pop under it, and backpressure waits on it. The
        # dispatch lock serializes every device-op sequence that reads or
        # swaps storage/ptr/size (ship calls here, chunk dispatch in
        # parallel/learner.py) so a donated-away storage buffer is never
        # observable mid-swap from another thread.
        self._max_coalesce = max(1, int(max_coalesce))
        self._ring = HostStagingRing(
            self.width, max(1, int(staging_blocks)) * self.block_size
        )
        self._staging = threading.Condition()
        self.dispatch_lock = threading.RLock()
        self._stats = IngestStats()
        # Chaos harness (faults.py): an optional FaultSite ticked once per
        # ship dispatch — shipper:ship:slow@k sleeps, shipper:ship:crash@k
        # raises (killing the shipper thread, which _check_shipper then
        # restarts — the supervised-recovery path under test).
        self._fault = fault
        # Pod chaos site (faults.py pod:<proc>:kill|hang@beat): ticked once
        # per lockstep sync_ship beat, so the beat ordinal is the trigger —
        # identical on every process, which is what lets a scripted
        # single-process death land at a deterministic pod-wide point.
        # train_jax arms it via arm_pod_fault at the first POST-WARMUP
        # beat: warmup's beat count is wall-clock-dependent (actor startup
        # pacing), steady-state beats advance one per lockstep chunk.
        self._pod_fault = pod_fault
        self._shipper_restarts = 0
        self._max_shipper_restarts = 3

        donate = partial(
            jax.jit,
            donate_argnums=(0,),
            **(
                dict(
                    in_shardings=(sharding, sharding, scalar_sharding, scalar_sharding),
                    out_shardings=(sharding, scalar_sharding, scalar_sharding),
                )
                if sharding is not None
                else {}
            ),
        )

        def _insert_impl(storage, block, ptr, size):
            m = block.shape[0]
            idx = (ptr + jnp.arange(m, dtype=jnp.int32)) % self.capacity
            storage = storage.at[idx].set(block)
            new_ptr = (ptr + m) % self.capacity
            new_size = jnp.minimum(size + m, self.capacity)
            return storage, new_ptr, new_size

        # Pure insert body, kept for composition inside LARGER jitted
        # programs (the fused megastep, parallel/megastep.py) — the jitted
        # wrappers below own donation/shardings for standalone dispatch.
        self._insert_pure = _insert_impl

        # One jitted program per super-block shape; shapes are restricted
        # to power-of-two multiples of block_size (_coalesce_k), so the
        # jit cache holds at most log2(max_coalesce)+1 entries. In sharded
        # mode the replicated-storage program is never built — the
        # per-shard scatter caches below replace it (same bounded set of
        # shapes, one program per m).
        self._insert = None if self.sharded else donate(_insert_impl)
        if self.sharded:
            self._block_sharding_sharded = NamedSharding(mesh, P("data", None))
            self._scalar_sharding = scalar_sharding
            self._insert_grouped_cache = {}
            self._insert_replrows_cache = {}
            # Restore-time reshard programs (elastic pod): land a full
            # replicated LOGICAL state onto this mesh's owners, whatever
            # process count wrote it (_get_reshard; docs/REPLAY_SHARDING.md
            # all-writer checkpoints).
            self._reshard_cache = {}

        # Multi-host ingest (see module docstring): a second compiled insert
        # whose block input is SHARDED over the data axis — each process
        # feeds its local rows, XLA all-gathers into the replicated storage.
        self._procs = jax.process_count() if mesh is not None else 1
        if self._procs > 1:
            global_rows = self._procs * self.block_size
            if global_rows % mesh.shape["data"]:
                raise ValueError(
                    f"block_size {self.block_size} x {self._procs} processes "
                    f"must divide evenly over data axis {mesh.shape['data']}"
                )
            self._block_sharding = NamedSharding(mesh, P("data", None))
            self._global_in_shardings = (
                sharding, self._block_sharding, scalar_sharding, scalar_sharding
            )
            self._global_out_shardings = (
                sharding, scalar_sharding, scalar_sharding
            )
            self._insert_global_cache = {}
            self._insert_global_sharded_cache = {}

        # --- unified transfer scheduler integration (docs/TRANSFER.md) ---
        # When a TransferScheduler is attached, single-process async
        # shipping submits ingest work items to it instead of running the
        # private _IngestShipper thread, the coalesce cap can adapt, a
        # host-buffer pool recycles the super-block staging copies, and
        # multi-host sync_ship beats can run on the scheduler's lockstep
        # lane in the background.
        self._sched = scheduler
        self._adaptive = (
            AdaptiveCoalesce(hi=self._max_coalesce, block_size=self.block_size)
            if adaptive_coalesce and self._max_coalesce > 1
            else None
        )
        self._pool = HostBufferPool(self.width) if host_pool else None
        self._ingest_inflight = False
        self._ingest_ticket = None
        self._ingest_exc: Optional[BaseException] = None
        self._bg_sync = (
            bool(background_sync) and scheduler is not None and self._procs > 1
        )
        self._beat = 0

        # --- ingest-source attribution (guardrails.py bad-row quarantine) ---
        # A host-side mirror of "which actor slot produced the row at each
        # storage position": add_packed tags staged rows with a source id,
        # a FIFO of (source, count) runs parallel to the staging ring, and
        # every successful ship stamps the landed positions using a host
        # mirror of the device insert pointer (advanced only on success,
        # exactly like the device ptr). Multi-host stamps only THIS
        # process's interleave slots (each process drains — and can
        # quarantine — only its own workers). Off (default): zero
        # bookkeeping, sources_of reports -1 (untracked).
        self._track_sources = bool(track_sources)
        self._source_map = (
            np.full(self.capacity, -1, np.int32)
            if self._track_sources else None
        )
        self._src_fifo: deque = deque()  # mutable [source, rows] run-lengths
        self._host_ptr = 0
        self._proc_idx = jax.process_index() if self._procs > 1 else 0

        # Background shipper (single-process only: multi-host rows may
        # leave the host ONLY via the lockstep sync_ship collective).
        self._async = bool(async_ship) and self._procs == 1
        self._sched_ingest = self._async and self._sched is not None
        self._shipper = (
            _IngestShipper(self).start()
            if self._async and not self._sched_ingest
            else None
        )

    def __len__(self) -> int:
        return int(jax.device_get(self.size))

    def reward_sample(self, max_n: int = 100_000):
        """(reward, discount) columns, up to max_n rows, pulled to host —
        feeds the C51 auto-support sizing (ops/support_auto.initial_bounds;
        discount==0 marks terminal transitions, whose one-off rewards must
        not enter the persistent-reward bound).
        One bounded d2h outside the hot loop. Multi-process: REPLICATED
        storage only — the staging ring holds process-LOCAL un-shipped
        rows, and per-process bounds derived from them would compile
        different Bellman targets per replica (the replica fork this
        module's insert discipline exists to prevent). Single-process
        includes staged rows so a just-warmed buffer is fully
        represented."""
        col = self.obs_dim + self.act_dim
        # dispatch_lock: the async shipper's insert DONATES storage, so an
        # unlocked read here could dispatch against a deleted buffer.
        with self.dispatch_lock:
            size = len(self)
            n = min(size, max_n)
            if self.sharded:
                # Logical rows live strided across shards: map the sample
                # (full fill, or the same deterministic stride as the
                # replicated branch) through the placement and gather.
                # Same logical rows as replicated mode -> identical
                # support-sizing decisions (the replica-fork rule below).
                idx = (
                    np.arange(size, dtype=np.int64)
                    if n == size
                    else np.linspace(0, size - 1, n).astype(np.int64)
                )
                cols = np.asarray(
                    jax.device_get(
                        jnp.take(
                            self.storage[:, col : col + 2],
                            jnp.asarray(self._phys_of_logical(idx)),
                            axis=0,
                        )
                    )
                )
            elif n == size:
                cols = np.asarray(
                    jax.device_get(self.storage[:n, col : col + 2])
                )
            else:
                # Evenly strided over the live region, not the [:n] prefix
                # — a 1M-ring prefix can be ~900k insertions stale, and the
                # round-5 corroboration gate would refuse legitimate
                # expansions against long-gone rewards. Deterministic
                # stride: replicas and strict_sync replays see identical
                # samples.
                idx = np.linspace(0, size - 1, n).astype(np.int64)
                cols = np.asarray(
                    jax.device_get(jnp.take(self.storage[:, col : col + 2],
                                            jnp.asarray(idx), axis=0))
                )
        if self._procs == 1:
            with self._staging:
                pend = self._ring.peek_cols(col, 2, max_n)
            if len(pend):
                cols = np.concatenate([cols, pend])
        return cols[:, 0], cols[:, 1]

    @property
    def pending_rows(self) -> int:
        """Host-side rows staged but not yet shipped (multi-host: waiting
        for the lockstep sync_ship; callers use this for backpressure)."""
        with self._staging:
            return len(self._ring)

    def ingest_snapshot(self) -> dict:
        """Interval ingest observability fields (metrics.IngestStats):
        rows/sec shipped, ship calls, coalesce factor, producer stall
        time, queue depth — emitted into train/bench records. The shipper
        restart count (cumulative, recovery path) rides along."""
        out = self._stats.snapshot(pending_rows=self.pending_rows)
        out["ingest_shipper_restarts"] = self._shipper_restarts
        # Placement-layer fields (replay_* family, docs/REPLAY_SHARDING.md):
        # measured landed bytes/row, per-device storage bytes, per-shard
        # fill, exchange-dispatch tails.
        out.update(
            self._shard_stats.snapshot(
                n_shards=self._n_shards,
                device_storage_bytes=(
                    self.capacity * self.width * 4 // self._n_shards
                ),
                fill=len(self),
            )
        )
        return out

    def transfer_snapshot(self) -> dict:
        """Replay-owned transfer_* fields: the adaptive-coalesce
        trajectory and host-pool gauges (the scheduler's own counters ride
        TransferScheduler.snapshot; train.py merges both)."""
        out = {}
        if self._adaptive is not None:
            out.update(self._adaptive.snapshot())
        if self._pool is not None:
            out.update(self._pool.snapshot())
        return out

    def arm_pod_fault(self, site) -> None:
        """Attach the pod chaos site (see __init__). Armed late so the
        trigger ordinal counts beats from a deterministic point (the
        warmup/steady boundary is lockstep on every process)."""
        self._pod_fault = site

    def close(self) -> None:
        """Stop the background shipper (if any) and detach from the
        transfer scheduler; subsequent add_packed calls fall back to
        inline shipping, so teardown stragglers still land."""
        if self._shipper is not None:
            self._shipper.stop()
            self._shipper = None
            self._async = False
        if self._sched_ingest:
            self._sched_ingest = False
            self._async = False

    # --- host -> HBM ingestion ---

    def _check_shipper(self) -> None:
        """Surface — or recover from — a dead shipper thread. The shipper
        is stateless between ships (staged rows stay in the ring until a
        pop commits to a dispatch... except the in-flight super-block a
        crash mid-ship loses, bounded by max_coalesce * block_size rows),
        so a bounded number of restarts is safe; past the cap the failure
        is structural and must surface."""
        s = self._shipper
        if s is not None and s.exc is not None:
            if self._shipper_restarts < self._max_shipper_restarts:
                self._shipper_restarts += 1
                exc, s.exc = s.exc, None
                trace.instant("shipper_restart", n=self._shipper_restarts)
                import sys

                print(
                    f"[ingest] shipper thread died ({exc!r}); restarting "
                    f"({self._shipper_restarts}/"
                    f"{self._max_shipper_restarts})",
                    file=sys.stderr, flush=True,
                )
                self._shipper = _IngestShipper(self).start()
                return
            raise IngestError("ingest shipper thread died") from s.exc
        # Scheduler-path equivalent: a failed ingest work item (its own
        # exception, or a scheduler-thread death that failed the ticket
        # before the item ran) recovers through the same bounded-restart
        # budget — resubmit up to the cap, then IngestError.
        t = self._ingest_ticket
        if t is not None and t.done() and t.exception is not None:
            with self._staging:
                self._ingest_inflight = False
            self._ingest_exc = self._ingest_exc or t.exception
            self._ingest_ticket = None
        exc = self._ingest_exc
        if exc is not None:
            self._ingest_exc = None
            if self._shipper_restarts < self._max_shipper_restarts:
                self._shipper_restarts += 1
                trace.instant("shipper_restart", n=self._shipper_restarts)
                import sys

                print(
                    f"[ingest] transfer ingest work died ({exc!r}); "
                    f"resubmitting ({self._shipper_restarts}/"
                    f"{self._max_shipper_restarts})",
                    file=sys.stderr, flush=True,
                )
                if self._sched_ingest:
                    with self._staging:
                        self._submit_ingest_locked()
                return
            raise IngestError("ingest shipper thread died") from exc

    # --- ingest-source attribution helpers (see __init__) ---

    def _pop_sources_locked(self, n: int) -> Optional[np.ndarray]:
        """Consume n rows' worth of source tags from the FIFO (caller holds
        _staging, at the same moment it pops the ring so the two stay in
        lockstep). Padding/short entries report -1."""
        if not self._track_sources:
            return None
        out = np.full(n, -1, np.int32)
        i = 0
        while i < n and self._src_fifo:
            entry = self._src_fifo[0]
            take = min(entry[1], n - i)
            out[i : i + take] = entry[0]
            entry[1] -= take
            if entry[1] == 0:
                self._src_fifo.popleft()
            i += take
        return out

    def _note_shipped(self, srcs: Optional[np.ndarray],
                      offsets: Optional[np.ndarray], advance: int) -> None:
        """Advance the host insert-pointer mirror past one SUCCESSFUL ship
        of `advance` rows and stamp the landed positions: `offsets` (row
        offsets from the pre-ship pointer) get `srcs`, everything else in
        the advanced range is marked untracked (-1) — other processes'
        interleave slots, padding."""
        if not self._track_sources:
            return
        pos_all = (self._host_ptr + np.arange(advance)) % self.capacity
        self._source_map[pos_all] = -1
        if srcs is not None and offsets is not None:
            pos = (self._host_ptr + offsets) % self.capacity
            self._source_map[pos] = srcs
        self._host_ptr = (self._host_ptr + advance) % self.capacity

    def sources_of(self, idx) -> np.ndarray:
        """Actor-slot ids that produced the rows at replay positions `idx`
        (-1 = untracked: sources off, another process's rows, restored
        contents, or padding). Best-effort under the async shipper — the
        map is stamped post-ship without a reader lock; attribution feeds
        a repeat-offender threshold, not an exact count."""
        idx = np.asarray(idx, np.int64)
        if self._source_map is None:
            return np.full(idx.shape, -1, np.int32)
        return self._source_map[idx % self.capacity]

    def _coalesce_k(self, n_blocks: int, cap_blocks: int, cap: Optional[int] = None) -> int:
        """Blocks to fold into the next super-block ship: largest power of
        two <= min(staged, coalesce cap, capacity) — capacity-capped so
        every scatter index within one super-block is distinct, which is
        what makes the coalesced scatter equal the serial sequence. The
        cap defaults to the static config value; single-process shipping
        paths pass the adaptive controller's effective cap (any cap
        sequence lands rows at identical positions, so adaptivity cannot
        perturb replay contents)."""
        k = min(n_blocks, cap or self._max_coalesce, max(1, cap_blocks))
        if k <= 0:
            return 0
        return 1 << (k.bit_length() - 1)

    def _effective_coalesce(self) -> int:
        return (
            self._adaptive.cap()
            if self._adaptive is not None
            else self._max_coalesce
        )

    def _drain_step(self) -> int:
        """Ship ONE coalesced super-block if at least one full block is
        staged; returns rows shipped. All pops happen under the dispatch
        lock so the pop -> device-op order is the ring's FIFO order no
        matter which thread ships (inline, _IngestShipper, or the transfer
        scheduler)."""
        cap_blocks = self.capacity // self.block_size
        with self.dispatch_lock:
            with self._staging:
                k = self._coalesce_k(
                    len(self._ring) // self.block_size, cap_blocks,
                    cap=self._effective_coalesce(),
                )
            if k == 0:
                return 0
            n = k * self.block_size
            # Pooled staging copy (transfer/hostbuf.py): acquire OUTSIDE
            # the staging condition (it may fence-wait on the device), pop
            # into it under the condition. The ring can only grow between
            # the two (every popper holds dispatch_lock), so k stays valid.
            buf = self._pool.acquire(n) if self._pool is not None else None
            with self._staging:
                rows = (
                    self._ring.pop_into(n, buf)
                    if buf is not None
                    else self._ring.pop(n)
                )
                srcs = self._pop_sources_locked(n)
                self._staging.notify_all()
            t0 = time.perf_counter()
            try:
                with trace.span("ingest_ship", rows=n, blocks=k):
                    self._ship(rows)
            except BaseException:
                if buf is not None:
                    # The ship never consumed the buffer into storage (or
                    # the orphaned device_put copy will never be read):
                    # return it unfenced so the bounded-restart resubmit
                    # does not find the pool drained.
                    self._pool.commit(buf, None)
                raise
            dt = time.perf_counter() - t0
            self._stats.record_ship(n, k, dt)
            # Source map advances only with a ship that actually landed —
            # like the device ptr, so the mirror can never drift on the
            # bounded-restart path (the popped rows AND their source tags
            # are lost together).
            if srcs is not None:
                self._note_shipped(srcs, np.arange(n), n)
            if buf is not None:
                # Fence on the insert's OUTPUT: the buffer recirculates
                # only after the op that read the transferred chunk has
                # executed (hostbuf.py module docstring).
                self._pool.commit(buf, self.size)
            if self._adaptive is not None:
                with self._staging:
                    queue_rows = len(self._ring)
                self._adaptive.observe_ship(k, dt, queue_rows)
        return n

    def _drain_ring(self) -> int:
        """Ship every currently-staged FULL block, coalesced. Called
        inline (sync mode), from the shipper thread (async mode), and from
        flush/sync_ship/drain_pending."""
        shipped = 0
        while True:
            n = self._drain_step()
            if n == 0:
                return shipped
            shipped += n

    # --- transfer-scheduler ingest work items (docs/TRANSFER.md) ---

    def _submit_ingest_locked(self) -> None:
        """Queue one ingest work item on the transfer scheduler if a full
        block is staged and none is in flight. Caller holds _staging."""
        if (
            not self._sched_ingest
            or self._ingest_inflight
            or len(self._ring) < self.block_size
        ):
            return
        self._ingest_inflight = True
        try:
            self._ingest_ticket = self._sched.submit(
                "ingest", self._scheduled_drain_step, label="ingest_ship"
            )
        except BaseException as e:
            # A dead/closed scheduler must not wedge ingest behind a
            # leaked in-flight flag, and must surface through the
            # contracted IngestError path (_check_shipper), not as a raw
            # TransferError from whoever happened to stage rows.
            self._ingest_inflight = False
            self._ingest_exc = self._ingest_exc or e

    def _scheduled_drain_step(self) -> int:
        """One scheduler-dispatched super-block ship. Re-arms itself while
        full blocks remain (one item in flight at a time, so the fair
        queue can interleave prefetch between super-blocks); failures park
        in _ingest_exc for the producer's bounded-restart check. Returns
        bytes moved (the scheduler's fair-queue currency)."""
        try:
            shipped = self._drain_step()
        except BaseException as e:
            with self._staging:
                self._ingest_inflight = False
                self._ingest_exc = e
                self._staging.notify_all()  # unblock backpressure waiters
            return 0
        with self._staging:
            self._ingest_inflight = False
            self._submit_ingest_locked()
        return shipped * self.width * 4

    def add_packed(self, block: np.ndarray, source: int = -1) -> None:
        """Stage packed [M, D] rows in the host ring; ship in fixed-size
        blocks (fixed power-of-two super-block shapes -> a bounded set of
        compiled inserts, no retrace churn). Multi-host: stages ONLY —
        rows leave via the lockstep sync_ship(). async_ship mode: the
        shipper thread does the device work; a full ring blocks here
        (backpressure, counted as ingest_stall_ms). `source` tags the
        rows' ingest source (actor slot) for the guardrails' bad-row
        attribution when track_sources is on; -1 = untracked."""
        self._check_shipper()
        rows = np.asarray(block, np.float32)
        stall = 0.0
        with self._staging:
            if self._async:
                t0 = time.perf_counter()
                while (
                    len(self._ring) + len(rows) > self._ring.capacity
                    and len(self._ring) >= self.block_size
                ):
                    self._staging.wait(0.05)
                    self._check_shipper()
                    if not self._async:
                        # close() raced us: nothing will drain the ring;
                        # fall through to push (the ring grows) and the
                        # inline ship below.
                        break
                stall = time.perf_counter() - t0
                if stall > 0.001:
                    # Producer blocked on a full staging ring: the
                    # backpressure interval as a span, so the timeline
                    # shows WHO was stalled while the shipper dispatched.
                    trace.complete(
                        "ingest_backpressure", t0, stall, rows=len(rows)
                    )
            self._ring.push(rows)
            if self._track_sources and len(rows):
                self._src_fifo.append([int(source), len(rows)])
            self._stats.record_push(len(rows), stall)
            self._staging.notify_all()
            self._submit_ingest_locked()
        if self._procs > 1 or self._async:
            return
        self._drain_ring()

    def insert_device_rows(self, rows) -> int:
        """Land an ALREADY-DEVICE-RESIDENT [M, D] block with the donated
        jitted scatter — the device-actor path (actors/device_pool.py;
        docs/DEVICE_ACTORS.md). The rows never touch the host: no staging
        ring, no transfer-scheduler ingest class, no IngestStats traffic —
        the devactor_* family accounts for this source instead, and a
        device-actor-only run reports transfer_ingest_items == 0.

        Multi-host: `rows` must be REPLICATED (NamedSharding P(None, None))
        and every process must call this at the same loop point — the
        device-actor rollout is a global SPMD program all processes
        execute in lockstep, so the replicated storage cannot fork and the
        host-row sync_ship accounting is untouched. The source-map pointer
        mirror advances with untracked (-1) tags so host-row attribution
        (guardrails) stays aligned when both backends feed the ring."""
        m = int(rows.shape[0])
        if m == 0:
            return 0
        with self.dispatch_lock:
            old_ptr = self.ptr  # not donated by _insert; PER stamp input
            if self.sharded:
                if m % self._n_shards:
                    raise ValueError(
                        f"insert_device_rows: {m} rows do not divide over "
                        f"{self._n_shards} shards — sharded mode requires "
                        "every insert to move a multiple of the shard "
                        "count (keeps ptr N-aligned; config.py validates "
                        "the device-actor chunk shape when data_axis is "
                        "explicit)"
                    )
                self.storage, self.ptr, self.size = (
                    self._get_insert_replrows(m)(
                        self.storage, rows, self.ptr, self.size
                    )
                )
            else:
                self.storage, self.ptr, self.size = self._insert(
                    self.storage, rows, self.ptr, self.size
                )
            self._stamp_device_rows(m, old_ptr)
            self._note_shipped(None, None, m)
        return m

    def _stamp_device_rows(self, m: int, old_ptr) -> None:
        """PER hook: DevicePrioritizedReplay stamps the landed rows with
        the running max priority (every-transition-seen-once rule); the
        uniform buffer needs nothing."""

    def drain_pending(self) -> int:
        """Ship all staged full blocks and block until the inserts have
        executed — the barrier bench/tests use before reading storage.
        Single-process only (multi-host draining IS sync_ship)."""
        if self._procs > 1:
            raise ReplayUsageError("drain_pending() is per-process; use "
                               "sync_ship() in multi-host runs")
        self._check_shipper()
        moved = self._drain_ring()
        with self.dispatch_lock:  # donation safety: see reward_sample
            jax.block_until_ready(self.storage)
        return moved

    def flush(self, min_rows: int = 1) -> None:
        """Force pending rows out (padded by repetition to the block shape —
        only used at warmup / shutdown, so the tiny duplication bias is
        confined to the first/last block). Single-process only; multi-host
        callers use sync_ship(force=True)."""
        if self._procs > 1:
            raise ReplayUsageError("flush() is per-process; use sync_ship() "
                               "in multi-host runs")
        self._check_shipper()
        self._drain_ring()
        with self.dispatch_lock:
            with self._staging:
                n = len(self._ring)
                rows = self._ring.pop(n) if (n >= min_rows and n > 0) else None
                srcs = (
                    self._pop_sources_locked(n) if rows is not None else None
                )
                if rows is not None:
                    self._staging.notify_all()
            if rows is not None:
                reps = -(-self.block_size // n)
                chunk = np.tile(rows, (reps, 1))[: self.block_size]
                t0 = time.perf_counter()
                with trace.span("ingest_flush", rows=n):
                    self._ship(chunk)
                self._stats.record_ship(n, 1, time.perf_counter() - t0)
                if srcs is not None:
                    # Padding repeats real rows, so the copies inherit the
                    # originals' source tags (a poisoned row's duplicate
                    # is just as attributable).
                    self._note_shipped(
                        np.tile(srcs, reps)[: self.block_size],
                        np.arange(self.block_size),
                        self.block_size,
                    )

    def sync_ship(self, force: bool = False) -> int:
        """Multi-host-safe ingest step. ALL processes must call this at the
        same point in their loop (train_jax: once per learner chunk) — it
        all-gathers pending counts and ships exactly min-over-processes
        full blocks, so every process executes the identical sequence of
        global device ops on a consistently-sharded block. Full blocks are
        coalesced into power-of-two super-blocks (identical k sequence on
        every process — it derives from the all-gathered min), each landed
        by ONE all-gathering insert whose on-device transpose reproduces
        the serial per-block interleave exactly.

        force=True additionally pads one block from the remainders (only
        when every process holds >= 1 pending row) — warmup/shutdown use.
        Returns locally shipped real (unpadded) rows. Single-process it
        degrades to the add_packed/flush fast path."""
        if self._procs == 1:
            self._check_shipper()
            moved = self._drain_ring()
            if force and self.pending_rows:
                moved += self.pending_rows
                self.flush()
            return moved
        if self._bg_sync:
            # Background-beat mode: even a synchronous caller must route
            # through the scheduler's lockstep lane — with beats possibly
            # queued ahead, a collective that bypassed the lane would
            # execute in a different order on different processes and
            # mismatch (docs/TRANSFER.md token protocol). The outer wait
            # is bounded by the CONFIGURED pod deadline (multihost.
            # wait_beat_ticket — a small multiple of
            # pod_collective_timeout_s plus any active grant), not a
            # hardcoded 10 minutes: a wedged lane surfaces as a typed
            # PodPeerLost on the clean-abort path (exit 76) instead of a
            # silent stall.
            from distributed_ddpg_tpu.parallel import multihost

            return multihost.wait_beat_ticket(
                self.sync_ship_begin(force=force)
            )
        return self._sync_ship_collective(force)

    def sync_ship_begin(self, force: bool = False):
        """Issue one lockstep ingest beat on the transfer scheduler's
        ordered lane and return its TransferTicket WITHOUT waiting — the
        background sync_ship mode (docs/TRANSFER.md). ALL processes must
        issue beats at the same points in the same order (train_jax's
        lockstep loop guarantees it), and the caller must wait the ticket
        before its next collective-bearing dispatch so per-process
        enqueue order stays identical. Each beat reads its pending count
        when it EXECUTES on the lane — strictly after every earlier beat
        (FIFO), so rows are never claimed twice; replicas agree because
        the shipped quantity derives from the all-gathered min, and the
        FIFO grouping invariance (_coalesce_k) keeps the final storage
        bit-identical to the synchronous reference."""
        if not self._bg_sync:
            raise ReplayUsageError(
                "sync_ship_begin() needs background_sync=True, an attached "
                "TransferScheduler, and a multi-process mesh"
            )
        self._beat += 1
        # Sharded beats ride the scheduler's shard_exchange class — the
        # SAME ordered lane (strict FIFO with lockstep, same pod deadline
        # wrap), separately accounted in transfer_shard_exchange_* so the
        # exchange cost is visible next to plain lockstep beats.
        return self._sched.submit(
            "shard_exchange" if self.sharded else "lockstep",
            lambda: self._sync_ship_collective(force),
            label=f"sync_ship_beat_{self._beat}",
        )

    def _sync_ship_collective(self, force: bool) -> int:
        # Count read at execution time (see sync_ship_begin): the staged
        # rows not consumed by any earlier beat. `count - moved` below is
        # stable against rows the producer stages concurrently — those
        # belong to a later beat.
        count = self.pending_rows
        from distributed_ddpg_tpu.parallel import multihost

        # Pod chaos trigger: the beat ordinal (see __init__). Fires
        # BEFORE the collective, so a kill/hang leaves the peers blocked
        # inside THIS beat's all-gather — the exact failure the pod
        # collective deadline (docs/RESILIENCE.md) exists to surface.
        if self._pod_fault is not None:
            self._pod_fault.tick()
        # One span over the whole lockstep beat (count all-gather +
        # ships): on the timeline this is the calling thread blocked on
        # the DCN collective — in background mode the span lands on the
        # transfer-sched track, overlapping the learner's chunk compute
        # (the overlap the ROADMAP lockstep-token item asked for).
        # beat_allgather piggybacks the pod heartbeat word on the count
        # payload (parallel/multihost.py peer-liveness tracking).
        with trace.span("sync_ship", beat=self._beat):
            counts = multihost.beat_allgather(count)
            m = int(counts.min())
            moved = 0
            cap_blocks = self.capacity // (self._procs * self.block_size)
            remaining = m // self.block_size
            with self.dispatch_lock:
                while remaining:
                    k = self._coalesce_k(remaining, cap_blocks)
                    with self._staging:
                        rows = self._ring.pop(k * self.block_size)
                        srcs = self._pop_sources_locked(k * self.block_size)
                    t0 = time.perf_counter()
                    with trace.span(
                        "ingest_ship_global", rows=k * self.block_size,
                        blocks=k,
                    ):
                        self._ship_global(rows, k=k)
                    self._stats.record_ship(
                        k * self.block_size, k, time.perf_counter() - t0
                    )
                    if srcs is not None:
                        # This process's k blocks land interleaved at
                        # offsets j*(procs*bs) + p*bs + r (the permuted
                        # scatter in _get_global_insert); other processes'
                        # slots stay -1 — each process attributes (and
                        # quarantines) only its own workers.
                        bs, procs, p = (
                            self.block_size, self._procs, self._proc_idx,
                        )
                        offsets = (
                            np.arange(k)[:, None] * (procs * bs)
                            + p * bs
                            + np.arange(bs)[None, :]
                        ).reshape(-1)
                        self._note_shipped(srcs, offsets, procs * k * bs)
                    moved += k * self.block_size
                    remaining -= k
                if force and m % self.block_size:
                    # Pad from the SNAPSHOT remainder (count was captured
                    # at token time): rows staged after the token belong
                    # to a later beat, and in background mode the producer
                    # may have staged more since.
                    take = min(count - moved, self.block_size)
                    with self._staging:
                        rows = self._ring.pop(take)
                        srcs = self._pop_sources_locked(take)
                    reps = -(-self.block_size // take)
                    t0 = time.perf_counter()
                    self._ship_global(
                        np.tile(rows, (reps, 1))[: self.block_size]
                    )
                    self._stats.record_ship(
                        take, 1, time.perf_counter() - t0
                    )
                    if srcs is not None:
                        bs, procs, p = (
                            self.block_size, self._procs, self._proc_idx,
                        )
                        self._note_shipped(
                            np.tile(srcs, reps)[:bs],
                            p * bs + np.arange(bs),
                            procs * bs,
                        )
                    moved += take
        return moved

    # --- sharded placement (replay_sharding='sharded'; module docstring,
    # docs/REPLAY_SHARDING.md). Logical ring semantics are identical to
    # replicated mode; only WHERE each logical row physically lives
    # changes: position p -> shard p % N, local slot p // N. ---

    def _phys_of_logical(self, p) -> np.ndarray:
        """Physical storage row of logical ring position(s) p (host-side
        numpy; the device programs compute the same map inline)."""
        p = np.asarray(p, np.int64)
        return (p % self._n_shards) * self._shard_cap + p // self._n_shards

    def _to_logical_rows(self, phys: np.ndarray) -> np.ndarray:
        """Physical [capacity, ...] array -> logical ring order (the
        checkpoint wire format, shared with replicated mode so state_dicts
        roundtrip ACROSS placement modes)."""
        n, sc = self._n_shards, self._shard_cap
        return np.ascontiguousarray(
            phys.reshape(n, sc, *phys.shape[1:]).swapaxes(0, 1)
            .reshape(phys.shape)
        )

    def _to_physical_rows(self, logical: np.ndarray) -> np.ndarray:
        n, sc = self._n_shards, self._shard_cap
        return np.ascontiguousarray(
            logical.reshape(sc, n, *logical.shape[1:]).swapaxes(0, 1)
            .reshape(logical.shape)
        )

    def _get_insert_grouped(self, m: int):
        """Compiled sharded insert for an m-row staged ship whose host
        block was GROUPED by owner shard (_ship orders shard s's rows
        s-th): the sharded device_put lands each group on exactly its
        owner, and each shard scatters one contiguous local run — zero
        collective, 1/N landed bytes. Relies on ptr % N == 0 (module
        docstring invariant): group s's local slots all start at ptr // N.
        Cached per m (the same bounded power-of-two set as _insert)."""
        fn = self._insert_grouped_cache.get(m)
        if fn is None:
            from distributed_ddpg_tpu.parallel import mesh as mesh_lib

            n, sc, cap = self._n_shards, self._shard_cap, self.capacity

            def body(st, bl, ptr, size):
                start = ptr // n
                slots = (start + jnp.arange(m // n, dtype=jnp.int32)) % sc
                st = st.at[slots].set(bl)
                return st, (ptr + m) % cap, jnp.minimum(size + m, cap)

            fn = jax.jit(
                mesh_lib.shard_map(
                    body, self._mesh,
                    in_specs=(P("data", None), P("data", None), P(), P()),
                    out_specs=(P("data", None), P(), P()),
                ),
                donate_argnums=(0,),
                in_shardings=(
                    self._storage_sharding, self._block_sharding_sharded,
                    self._scalar_sharding, self._scalar_sharding,
                ),
                out_shardings=(
                    self._storage_sharding, self._scalar_sharding,
                    self._scalar_sharding,
                ),
            )
            self._insert_grouped_cache[m] = fn
        return fn

    def _make_insert_replrows_body(self, m: int):
        """Pure sharded insert for an m-row REPLICATED device block: every
        shard already holds the whole block, so each just gathers its
        owned rows (offset j with j % N == shard — ptr-aligned) and
        scatters them into its contiguous local run. No collective, no
        host bytes. Shared by the jitted standalone insert below and the
        fused-megastep composition (pure_insert_device_rows_fn)."""
        from distributed_ddpg_tpu.parallel import mesh as mesh_lib

        n, sc, cap = self._n_shards, self._shard_cap, self.capacity

        def body(st, rows, ptr, size):
            s = jax.lax.axis_index("data")
            mine = rows[s + jnp.arange(m // n, dtype=jnp.int32) * n]
            start = ptr // n
            slots = (start + jnp.arange(m // n, dtype=jnp.int32)) % sc
            st = st.at[slots].set(mine)
            return st, (ptr + m) % cap, jnp.minimum(size + m, cap)

        return mesh_lib.shard_map(
            body, self._mesh,
            in_specs=(P("data", None), P(), P(), P()),
            out_specs=(P("data", None), P(), P()),
        )

    def pure_insert_device_rows_fn(self, m: int):
        """Pure (unjitted) insert body for an m-row ALREADY-DEVICE-RESIDENT
        replicated block — (storage, rows, ptr, size) -> (storage, ptr,
        size) with the exact math insert_device_rows dispatches, for
        composition inside a larger jitted program (the fused megastep,
        parallel/megastep.py; docs/FUSED_BEAT.md). The caller owns
        donation and the host-side bookkeeping (note_device_rows)."""
        if not self.sharded:
            return self._insert_pure
        if m % self._n_shards:
            raise ReplayUsageError(
                f"pure_insert_device_rows_fn: {m} rows do not divide over "
                f"{self._n_shards} shards (the insert_device_rows "
                "alignment invariant)"
            )
        return self._make_insert_replrows_body(m)

    def note_device_rows(self, m: int) -> None:
        """Advance the host-side source-attribution mirror past m device-
        produced rows landed by an EXTERNAL program's in-program insert
        (the fused megastep) — the same bookkeeping insert_device_rows
        does after its own scatter. Caller holds dispatch_lock."""
        self._note_shipped(None, None, m)

    def _get_insert_replrows(self, m: int):
        """Compiled sharded insert for an m-row REPLICATED device block
        (the device-actor path, insert_device_rows): the jitted/donating
        wrapper over _make_insert_replrows_body."""
        fn = self._insert_replrows_cache.get(m)
        if fn is None:
            fn = jax.jit(
                self._make_insert_replrows_body(m),
                donate_argnums=(0,),
                in_shardings=(
                    self._storage_sharding,
                    NamedSharding(self._mesh, P(None, None)),
                    self._scalar_sharding, self._scalar_sharding,
                ),
                out_shardings=(
                    self._storage_sharding, self._scalar_sharding,
                    self._scalar_sharding,
                ),
            )
            self._insert_replrows_cache[m] = fn
        return fn

    def _make_reshard_body(self):
        """Pure restore-time reshard (elastic pod; docs/REPLAY_SHARDING.md
        all-writer checkpoints): the full LOGICAL ring arrives replicated
        (merged from a complete slice set, identical on every process),
        and each shard gathers exactly the positions it owns under THIS
        mesh's strided map (p % N) into its local run — the placement
        twin of _make_insert_replrows_body with no ring-pointer state.
        Because the input is placement-free logical order, the same
        program lands a slice set written by ANY process count M onto a
        pod of N processes (the N->M reshard). No collective, no host
        bytes beyond the replicated feed."""
        from distributed_ddpg_tpu.parallel import mesh as mesh_lib

        n, sc = self._n_shards, self._shard_cap

        def body(rows):
            s = jax.lax.axis_index("data")
            return rows[s + jnp.arange(sc, dtype=jnp.int32) * n]

        return mesh_lib.shard_map(
            body, self._mesh,
            in_specs=(P(None, None),),
            out_specs=P("data", None),
        )

    def _get_reshard(self):
        """Jitted _make_reshard_body — full-capacity logical rows
        (replicated) -> sharded physical storage. One program per buffer
        (restore-time only, never on the hot path)."""
        if not self.sharded:
            raise ReplayUsageError(
                "reshard is the sharded-placement restore program; "
                "replicated buffers load logical state directly"
            )
        fn = self._reshard_cache.get("rows")
        if fn is None:
            fn = jax.jit(
                self._make_reshard_body(),
                in_shardings=(NamedSharding(self._mesh, P(None, None)),),
                out_shardings=self._storage_sharding,
            )
            self._reshard_cache["rows"] = fn
        return fn

    def _get_global_insert_sharded(self, k: int):
        """Compiled multi-host sharded insert for a k-block lockstep beat:
        all-gather the process-major arrival block, compute each gathered
        row's logical target through the SAME per-process interleave math
        as the replicated path (_get_global_insert), and drop-scatter only
        the rows this shard owns into its local run. Per-device HBM writes
        and storage stay 1/N; the all-gather's wire bytes match the
        replicated beat (a true all-to-all lowering is the ROADMAP
        follow-on — gloo's CPU backend has no all_to_all to pin it
        against)."""
        fn = self._insert_global_sharded_cache.get(k)
        if fn is None:
            from distributed_ddpg_tpu.parallel import mesh as mesh_lib

            procs, bs = self._procs, self.block_size
            n, sc, cap = self._n_shards, self._shard_cap, self.capacity

            def body(st, bl, ptr, size):
                m = procs * k * bs
                full = jax.lax.all_gather(bl, "data", axis=0, tiled=True)
                g = jnp.arange(m, dtype=jnp.int32)
                if k > 1:
                    p = g // (k * bs)
                    j = (g % (k * bs)) // bs
                    r = g % bs
                    off = j * (procs * bs) + p * bs + r
                else:
                    off = g
                tgt = (ptr + off) % cap
                s = jax.lax.axis_index("data")
                loc = jnp.where((tgt % n) == s, tgt // n, sc)
                st = st.at[loc].set(full, mode="drop")
                return st, (ptr + m) % cap, jnp.minimum(size + m, cap)

            fn = jax.jit(
                mesh_lib.shard_map(
                    body, self._mesh,
                    in_specs=(P("data", None), P("data", None), P(), P()),
                    out_specs=(P("data", None), P(), P()),
                ),
                donate_argnums=(0,),
                in_shardings=(
                    self._storage_sharding, self._block_sharding,
                    self._scalar_sharding, self._scalar_sharding,
                ),
                out_shardings=(
                    self._storage_sharding, self._scalar_sharding,
                    self._scalar_sharding,
                ),
            )
            self._insert_global_sharded_cache[k] = fn
        return fn

    def _get_global_insert(self, k: int):
        """Compiled all-gathering insert for a k-block super-block. The
        global array arrives ordered [proc0's k blocks | proc1's k blocks
        | ...] (data-axis shard order); serial shipping would have landed
        it block-by-block as [b0p0 b0p1 ... | b1p0 b1p1 ...]. Rather than
        transposing the SHARDED operand (a resharding XLA's multiprocess
        CPU backend refuses to compile), the scatter INDICES are permuted:
        gathered row g = (p, j, r) writes at ptr + j*(procs*bs) + p*bs + r
        — pure elementwise iota math, same all-gather + local scatter
        structure as k=1, and the storage layout stays bit-identical to
        the seed's serial sequence. Cached per k (power-of-two set, so
        O(log max_coalesce) programs)."""
        fn = self._insert_global_cache.get(k)
        if fn is None:
            procs, bs = self._procs, self.block_size

            def impl(storage, block, ptr, size):
                m = block.shape[0]  # procs * k * bs
                g = jnp.arange(m, dtype=jnp.int32)
                if k > 1:
                    p = g // (k * bs)
                    j = (g % (k * bs)) // bs
                    r = g % bs
                    offset = j * (procs * bs) + p * bs + r
                else:
                    offset = g
                idx = (ptr + offset) % self.capacity
                storage = storage.at[idx].set(block)
                new_ptr = (ptr + m) % self.capacity
                new_size = jnp.minimum(size + m, self.capacity)
                return storage, new_ptr, new_size

            fn = jax.jit(
                impl,
                donate_argnums=(0,),
                in_shardings=self._global_in_shardings,
                out_shardings=self._global_out_shardings,
            )
            self._insert_global_cache[k] = fn
        return fn

    def _ship_global(self, local_rows: np.ndarray, k: int = 1) -> None:
        if self._fault is not None:
            self._fault.tick()
        t0 = time.perf_counter()
        block = jax.make_array_from_process_local_data(
            self._block_sharding,
            np.ascontiguousarray(local_rows, np.float32),
            (self._procs * k * self.block_size, self.width),
        )
        insert = (
            self._get_global_insert_sharded(k)
            if self.sharded
            else self._get_global_insert(k)
        )
        self.storage, self.ptr, self.size = insert(
            self.storage, block, self.ptr, self.size
        )
        # This process's h2d contribution (its own local rows, once); the
        # collective's cross-device traffic is not host-visible here.
        self._shard_stats.record_ship(
            self._procs * k * self.block_size,
            sum(s.data.nbytes for s in block.addressable_shards),
            time.perf_counter() - t0,
        )

    def _ship(self, chunk: np.ndarray) -> None:
        if self._fault is not None:
            self._fault.tick()
        t0 = time.perf_counter()
        m = len(chunk)
        if self.sharded:
            # Group rows by owner shard (owner of ptr+j is j % N — ptr is
            # N-aligned) so the sharded device_put lands each row ONLY on
            # its owner: 1/N of the replicated path's landed bytes, the
            # measured claim behind BENCH_SHARDED_REPLAY.
            n = self._n_shards
            grouped = np.ascontiguousarray(
                np.asarray(chunk, np.float32)
                .reshape(m // n, n, self.width)
                .transpose(1, 0, 2)
                .reshape(m, self.width)
            )
            block = jax.device_put(grouped, self._block_sharding_sharded)
            nbytes = sum(s.data.nbytes for s in block.addressable_shards)
            self.storage, self.ptr, self.size = self._get_insert_grouped(m)(
                self.storage, block, self.ptr, self.size
            )
        else:
            if self._mesh is not None:
                chunk = jax.device_put(
                    chunk, NamedSharding(self._mesh, P(None, None))
                )
                nbytes = sum(
                    s.data.nbytes for s in chunk.addressable_shards
                )
            else:
                nbytes = m * self.width * 4
            self.storage, self.ptr, self.size = self._insert(
                self.storage, chunk, self.ptr, self.size
            )
        self._shard_stats.record_ship(m, nbytes, time.perf_counter() - t0)

    # --- state for the fused sampling learner path ---

    def device_state(self):
        return self.storage, self.size

    # --- checkpoint support (same contract as host buffers) ---

    def state_dict(self):
        with self.dispatch_lock:
            if self.sharded and self._procs > 1:
                raise ReplayUsageError(
                    "sharded replay contents span processes and have no "
                    "single-writer snapshot; each process checkpoints its "
                    "own slice instead (slice_state_dict + "
                    "checkpoint.write_replay_slice; docs/REPLAY_SHARDING.md)"
                )
            n = len(self)
            storage = np.asarray(jax.device_get(self.storage))
            if self.sharded:
                # Checkpoint wire format is LOGICAL ring order — shared
                # with replicated mode, so state_dicts roundtrip across
                # placement modes.
                storage = self._to_logical_rows(storage)
            return {
                "packed": storage[:n].copy(),
                "ptr": np.asarray(int(jax.device_get(self.ptr))),
                "size": np.asarray(n),
            }

    def slice_state_dict(self):
        """This process's slice of the logical ring — the all-writer
        checkpoint payload (checkpoint.write_replay_slice;
        docs/REPLAY_SHARDING.md). `positions` are the LOGICAL ring indices
        in [0, size) whose shards this process hosts (strided ownership
        p % N), ascending; `rows` are the packed rows at those positions.
        The format is position-indexed rather than shard-indexed, so a
        restore can merge any complete set and re-scatter to a DIFFERENT
        process count (merge_slice_states + load_state_dict). A
        single-process buffer (replicated or sharded) degenerates to one
        slice covering the whole ring."""
        with self.dispatch_lock:
            if not (self.sharded and self._procs > 1):
                st = self.state_dict()
                n = int(st["size"])
                out = {
                    "positions": np.arange(n, dtype=np.int64),
                    "rows": np.asarray(st["packed"], np.float32),
                    "ptr": np.asarray(int(st["ptr"]), np.int64),
                    "size": np.asarray(n, np.int64),
                    "capacity": np.asarray(self.capacity, np.int64),
                }
                if "priorities" in st:
                    out["priorities"] = np.asarray(
                        st["priorities"], np.float32
                    )
                    out["max_priority"] = np.asarray(
                        st["max_priority"], np.float32
                    )
                return out
            n = int(jax.device_get(self.size))
            ptr = int(jax.device_get(self.ptr))
            N, sc = self._n_shards, self._shard_cap
            pos_parts, row_parts = [], []
            seen = set()
            for sh in self.storage.addressable_shards:
                # Model-axis replicas repeat the same data shard; dedupe
                # by the shard's row offset into the global array.
                start = sh.index[0].start or 0
                if start in seen:
                    continue
                seen.add(start)
                sid = start // sc
                cnt = (n - sid + N - 1) // N if n > sid else 0
                if cnt <= 0:
                    continue
                # Local slot j of shard sid holds logical sid + j*N.
                pos_parts.append(
                    sid + np.arange(cnt, dtype=np.int64) * N
                )
                row_parts.append(
                    np.asarray(np.asarray(sh.data)[:cnt], np.float32)
                )
            if pos_parts:
                positions = np.concatenate(pos_parts)
                rows = np.concatenate(row_parts)
                order = np.argsort(positions, kind="stable")
                positions = positions[order]
                rows = np.ascontiguousarray(rows[order])
            else:
                positions = np.zeros((0,), np.int64)
                rows = np.zeros((0, self.width), np.float32)
            return {
                "positions": positions,
                "rows": rows,
                "ptr": np.asarray(ptr, np.int64),
                "size": np.asarray(n, np.int64),
                "capacity": np.asarray(self.capacity, np.int64),
            }

    def _replicated_scalar(self, v: int):
        out = jnp.asarray(int(v), jnp.int32)
        if self._mesh is not None:
            out = jax.device_put(out, NamedSharding(self._mesh, P()))
        return out

    def _load_state_multihost(self, state) -> None:
        """Multi-host sharded restore (elastic pod): every process holds
        the SAME full logical state (merged from a verified slice set on
        the shared checkpoint namespace), feeds it replicated — the
        module-docstring device_put discipline: identical global value on
        every process — and the reshard program scatters each shard's
        owned positions locally. This is the N->M reshard: the slice
        set's writer count never appears here, only the logical order."""
        n = int(state["size"])
        with self.dispatch_lock:
            full = np.zeros((self.capacity, self.width), np.float32)
            full[:n] = np.asarray(state["packed"], np.float32)
            rows = jax.device_put(
                jnp.asarray(full), NamedSharding(self._mesh, P(None, None))
            )
            self.storage = self._get_reshard()(rows)
            self.ptr = self._replicated_scalar(
                int(state["ptr"]) % self.capacity
            )
            self.size = self._replicated_scalar(n)
            if self._track_sources:
                self._source_map.fill(-1)
                self._src_fifo.clear()
                self._host_ptr = int(state["ptr"]) % self.capacity

    def load_state_dict(self, state) -> None:
        n = int(state["size"])
        if n > self.capacity:
            raise ValueError(f"checkpointed size {n} exceeds capacity {self.capacity}")
        if self.sharded and self._procs > 1:
            self._load_state_multihost(state)
            return
        with self.dispatch_lock:
            if self.sharded:
                # np.array: device_get hands back a READ-ONLY buffer, and
                # the logical permutation is a no-op (same buffer) when
                # there is a single shard.
                storage = self._to_logical_rows(
                    np.array(jax.device_get(self.storage))
                )
                storage[:n] = state["packed"]
                storage = self._to_physical_rows(storage)
            else:
                storage = np.array(jax.device_get(self.storage))  # writable copy
                storage[:n] = state["packed"]
            sharding = self._storage_sharding
            self.storage = (
                jax.device_put(jnp.asarray(storage), sharding)
                if sharding is not None
                else jnp.asarray(storage)
            )
            self.ptr = jnp.asarray(int(state["ptr"]) % self.capacity, jnp.int32)
            self.size = jnp.asarray(n, jnp.int32)
            if self._mesh is not None:
                scalar = NamedSharding(self._mesh, P())
                self.ptr = jax.device_put(self.ptr, scalar)
                self.size = jax.device_put(self.size, scalar)
            if self._track_sources:
                # Restored rows carry no attribution; re-sync the pointer
                # mirror with the restored device ptr.
                self._source_map.fill(-1)
                self._src_fifo.clear()
                self._host_ptr = int(state["ptr"]) % self.capacity


def merge_slice_states(slices):
    """Merge a complete all-writer slice set (checkpoint.load_replay_slices
    output, any order) back into ONE logical-order state_dict —
    load_state_dict's wire format, placement-portable by construction.
    Validates that every slice agrees on the ring scalars and that the
    positions tile [0, size) exactly once: a hole or an overlap means the
    set mixes worlds or writers, and silently loading it would corrupt the
    data distribution the learner resumes on."""
    if not slices:
        raise ReplayUsageError("merge_slice_states: empty slice set")
    size = int(slices[0]["size"])
    ptr = int(slices[0]["ptr"])
    cap = int(slices[0]["capacity"])
    for s in slices:
        got = (int(s["size"]), int(s["ptr"]), int(s["capacity"]))
        if got != (size, ptr, cap):
            raise ReplayUsageError(
                f"slice set disagrees on ring scalars: {got} != "
                f"{(size, ptr, cap)} (slices from different steps or runs)"
            )
    width = int(np.asarray(slices[0]["rows"]).shape[-1])
    packed = np.zeros((size, width), np.float32)
    covered = np.zeros(size, bool)
    has_prio = any("priorities" in s for s in slices)
    prios = np.zeros(size, np.float32) if has_prio else None
    maxp = 1.0
    for s in slices:
        pos = np.asarray(s["positions"], np.int64)
        if pos.size == 0:
            continue
        if pos.min() < 0 or pos.max() >= size:
            raise ReplayUsageError(
                f"slice positions out of range [0, {size}): "
                f"[{pos.min()}, {pos.max()}]"
            )
        if covered[pos].any():
            raise ReplayUsageError(
                "overlapping slice positions (two writers claim the same "
                "ring rows — mixed slice sets)"
            )
        packed[pos] = np.asarray(s["rows"], np.float32)
        covered[pos] = True
        if has_prio:
            prios[pos] = np.asarray(s["priorities"], np.float32)
            maxp = max(maxp, float(s["max_priority"]))
    if not covered.all():
        raise ReplayUsageError(
            f"slice set does not cover the ring: {int((~covered).sum())} "
            f"of {size} positions missing"
        )
    out = {
        "packed": packed,
        "ptr": np.asarray(ptr),
        "size": np.asarray(size),
    }
    if has_prio:
        out["priorities"] = prios
        out["max_priority"] = np.asarray(maxp, np.float32)
    return out


def split_slice_state(state, nslices: int, capacity: int):
    """Partition a full logical state_dict into `nslices` position-strided
    slices (position p -> slice p % n, the ownership map an n-process
    sharded pod would have written) — the inverse of merge_slice_states,
    for the reshard-matrix tests and offline resharding tools."""
    n = int(state["size"])
    out = []
    for k in range(nslices):
        pos = np.arange(k, n, nslices, dtype=np.int64)
        sl = {
            "positions": pos,
            "rows": np.asarray(state["packed"], np.float32)[pos],
            "ptr": np.asarray(int(state["ptr"]), np.int64),
            "size": np.asarray(n, np.int64),
            "capacity": np.asarray(int(capacity), np.int64),
        }
        if "priorities" in state:
            sl["priorities"] = np.asarray(
                state["priorities"], np.float32
            )[pos]
            sl["max_priority"] = np.asarray(
                state["max_priority"], np.float32
            )
        out.append(sl)
    return out


def draw_per_indices(key, priorities, size, shape, beta):
    """Stratified proportional PER draw, fully on device (the TPU-native
    replacement for the host sum-tree walk, replay/prioritized.py): one
    cumsum over the priority vector + a vectorized searchsorted — O(cap)
    memory-bandwidth + O(n log cap) compare ops, no branchy tree descent.

    shape = (K, B): K scan steps of B samples, stratified within each B
    (mirroring SumTree.stratified_sample). Returns (idx[K,B], weights[K,B])
    with IS weights w = (size * p/total)^-beta normalized per B-batch by
    its max (exactly the host formula).

    f32 cumsum note: with ~1e6 priorities the running total's f32 ulp is
    ~0.06 at total ~1e6, so individual sample boundaries can shift by
    O(ulp/total) probability mass — negligible against PER's own eps floor;
    the host tree keeps f64 and the parity test bounds the difference."""
    k, b = shape
    cum = jnp.cumsum(priorities)
    total = cum[-1]
    u = (jnp.arange(b, dtype=jnp.float32)[None, :]
         + jax.random.uniform(key, (k, b))) / b * total
    idx = jnp.searchsorted(cum, u.reshape(-1), side="right").reshape(k, b)
    idx = jnp.minimum(idx.astype(jnp.int32), jnp.maximum(size - 1, 0))
    probs = priorities[idx] / jnp.maximum(total, 1e-12)
    weights = (size.astype(jnp.float32) * jnp.maximum(probs, 1e-12)) ** (-beta)
    weights = weights / jnp.max(weights, axis=-1, keepdims=True)
    return idx, weights


def make_sharded_per_draw(mesh):
    """Factory for the SHARDED counterpart of draw_per_indices: shard-
    local priority cumsums with a replicated top-level sampler
    (docs/REPLAY_SHARDING.md; the 'shard-local trees, replicated root'
    shape replay/prioritized.py's host sum-tree hints at). Each shard
    cumsums only its own priority slots; the per-shard masses are
    all-gathered (N floats — the tiny 'root node' exchange); the
    stratified uniforms are drawn replica-identically from the same key
    and each lands in exactly one shard's half-open mass interval
    (interval bounds come from ONE replicated cumsum of the gathered
    totals, so no f32 reassociation can double- or zero-claim a sample;
    the last shard's upper bound is +inf to absorb u==total rounding).
    The owning shard searches its local cumsum and contributes the
    LOGICAL index + priority; a psum (each sample has exactly one
    contributor) replicates them. Same signature and weight formula as
    draw_per_indices; the sampling distribution matches, the exact index
    stream does not (different cumsum partition), so the sharded-PER test
    is statistical where the uniform parity oracle is exact."""
    from distributed_ddpg_tpu.parallel import mesh as mesh_lib

    n = mesh.shape["data"]

    def draw(key, priorities, size, shape, beta):
        k, b = shape

        def body(key, pr, size):
            sc = pr.shape[0]
            s = jax.lax.axis_index("data")
            cum = jnp.cumsum(pr)
            totals = jax.lax.all_gather(cum[-1], "data")
            cumtot = jnp.cumsum(totals)
            total = cumtot[-1]
            lo = jnp.where(s == 0, 0.0, cumtot[jnp.maximum(s - 1, 0)])
            hi = jnp.where(s == n - 1, jnp.inf, cumtot[s])
            u = (
                jnp.arange(b, dtype=jnp.float32)[None, :]
                + jax.random.uniform(key, (k, b))
            ) / b * total
            mine = (u >= lo) & (u < hi)
            loc = jnp.searchsorted(
                cum, (u - lo).reshape(-1), side="right"
            ).reshape(k, b)
            # Clamp to this shard's last LIVE slot, not its capacity: a
            # boundary-rounded u (fl(lo + tot) can exceed lo + cum[-1] by
            # an ulp, and u == total can reach the last shard) would
            # otherwise searchsort past the live region and select an
            # empty zero-priority slot — idx >= size with probs == 0,
            # whose (size * 1e-12)^-beta IS weight would crush the whole
            # batch's normalization. The live bound keeps the gathered
            # priority consistent with the returned index — the sharded
            # twin of draw_per_indices' jnp.minimum(idx, size - 1). A
            # shard with zero live rows has tot == 0 and never claims, so
            # the maximum(., 1) floor is never observable.
            live = jnp.maximum((size - s + n - 1) // n, 1)
            loc = jnp.minimum(
                loc.astype(jnp.int32), jnp.minimum(live - 1, sc - 1)
            )
            idx = jax.lax.psum(jnp.where(mine, loc * n + s, 0), "data")
            p = jax.lax.psum(jnp.where(mine, pr[loc], 0.0), "data")
            return idx, p, total

        idx, probs_raw, total = mesh_lib.shard_map(
            body, mesh,
            in_specs=(P(), P("data"), P()), out_specs=(P(), P(), P()),
        )(key, priorities, size)
        probs = probs_raw / jnp.maximum(total, 1e-12)
        weights = (
            size.astype(jnp.float32) * jnp.maximum(probs, 1e-12)
        ) ** (-beta)
        weights = weights / jnp.max(weights, axis=-1, keepdims=True)
        return idx, weights

    return draw


class DevicePrioritizedReplay(DeviceReplay):
    """Proportional PER with priorities resident in HBM (SURVEY.md §7 hard
    part (a) applied to PER; VERDICT.md round-1 Missing #4).

    The host PrioritizedReplay keeps a sum-tree on CPU, which forces the
    flagship path back to host sampling + per-chunk h2d transfers. Here the
    priority vector is a replicated f32[capacity] device array:

      - inserts stamp new rows with the running max priority (same
        every-transition-seen-once rule as the host buffer) inside a jitted
        scatter chained onto the storage insert;
      - sampling is draw_per_indices fused INTO the learner chunk
        (ShardedLearner.run_sample_chunk on a prioritized replay) — zero
        h2d, zero d2h for priorities;
      - priority updates scatter (|td|+eps)^alpha for the chunk's sampled
        indices at chunk end — the same once-per-chunk cadence the host
        path has (update_priorities is called once per after_chunk).

    Coalesced ingest stamps the whole super-block from the pre-insert ptr
    with the current max priority — exactly what k serial stamps with the
    same (learner-updated-only) max would do, so parity holds.

    Multi-host: priorities/max_priority are replicated like storage, and
    every update is computed from replicated inputs (state, key, td), so
    replicas stay identical with no extra collectives."""

    def __init__(
        self,
        capacity: int,
        obs_dim: int,
        act_dim: int,
        mesh: Optional[Mesh] = None,
        block_size: int = 4096,
        seed: int = 0,
        alpha: float = 0.6,
        eps: float = 1e-6,
        **kwargs,
    ):
        super().__init__(capacity, obs_dim, act_dim, mesh=mesh,
                         block_size=block_size, seed=seed, **kwargs)
        self.alpha = float(alpha)
        self.eps = float(eps)
        # Sharded mode: priorities shard over 'data' with the SAME strided
        # placement as storage (logical slot p -> shard p % N), so the
        # scatter/stamp index math is shared and the two arrays can never
        # disagree about a row's owner.
        vec_sharding = (
            NamedSharding(mesh, P("data") if self.sharded else P(None))
            if mesh is not None
            else None
        )
        scalar_sharding = NamedSharding(mesh, P()) if mesh is not None else None
        self._stamp_shardings = (vec_sharding, scalar_sharding)
        self.priorities = jnp.zeros((self.capacity,), jnp.float32)
        self.max_priority = jnp.ones((), jnp.float32)
        if vec_sharding is not None:
            self.priorities = jax.device_put(self.priorities, vec_sharding)
            self.max_priority = jax.device_put(self.max_priority, scalar_sharding)
        # One stamp program per super-block row count m (power-of-two
        # multiples of block_size, same bounded set as the inserts).
        self._stamp_cache = {}

    def _make_stamp_body(self, m: int):
        """Pure stamp body — (priorities, maxp, old_ptr) -> priorities —
        shared by the jitted standalone stamp and the fused-megastep
        composition (pure_stamp_fn)."""
        if self.sharded:
            # Sharded stamp: the landed positions are a contiguous
            # logical run starting at the N-aligned old_ptr, so each
            # shard stamps its own contiguous m/N local slots — the
            # priority twin of _get_insert_grouped, no collective.
            from distributed_ddpg_tpu.parallel import mesh as mesh_lib

            n, sc = self._n_shards, self._shard_cap

            def stamp_body(prios, maxp, old_ptr):
                start = old_ptr // n
                slots = (
                    start + jnp.arange(m // n, dtype=jnp.int32)
                ) % sc
                return prios.at[slots].set(maxp)

            return mesh_lib.shard_map(
                stamp_body, self._mesh,
                in_specs=(P("data"), P(), P()),
                out_specs=P("data"),
            )

        def stamp(prios, maxp, old_ptr):
            idx = (old_ptr + jnp.arange(m, dtype=jnp.int32)) % self.capacity
            return prios.at[idx].set(maxp)

        return stamp

    def pure_stamp_fn(self, m: int):
        """Pure (unjitted) max-priority stamp for m freshly-landed rows,
        for composition inside a larger jitted program (the fused
        megastep's in-program insert stamps exactly like
        _stamp_device_rows would after a standalone one)."""
        return self._make_stamp_body(m)

    def _get_stamp(self, m: int):
        fn = self._stamp_cache.get(m)
        if fn is None:
            vec_sharding, scalar_sharding = self._stamp_shardings
            kwargs = (
                dict(
                    in_shardings=(vec_sharding, scalar_sharding, scalar_sharding),
                    out_shardings=vec_sharding,
                )
                if vec_sharding is not None
                else {}
            )
            fn = jax.jit(
                self._make_stamp_body(m), donate_argnums=(0,), **kwargs
            )
            self._stamp_cache[m] = fn
        return fn

    def _ship(self, chunk: np.ndarray) -> None:
        old_ptr = self.ptr  # not donated by _insert; still valid after
        super()._ship(chunk)
        self.priorities = self._get_stamp(len(chunk))(
            self.priorities, self.max_priority, old_ptr
        )

    def _ship_global(self, local_rows: np.ndarray, k: int = 1) -> None:
        old_ptr = self.ptr
        super()._ship_global(local_rows, k=k)
        self.priorities = self._get_stamp(self._procs * k * self.block_size)(
            self.priorities, self.max_priority, old_ptr
        )

    def _stamp_device_rows(self, m: int, old_ptr) -> None:
        # Device-actor inserts (insert_device_rows) stamp like every other
        # source: the running max priority over the landed range, from the
        # pre-insert pointer.
        self.priorities = self._get_stamp(m)(
            self.priorities, self.max_priority, old_ptr
        )

    # --- state for the fused PER sampling learner path ---

    def per_state(self):
        return self.storage, self.size, self.priorities, self.max_priority

    def set_per_state(self, priorities, max_priority) -> None:
        """Install the updated priority vector returned by the learner's
        fused chunk (both already carry the replicated sharding). Callers
        must hold dispatch_lock across per_state -> dispatch ->
        set_per_state (parallel/learner.py does) — otherwise a concurrent
        shipper stamp between the read and this write would be lost and
        freshly-inserted rows would keep priority 0 forever."""
        self.priorities = priorities
        self.max_priority = max_priority

    # --- checkpoint support ---

    def _get_prio_reshard(self):
        """Jitted restore-time reshard for the priority vector — the 1-D
        twin of _get_reshard, sharing the strided ownership map so the
        priorities can never land on a different owner than their rows
        (the rebuild half of 'priority-tree rebuild': shard-local
        cumsums are recomputed from these slots at the next draw)."""
        if not self.sharded:
            raise ReplayUsageError(
                "prio reshard is the sharded-placement restore program"
            )
        fn = self._reshard_cache.get("prio")
        if fn is None:
            from distributed_ddpg_tpu.parallel import mesh as mesh_lib

            n, sc = self._n_shards, self._shard_cap

            def body(prios):
                s = jax.lax.axis_index("data")
                return prios[s + jnp.arange(sc, dtype=jnp.int32) * n]

            fn = jax.jit(
                mesh_lib.shard_map(
                    body, self._mesh, in_specs=(P(None),), out_specs=P("data")
                ),
                in_shardings=(NamedSharding(self._mesh, P(None)),),
                out_shardings=self._stamp_shardings[0],
            )
            self._reshard_cache["prio"] = fn
        return fn

    def state_dict(self):
        with self.dispatch_lock:
            state = super().state_dict()
            n = int(state["size"])
            prios = np.asarray(jax.device_get(self.priorities))
            if self.sharded:
                prios = self._to_logical_rows(prios)
            state["priorities"] = prios[:n].copy()
            state["max_priority"] = np.asarray(
                float(jax.device_get(self.max_priority))
            )
            return state

    def slice_state_dict(self):
        with self.dispatch_lock:
            out = super().slice_state_dict()
            if not (self.sharded and self._procs > 1):
                return out  # state_dict already carried the priorities
            n = int(out["size"])
            N, sc = self._n_shards, self._shard_cap
            # Priorities share the rows' strided owner map, so the slots
            # backing out["positions"] live in this process's priority
            # shards; index them through a position-keyed scratch vector
            # to reuse the base class's position ordering.
            scratch = np.zeros(self.capacity, np.float32)
            seen = set()
            for sh in self.priorities.addressable_shards:
                start = sh.index[0].start or 0
                if start in seen:
                    continue
                seen.add(start)
                sid = start // sc
                cnt = (n - sid + N - 1) // N if n > sid else 0
                if cnt <= 0:
                    continue
                scratch[sid + np.arange(cnt, dtype=np.int64) * N] = (
                    np.asarray(sh.data)[:cnt]
                )
            out["priorities"] = scratch[out["positions"]]
            out["max_priority"] = np.asarray(
                float(jax.device_get(self.max_priority)), np.float32
            )
            return out

    def load_state_dict(self, state) -> None:
        with self.dispatch_lock:
            super().load_state_dict(state)
            if "priorities" not in state:
                return
            n = int(state["size"])
            if self.sharded and self._procs > 1:
                # Elastic restore (the _load_state_multihost twin): feed
                # the full logical priority vector replicated, scatter
                # each shard's owned slots locally.
                full = np.zeros((self.capacity,), np.float32)
                full[:n] = np.asarray(state["priorities"], np.float32)
                rep = jax.device_put(
                    jnp.asarray(full), NamedSharding(self._mesh, P(None))
                )
                self.priorities = self._get_prio_reshard()(rep)
                self.max_priority = jax.device_put(
                    jnp.asarray(float(state["max_priority"]), jnp.float32),
                    self._stamp_shardings[1],
                )
                return
            prios = np.array(jax.device_get(self.priorities))
            if self.sharded:
                prios = self._to_logical_rows(prios)
            prios[:n] = state["priorities"]
            if self.sharded:
                prios = self._to_physical_rows(prios)
            vec_sharding = self._stamp_shardings[0]
            scalar = (
                NamedSharding(self._mesh, P()) if self._mesh is not None else None
            )
            self.priorities = jnp.asarray(prios)
            self.max_priority = jnp.asarray(
                float(state["max_priority"]), jnp.float32
            )
            if vec_sharding is not None:
                self.priorities = jax.device_put(self.priorities, vec_sharding)
                self.max_priority = jax.device_put(self.max_priority, scalar)


# ---------------------------------------------------------------------------
# program-contract analyzer hook (analysis/programs.py; docs/ANALYSIS.md
# "Layer 2")
# ---------------------------------------------------------------------------


def program_specs():
    """The donated insert/scatter/stamp program family, built over tiny
    rings (capacity 64, blocks of 8) — replicated and sharded placement
    both. The multi-host global inserts (all-gather beats) need a real
    multi-process pod and are exercised by the gloo chaos tests instead;
    this registry holds what one process can trace."""
    from distributed_ddpg_tpu.analysis.programs import (
        BuiltProgram,
        ProgramSpec,
        probe_mesh,
    )

    OWNER = "replay/device.py"
    M = 8  # rows per probe ship (one block)

    def insert():
        r = DeviceReplay(64, 3, 1, block_size=M, async_ship=False)
        block = np.zeros((M, r.width), np.float32)
        return BuiltProgram(r._insert, (r.storage, block, r.ptr, r.size), (0,))

    def insert_sharded():
        r = DeviceReplay(
            64, 3, 1, mesh=probe_mesh(), block_size=M, async_ship=False,
            replay_sharding="sharded",
        )
        block = jax.device_put(
            np.zeros((M, r.width), np.float32), r._block_sharding_sharded
        )
        return BuiltProgram(
            r._get_insert_grouped(M), (r.storage, block, r.ptr, r.size), (0,)
        )

    def insert_devrows_sharded():
        mesh = probe_mesh()
        r = DeviceReplay(
            64, 3, 1, mesh=mesh, block_size=M, async_ship=False,
            replay_sharding="sharded",
        )
        rows = jax.device_put(
            np.zeros((M, r.width), np.float32),
            NamedSharding(mesh, P(None, None)),
        )
        return BuiltProgram(
            r._get_insert_replrows(M), (r.storage, rows, r.ptr, r.size), (0,)
        )

    def stamp():
        r = DevicePrioritizedReplay(64, 3, 1, block_size=M, async_ship=False)
        return BuiltProgram(
            r._get_stamp(M), (r.priorities, r.max_priority, r.ptr), (0,)
        )

    def stamp_sharded():
        r = DevicePrioritizedReplay(
            64, 3, 1, mesh=probe_mesh(), block_size=M, async_ship=False,
            replay_sharding="sharded",
        )
        return BuiltProgram(
            r._get_stamp(M), (r.priorities, r.max_priority, r.ptr), (0,)
        )

    def reshard_sharded():
        # The elastic-pod restore scatter (docs/REPLAY_SHARDING.md
        # all-writer checkpoints): full logical ring replicated -> each
        # shard's owned positions. Not donated — restore-time only, and
        # the replicated input never aliases the sharded output.
        r = DeviceReplay(
            64, 3, 1, mesh=probe_mesh(), block_size=M, async_ship=False,
            replay_sharding="sharded",
        )
        rows = jax.device_put(
            np.zeros((64, r.width), np.float32),
            NamedSharding(r._mesh, P(None, None)),
        )
        return BuiltProgram(r._get_reshard(), (rows,), ())

    def per_reshard_sharded():
        r = DevicePrioritizedReplay(
            64, 3, 1, mesh=probe_mesh(), block_size=M, async_ship=False,
            replay_sharding="sharded",
        )
        prios = jax.device_put(
            np.zeros((64,), np.float32), NamedSharding(r._mesh, P(None))
        )
        return BuiltProgram(r._get_prio_reshard(), (prios,), ())

    return [
        ProgramSpec("replay.insert", OWNER, insert),
        ProgramSpec("replay.insert.sharded", OWNER, insert_sharded),
        ProgramSpec(
            "replay.insert.devrows.sharded", OWNER, insert_devrows_sharded
        ),
        ProgramSpec("replay.stamp", OWNER, stamp),
        ProgramSpec("replay.stamp.sharded", OWNER, stamp_sharded),
        ProgramSpec("replay.reshard.sharded", OWNER, reshard_sharded),
        ProgramSpec("replay.per.reshard.sharded", OWNER, per_reshard_sharded),
    ]
