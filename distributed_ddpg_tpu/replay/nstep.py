"""N-step return accumulator (D4PG, arXiv 1804.08617; SURVEY.md §5 notes this
is 'a buffer feature, not a parallelism strategy').

Transforms a raw per-env stream of (obs, action, reward, done) into n-step
transitions (obs_t, a_t, sum_{k<n} gamma^k r_{t+k}, gamma^n * (1-done),
obs_{t+n}) before they enter replay, so the learner's TD target stays a
single fused multiply-add regardless of n. Handles episode truncation: on
`done`, all pending partial windows are flushed with their shortened returns.

Vectorized over a batch of envs (one accumulator drives a whole vector env).
"""

from __future__ import annotations

from collections import deque
from typing import Iterator, Tuple

import numpy as np


class NStepAccumulator:
    def __init__(self, n: int, gamma: float, num_envs: int = 1):
        self.n = int(n)
        self.gamma = float(gamma)
        self.num_envs = int(num_envs)
        # Per-env deque of (obs, action, reward) awaiting their bootstrap.
        self._pending = [deque() for _ in range(self.num_envs)]

    def push(
        self, obs, action, reward, done, next_obs
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, float, float, np.ndarray]]:
        """Feed one vector-env step; yields completed n-step transitions as
        (obs, action, n_step_reward, discount, bootstrap_obs)."""
        obs = np.atleast_2d(obs)
        action = np.atleast_2d(action)
        reward = np.atleast_1d(reward)
        done = np.atleast_1d(done)
        next_obs = np.atleast_2d(next_obs)
        for e in range(self.num_envs):
            pend = self._pending[e]
            pend.append((obs[e], action[e], float(reward[e])))
            if len(pend) == self.n:
                yield self._emit(pend, next_obs[e], terminal=bool(done[e]), length=self.n)
                pend.popleft()
            if done[e]:
                # Flush remaining partial windows with shortened horizons.
                while pend:
                    yield self._emit(pend, next_obs[e], terminal=True, length=len(pend))
                    pend.popleft()

    def _emit(self, pend, bootstrap_obs, terminal: bool, length: int):
        r = 0.0
        for k in range(length):
            r += (self.gamma ** k) * pend[k][2]
        discount = 0.0 if terminal else self.gamma ** length
        o, a, _ = pend[0]
        return o, a, np.float32(r), np.float32(discount), bootstrap_obs

    def reset(self, env_index: int | None = None) -> None:
        if env_index is None:
            for p in self._pending:
                p.clear()
        else:
            self._pending[env_index].clear()
