"""Uniform replay: a structure-of-arrays numpy ring buffer (SURVEY.md §2 #5).

Parity with the reference's `replay_buffer.py` (CPU deque/ring, `add`,
`sample(N) -> stacked arrays` [DRIVER]) but TPU-feed-oriented:

- Preallocated contiguous SoA arrays, not a deque of tuples: `sample` is one
  fancy-index gather per field, already laid out for `jax.device_put` —
  no per-sample Python in the hot path (SURVEY.md §7 'hard parts (a)').
- Stores `discount = gamma^n * (1 - done)` folded by the n-step accumulator,
  so the learner's TD target is a single fused multiply-add.
- `state_dict()`/`load_state_dict()` make the buffer checkpointable
  (SURVEY.md §3.5 says the reference never checkpoints replay; we do).
- When the C++ native core is available (native/ directory) the sampling
  index generation and gathers can be delegated to it; the numpy path is the
  always-available fallback with identical semantics.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class UniformReplay:
    def __init__(self, capacity: int, obs_dim: int, act_dim: int, seed: int = 0):
        self.capacity = int(capacity)
        self._rng = np.random.default_rng(seed)
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.action = np.zeros((capacity, act_dim), np.float32)
        self.reward = np.zeros((capacity,), np.float32)
        self.discount = np.zeros((capacity,), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self._ptr = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def reward_sample(self, max_n: int = 100_000):
        """(reward, discount) columns, up to max_n rows — feeds the C51
        auto-support sizing (ops/support_auto.initial_bounds; the discount
        column marks terminal transitions, whose one-off rewards must not
        enter the persistent-reward bound).

        Evenly STRIDED over the whole live region, not the [:max_n]
        prefix: with a 1M-capacity ring a prefix is up to ~900k
        insertions stale, and the round-5 data-corroboration gate would
        refuse legitimate expansions against rewards the policy earned
        long ago (deterministic stride, so strict_sync replays and
        replicas see identical samples)."""
        n = min(self._size, max_n)
        if n == self._size:
            return self.reward[:n].copy(), self.discount[:n].copy()
        idx = np.linspace(0, self._size - 1, n).astype(np.int64)
        return self.reward[idx], self.discount[idx]

    def add_batch(self, obs, action, reward, discount, next_obs) -> np.ndarray:
        """Insert B transitions; returns the slots written (for PER subclass)."""
        obs = np.atleast_2d(obs)
        b = obs.shape[0]
        idx = (self._ptr + np.arange(b)) % self.capacity
        self.obs[idx] = obs
        self.action[idx] = np.atleast_2d(action)
        self.reward[idx] = np.asarray(reward, np.float32).reshape(b)
        self.discount[idx] = np.asarray(discount, np.float32).reshape(b)
        self.next_obs[idx] = np.atleast_2d(next_obs)
        self._ptr = int((self._ptr + b) % self.capacity)
        self._size = int(min(self._size + b, self.capacity))
        return idx

    def add(self, obs, action, reward, discount, next_obs) -> int:
        return int(self.add_batch(obs[None], action[None], [reward], [discount], next_obs[None])[0])

    def sample_indices(self, batch_size: int) -> np.ndarray:
        return self._rng.integers(0, self._size, size=batch_size)

    def gather(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {
            "obs": self.obs[idx],
            "action": self.action[idx],
            "reward": self.reward[idx],
            "discount": self.discount[idx],
            "next_obs": self.next_obs[idx],
            "weight": np.ones(len(idx), np.float32),
        }

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self.sample_indices(batch_size)
        out = self.gather(idx)
        out["indices"] = idx
        return out

    def update_priorities(self, indices, td_errors) -> None:
        """No-op for uniform replay (interface shared with PER)."""

    # --- checkpoint support (SURVEY.md §5 'Checkpoint / resume') ---

    def state_dict(self) -> Dict[str, np.ndarray]:
        n = self._size
        return {
            "obs": self.obs[:n].copy(),
            "action": self.action[:n].copy(),
            "reward": self.reward[:n].copy(),
            "discount": self.discount[:n].copy(),
            "next_obs": self.next_obs[:n].copy(),
            "ptr": np.asarray(self._ptr),
            "size": np.asarray(self._size),
        }

    def load_state_dict(self, state: Dict[str, np.ndarray]) -> None:
        n = int(state["size"])
        if n > self.capacity:
            raise ValueError(f"checkpointed size {n} exceeds capacity {self.capacity}")
        self.obs[:n] = state["obs"]
        self.action[:n] = state["action"]
        self.reward[:n] = state["reward"]
        self.discount[:n] = state["discount"]
        self.next_obs[:n] = state["next_obs"]
        self._ptr = int(state["ptr"]) % self.capacity
        self._size = n
