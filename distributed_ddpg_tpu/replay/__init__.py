from distributed_ddpg_tpu.replay.uniform import UniformReplay
from distributed_ddpg_tpu.replay.prioritized import PrioritizedReplay
from distributed_ddpg_tpu.replay.nstep import NStepAccumulator


def make_replay(config, obs_dim: int, act_dim: int):
    """Replay factory honoring config.prioritized (SURVEY.md §2 #5/#7)."""
    if config.prioritized:
        return PrioritizedReplay(
            capacity=config.replay_capacity,
            obs_dim=obs_dim,
            act_dim=act_dim,
            alpha=config.per_alpha,
            beta=config.per_beta,
            eps=config.per_eps,
            seed=config.seed,
        )
    return UniformReplay(
        capacity=config.replay_capacity,
        obs_dim=obs_dim,
        act_dim=act_dim,
        seed=config.seed,
    )


__all__ = ["UniformReplay", "PrioritizedReplay", "NStepAccumulator", "make_replay"]
