"""Host-side staging ring for replay ingest (docs/INGEST.md).

The seed's `DeviceReplay.add_packed` staged pending rows in a growing
numpy array via `np.concatenate([pending, block])` — every actor batch
re-copied ALL pending rows, an O(n^2) pattern that BENCH_r05 put on the
learner's critical path (t_ingest_ms = 1347 vs t_dispatch_ms = 670 at 8
virtual devices). This module replaces it with a preallocated [capacity,
D] float32 ring: push is one bounded memcpy into the tail, pop is one
bounded memcpy out of the head (two on wraparound), and nothing else is
ever touched. FIFO order is exact — the ingest parity tests assert the
shipped row stream is bit-identical to the seed's concatenate/slice
sequence.

The ring itself is NOT thread-safe; DeviceReplay serializes access under
its staging condition variable (the same lock its backpressure waits on).
"""

from __future__ import annotations

import numpy as np


class HostStagingRing:
    """Preallocated FIFO ring of packed [*, width] float32 rows.

    Capacity grows by doubling only when a push cannot fit even after the
    consumer has drained (rare: a single oversized add, or the multi-host
    buffering mode where rows leave only via the lockstep sync_ship) — the
    steady state never allocates.
    """

    def __init__(self, width: int, capacity_rows: int):
        if capacity_rows < 1:
            raise ValueError(f"capacity_rows must be >= 1, got {capacity_rows}")
        self.width = int(width)
        self._buf = np.zeros((int(capacity_rows), self.width), np.float32)
        self._head = 0          # next row to pop
        self._size = 0          # live rows

    def __len__(self) -> int:
        return self._size

    @property
    def capacity(self) -> int:
        return self._buf.shape[0]

    def _grow(self, need_rows: int) -> None:
        new_cap = self.capacity
        while new_cap < need_rows:
            new_cap *= 2
        new_buf = np.zeros((new_cap, self.width), np.float32)
        if self._size:
            new_buf[: self._size] = self.peek(self._size)
        self._buf = new_buf
        self._head = 0

    def push(self, rows: np.ndarray) -> None:
        """Append rows (any length) in FIFO order; grows if needed."""
        n = len(rows)
        if n == 0:
            return
        if rows.shape[1:] != (self.width,):
            raise ValueError(
                f"expected [*, {self.width}] rows, got {rows.shape}"
            )
        if self._size + n > self.capacity:
            self._grow(self._size + n)
        tail = (self._head + self._size) % self.capacity
        first = min(n, self.capacity - tail)
        self._buf[tail : tail + first] = rows[:first]
        if n > first:
            self._buf[: n - first] = rows[first:]
        self._size += n

    def pop(self, n: int) -> np.ndarray:
        """Remove and return the n oldest rows as an owned contiguous
        array (always a copy — the region may be overwritten by a push
        while an async device_put still reads the result)."""
        if n > self._size:
            raise ValueError(f"pop({n}) from ring holding {self._size}")
        out = self.peek(n)
        self._head = (self._head + n) % self.capacity
        self._size -= n
        return out

    def pop_into(self, n: int, out: np.ndarray) -> np.ndarray:
        """pop(), but into a caller-owned buffer (the transfer host-buffer
        pool, transfer/hostbuf.py) — same FIFO semantics, zero allocation."""
        if n > self._size:
            raise ValueError(f"pop_into({n}) from ring holding {self._size}")
        if out.shape != (n, self.width):
            raise ValueError(
                f"pop_into needs a [{n}, {self.width}] buffer, got {out.shape}"
            )
        first = min(n, self.capacity - self._head)
        out[:first] = self._buf[self._head : self._head + first]
        if n > first:
            out[first:] = self._buf[: n - first]
        self._head = (self._head + n) % self.capacity
        self._size -= n
        return out

    def peek(self, n: int) -> np.ndarray:
        """Copy of the n oldest rows without consuming them."""
        if n > self._size:
            raise ValueError(f"peek({n}) from ring holding {self._size}")
        first = min(n, self.capacity - self._head)
        if first == n:
            return self._buf[self._head : self._head + n].copy()
        out = np.empty((n, self.width), np.float32)
        out[:first] = self._buf[self._head :]
        out[first:] = self._buf[: n - first]
        return out

    def peek_cols(self, col: int, ncols: int, max_n: int) -> np.ndarray:
        """Copy of [min(len, max_n), ncols] — the oldest rows' column
        slice, without materializing whole rows (reward_sample reads just
        the (reward, discount) pair out of potentially large pendings)."""
        n = min(self._size, max_n)
        first = min(n, self.capacity - self._head)
        out = np.empty((n, ncols), np.float32)
        out[:first] = self._buf[self._head : self._head + first, col : col + ncols]
        if n > first:
            out[first:] = self._buf[: n - first, col : col + ncols]
        return out
