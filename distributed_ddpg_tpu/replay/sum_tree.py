"""Vectorized sum-tree for prioritized replay (SURVEY.md §2 #7).

Array-based complete binary tree (1-indexed; leaves at [cap, 2*cap)). All
operations are batched numpy — set/propagate and the stratified sampling
descent run as O(log C) *vector* ops, never per-sample Python loops. A C++
implementation with identical layout lives in native/replay_core.cpp; this is
the always-available fallback and the correctness oracle for it.
"""

from __future__ import annotations

import numpy as np


class SumTree:
    def __init__(self, capacity: int):
        # Round up to a power of two so the descent depth is uniform.
        self.capacity = 1 << (int(capacity) - 1).bit_length()
        self.depth = self.capacity.bit_length() - 1
        self.tree = np.zeros(2 * self.capacity, np.float64)

    @property
    def total(self) -> float:
        return float(self.tree[1])

    def set(self, indices: np.ndarray, priorities: np.ndarray) -> None:
        """Set leaf priorities and repair all ancestor sums (batched)."""
        indices = np.asarray(indices, np.int64)
        self.tree[self.capacity + indices] = np.asarray(priorities, np.float64)
        nodes = self.capacity + indices
        for _ in range(self.depth):
            nodes = np.unique(nodes >> 1)
            self.tree[nodes] = self.tree[2 * nodes] + self.tree[2 * nodes + 1]

    def get(self, indices: np.ndarray) -> np.ndarray:
        return self.tree[self.capacity + np.asarray(indices, np.int64)]

    def sample(self, values: np.ndarray) -> np.ndarray:
        """Descend the tree for each value in [0, total); returns leaf indices.
        Vectorized over the batch: one comparison per level."""
        v = np.asarray(values, np.float64).copy()
        idx = np.ones(v.shape, np.int64)
        for _ in range(self.depth):
            left = 2 * idx
            left_sum = self.tree[left]
            go_right = v >= left_sum
            v = np.where(go_right, v - left_sum, v)
            idx = np.where(go_right, left + 1, left)
        return idx - self.capacity

    def stratified_sample(self, batch_size: int, rng: np.random.Generator) -> np.ndarray:
        """PER's stratified scheme: one uniform draw per equal-mass segment."""
        bounds = np.linspace(0.0, self.total, batch_size + 1)
        u = rng.uniform(bounds[:-1], bounds[1:])
        # Guard the upper edge against fp roundoff pushing past `total`.
        u = np.minimum(u, np.nextafter(self.total, 0.0))
        return self.sample(u)
