"""Numerical-health guardrails: on-device divergence detection and
bad-batch quarantine for the learner step (docs/RESILIENCE.md 'numerical
health'; the math-side counterpart of the process-resilience layers from
PRs 4-6).

A NaN gradient, an exploding critic, or a poisoned replay row silently
corrupts the params — and then every checkpoint written afterwards — long
before any host-visible symptom. D4PG-scale runs (PAPERS.md,
arXiv 1804.08617) and always-on Podracer fleets (arXiv 2104.06272) assume
weeks unattended, and this repo has already logged one real divergence
incident (the seed-1 C51 support runaway, ops/support_auto.py docstring).
So the learner itself carries a cheap jitted health probe:

  - **finite checks** on the step's TD errors, grad norms/losses, and the
    UPDATED float params — a non-finite anywhere marks the step bad;
  - **EWMA z-score anomaly detection** on critic loss and critic grad
    norm — a finite-but-absurd step (loss spike, grad explosion) marks
    the step bad once the EWMA has warmed up;
  - **bad-batch quarantine**: a bad step's update is DROPPED on device
    (params/opt state/targets keep their pre-step values; only the step
    counter advances, so the deterministic noise streams never re-draw),
    its TD errors are zeroed (a NaN TD must not poison PER priorities),
    and its metrics are zeroed out of the chunk mean;
  - **bad-row capture**: rows of the sampled minibatch that are
    themselves non-finite are counted and their replay indices recorded
    (first GUARD_BAD_IDX per chunk) so the host can attribute them to an
    ingest source and quarantine repeat offenders through the actor-pool
    machinery (train.py).

Everything lives in a small replicated `GuardState` pytree threaded
through the chunk scan (parallel/learner.py); the host reads ONE tiny
health vector per chunk (HEALTH_KEYS — a handful of int32 counters, one
d2h) and never pulls params or grads. All decisions are computed from
replicated inputs, so every data-parallel replica takes the identical
skip/keep branch and a mesh can never fork on a guardrail.

Deterministic chaos (faults.py `numeric:*` grammar): `numeric:grad:nan@K`
and `numeric:loss:spike@K` poison the K-th guarded step's minibatch
inside the program, keyed on `GuardState.total` — a MONOTONIC step clock
that rollback deliberately does not rewind (a step-keyed fault that
re-fired after every rollback would trap the run in its own repair).

With `config.guardrails=False` none of this exists: the chunk programs
are built exactly as before this module existed (the parity test pins
bit-identical outputs), and the wrapper is never constructed.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

# EWMA decay for the running loss/grad-norm statistics. ~1/ALPHA steps of
# memory: long enough to smooth minibatch noise, short enough to track the
# (nonstationary) loss scale of early training.
EWMA_ALPHA = 0.05

# Bad replay indices captured per chunk (fixed-size jit output; -1 pads).
GUARD_BAD_IDX = 32

# Reward scale applied by the numeric:loss:spike injection — finite but
# far outside any EWMA band, so it must trip the z-score detector and
# ONLY that detector (everything stays representable in f32).
SPIKE_SCALE = 1.0e6


class GuardState(NamedTuple):
    """Replicated device-resident probe state, threaded through the scan.

    `total` is the monotonic guarded-step clock (never rewound — numeric
    fault ordinals and the host's cumulative-counter deltas key on it).
    The four EWMA fields reset on rollback (the restored params have the
    OLD loss scale; statistics accumulated on the diverged trajectory
    would mis-score the first post-rollback steps); the counters are
    CUMULATIVE across rollbacks so the host's delta accounting never sees
    a counter move backwards."""

    loss_mean: jnp.ndarray   # f32: EWMA of critic_loss
    loss_var: jnp.ndarray    # f32: EW variance of critic_loss
    gnorm_mean: jnp.ndarray  # f32: EWMA of critic_grad_norm
    gnorm_var: jnp.ndarray   # f32: EW variance of critic_grad_norm
    warm: jnp.ndarray        # i32: clean observations absorbed by the EWMA
    total: jnp.ndarray       # i32: guarded steps processed (monotonic)
    nonfinite: jnp.ndarray   # i32: steps skipped for a non-finite value
    spikes: jnp.ndarray      # i32: steps skipped for a z-score anomaly
    skipped: jnp.ndarray     # i32: total updates dropped (>= the two above)
    bad_rows: jnp.ndarray    # i32: non-finite sampled replay rows seen


# Order of the per-chunk health vector (int32[len(HEALTH_KEYS)]) — the one
# word the host reads each chunk. Counters are cumulative; train.py
# differences consecutive reads.
HEALTH_KEYS = ("total", "nonfinite", "spikes", "skipped", "bad_rows")


def init_guard_state(
    total: int = 0,
    nonfinite: int = 0,
    spikes: int = 0,
    skipped: int = 0,
    bad_rows: int = 0,
) -> GuardState:
    """Fresh probe state. Rollback passes the preserved counter values so
    the cumulative contract survives the EWMA reset."""
    f = lambda v: jnp.asarray(v, jnp.float32)
    i = lambda v: jnp.asarray(v, jnp.int32)
    return GuardState(
        loss_mean=f(0.0), loss_var=f(0.0),
        gnorm_mean=f(0.0), gnorm_var=f(0.0),
        warm=i(0), total=i(total),
        nonfinite=i(nonfinite), spikes=i(spikes),
        skipped=i(skipped), bad_rows=i(bad_rows),
    )


def health_vector(g: GuardState) -> jnp.ndarray:
    """Pack the cumulative counters into the per-chunk health word."""
    return jnp.stack(
        [g.total, g.nonfinite, g.spikes, g.skipped, g.bad_rows]
    ).astype(jnp.int32)


def _tree_all_finite(tree) -> jnp.ndarray:
    """True iff every float leaf of `tree` is fully finite (int leaves —
    step counters, Adam counts — are finite by construction and skipped)."""
    ok = jnp.asarray(True)
    for leaf in jax.tree.leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def batch_row_health(packed: jnp.ndarray, idx: Optional[jnp.ndarray]):
    """Pre-step screen of the raw sampled rows.

    packed: f32[K, B, D] gathered minibatch rows; idx: i32[K, B] replay
    indices (None on the host-fed path, where the sampler owns indices).
    Returns (pre_bad f32-free bool[K], bad_count i32, bad_idx i32[GUARD_BAD_IDX])
    — per-step "this step's batch contains a non-finite row" flags, the
    total bad-row count, and the first GUARD_BAD_IDX offending replay
    indices (-1 padded) for the host's source attribution."""
    row_bad = jnp.logical_not(jnp.all(jnp.isfinite(packed), axis=-1))  # [K,B]
    pre_bad = jnp.any(row_bad, axis=-1)                                # [K]
    bad_count = jnp.sum(row_bad).astype(jnp.int32)
    if idx is None:
        return pre_bad, bad_count, jnp.full((GUARD_BAD_IDX,), -1, jnp.int32)
    flat_bad = row_bad.reshape(-1)
    flat_idx = idx.reshape(-1).astype(jnp.int32)
    # First-K bad positions via top_k over the bad mask (deterministic,
    # O(n log k)); non-bad slots mask to -1.
    k = min(GUARD_BAD_IDX, flat_bad.shape[0])
    vals, pos = jax.lax.top_k(flat_bad.astype(jnp.float32), k)
    got = jnp.where(vals > 0, flat_idx[pos], -1)
    if k < GUARD_BAD_IDX:
        got = jnp.concatenate(
            [got, jnp.full((GUARD_BAD_IDX - k,), -1, jnp.int32)]
        )
    return pre_bad, bad_count, got


def make_guarded_step(
    step_fn,
    zmax: float,
    warmup: int,
    inject: Optional[Dict[str, Tuple[int, ...]]] = None,
):
    """Wrap a pure learner step (state, batch) -> StepOutput with the
    health probe. Returns

        guarded(state, gstate, batch, pre_bad) ->
            (new_state, new_gstate, td_errors, metrics)

    where `pre_bad` is this step's raw-row screen from batch_row_health
    (a scalar bool; pass False when rows were screened elsewhere). The
    update is dropped when the step is bad; the TrainState step counter
    still advances so the fold_in(seed, step) noise streams never
    re-draw. `inject` maps 'grad'/'loss' to guarded-step ordinals
    (faults.numeric_steps) and is baked into the traced program — absent
    (the production case) the injection code does not exist."""
    inject = inject or {}
    zmax = float(zmax)
    warmup = int(warmup)

    def _fires(ordinal, ats):
        fire = jnp.asarray(False)
        for at in ats:
            fire = jnp.logical_or(fire, ordinal == jnp.int32(at))
        return fire

    def guarded(state, g: GuardState, batch, pre_bad):
        ordinal = g.total + 1
        if inject.get("grad"):
            fire = _fires(ordinal, inject["grad"])
            batch = batch._replace(
                obs=batch.obs + jnp.where(fire, jnp.nan, 0.0)
            )
        if inject.get("loss"):
            fire = _fires(ordinal, inject["loss"])
            batch = batch._replace(
                reward=batch.reward * jnp.where(fire, SPIKE_SCALE, 1.0)
            )

        out = step_fn(state, batch)
        m = out.metrics
        closs = m["critic_loss"]
        gnorm = m["critic_grad_norm"]
        finite_ok = jnp.logical_and(
            jnp.all(jnp.isfinite(out.td_errors)),
            jnp.logical_and(
                _tree_all_finite(
                    (closs, m["actor_loss"], gnorm, m["actor_grad_norm"])
                ),
                jnp.logical_and(
                    _tree_all_finite(out.state.actor_params),
                    _tree_all_finite(out.state.critic_params),
                ),
            ),
        )
        # One-sided z-scores (divergence is always UP): armed only after
        # `warmup` clean observations, and never on a non-finite step
        # (NaN z-scores must not double-count).
        armed = jnp.logical_and(g.warm >= warmup, finite_ok)
        z_loss = (closs - g.loss_mean) * jax.lax.rsqrt(g.loss_var + 1e-12)
        z_g = (gnorm - g.gnorm_mean) * jax.lax.rsqrt(g.gnorm_var + 1e-12)
        spike = jnp.logical_and(
            armed, jnp.logical_or(z_loss > zmax, z_g > zmax)
        )
        bad = jnp.logical_or(
            pre_bad, jnp.logical_or(jnp.logical_not(finite_ok), spike)
        )

        # Drop the update on a bad step: every leaf keeps its pre-step
        # value except the step counter (deterministic noise streams key
        # on it and must not re-draw the exact draw that just failed).
        kept = jax.tree.map(
            lambda old, new: jnp.where(bad, old, new), state, out.state
        )
        kept = kept._replace(step=out.state.step)
        td = jnp.where(bad, 0.0, out.td_errors)
        metrics = {k: jnp.where(bad, 0.0, v) for k, v in m.items()}

        # EWMA absorbs only clean, finite steps — a spike that updated its
        # own baseline would mask the follow-on steps of a divergence.
        upd = jnp.logical_not(bad)

        def ewma(mean, var, x):
            diff = x - mean
            incr = EWMA_ALPHA * diff
            new_mean = jnp.where(upd, mean + incr, mean)
            new_var = jnp.where(
                upd, (1.0 - EWMA_ALPHA) * (var + diff * incr), var
            )
            return new_mean, new_var

        loss_mean, loss_var = ewma(g.loss_mean, g.loss_var, closs)
        gnorm_mean, gnorm_var = ewma(g.gnorm_mean, g.gnorm_var, gnorm)
        new_g = GuardState(
            loss_mean=loss_mean, loss_var=loss_var,
            gnorm_mean=gnorm_mean, gnorm_var=gnorm_var,
            warm=g.warm + upd.astype(jnp.int32),
            total=ordinal,
            nonfinite=g.nonfinite
            + jnp.logical_not(finite_ok).astype(jnp.int32),
            spikes=g.spikes + spike.astype(jnp.int32),
            skipped=g.skipped + bad.astype(jnp.int32),
            bad_rows=g.bad_rows,
        )
        return kept, new_g, td, metrics

    return guarded
