"""PodSupervisor: the generation loop that turns the typed exit-code
contract (exits.py) from documentation into behavior (ISSUE 19;
docs/RESILIENCE.md exit-code matrix; docs/OPERATIONS.md runbook).

One *generation* = one spawned set of training processes sharing a
fresh coordinator port. The supervisor waits for the generation to die,
classifies the collected exit codes, and acts:

  all 0                     -> done; supervisor exits 0
  any 77 (numeric)          -> params presumed poisoned: REFUSE past the
                               `max_numeric` relaunch budget and raise a
                               typed SupervisorGaveUp (report on disk)
  any 78 (shrink-ready)     -> relaunch at M = members - dead(signal),
                               immediately, no backoff — the PR-17 slice
                               adoption makes the shrunk pod productive
  grow resize (self-initiated SIGTERM) -> relaunch at the restored
                               membership
  anything else (70/75/76/untyped) -> relaunch-in-place with exponential
                               backoff; repeated fast failures trip the
                               crash-loop circuit breaker (the
                               actors/pool.py quarantine-window pattern)
                               -> SupervisorGaveUp

While the pod runs below full strength the HealthProber polls the lost
slots' /healthz; once a slot clears the K-consecutive + hysteresis gate
(and the running generation is at least `grow_defer_s` old — a resize
must not thrash a generation still starting up), the supervisor performs
the checkpoint-boundary stop-the-world resize: SIGTERM the running pod
(each child takes its exit-75 emergency checkpoint), then relaunch at
the grown membership. This is the honest first rung toward live in-run
resize — membership only changes at a checkpoint boundary, so the
resume election + slice adoption do all the correctness work.

Stdlib only; no jax. Every deadline routes through SupervisorConfig.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from distributed_ddpg_tpu import exits
from distributed_ddpg_tpu.metrics import SupervisorStats
from distributed_ddpg_tpu.obs.probe import probe_healthz
from distributed_ddpg_tpu.supervisor.events import EventLog
from distributed_ddpg_tpu.supervisor.prober import HealthProber

# Child-reaping poll cadence (sub-second by design: the loop is also the
# stop-signal and grow-trigger check).
_POLL_S = 0.2


class SupervisorGaveUp(Exception):
    """Typed terminal verdict: the supervisor refuses to keep
    relaunching (crash-loop breaker, numeric budget, or generation
    budget). Carries the structured report it wrote — the CLI exits
    EXIT_SUPERVISOR_GAVE_UP and points at `report_path`."""

    def __init__(self, reason: str, report: Dict[str, Any],
                 report_path: str = ""):
        super().__init__(f"supervisor gave up: {reason}")
        self.reason = reason
        self.report = report
        self.report_path = report_path


@dataclasses.dataclass
class SupervisorConfig:
    """Knobs, grouped by the decision they govern. Durations are seconds;
    every blocking wait in core/prober routes through one of these (the
    timeout-discipline lint rule holds for supervisor code too)."""

    procs: int                       # N: full-strength membership
    # -- relaunch/backoff/breaker (the actors/pool.py quarantine shape) --
    backoff_base_s: float = 1.0      # first backoff; doubles per failure
    backoff_max_s: float = 60.0      # exponential cap
    breaker_failures: int = 5        # >= this many failing generations...
    breaker_window_s: float = 300.0  # ...within this window -> give up
    healthy_run_s: float = 60.0      # generations older than this reset
                                     # the consecutive-failure count
    max_numeric: int = 0             # 77 relaunch budget (default refuse)
    max_generations: int = 0         # hard generation cap (0 = unbounded)
    # -- generation teardown --
    drain_grace_s: float = 60.0      # first exit -> peers get this long
    kill_grace_s: float = 10.0       # SIGTERM -> SIGKILL escalation
    # -- health-gated rejoin --
    probe_host: str = "127.0.0.1"
    probe_port_base: int = 0         # slot i probed at base+i; 0 = no grow
    probe_interval_s: float = 2.0
    probe_healthy_k: int = 3
    probe_hysteresis_s: float = 10.0
    grow_defer_s: float = 30.0       # min generation age before a resize
    # -- artifacts --
    event_log: str = ""              # JSONL event stream ('' = memory only)
    report_path: str = ""            # gave-up report ('' = derive/cwd)
    child_log_dir: str = ""          # per-child stdout+stderr captures


def backoff_for(consecutive: int, base_s: float, max_s: float) -> float:
    """Exponential backoff before relaunch attempt `consecutive` (1-based
    count of consecutive failing generations): base * 2^(n-1), capped."""
    if consecutive <= 0:
        return 0.0
    return min(float(max_s), float(base_s) * (2.0 ** (consecutive - 1)))


def classify_generation(
    codes: Sequence[Optional[int]], grow_pending: bool = False
) -> str:
    """Pure exit-code dispatch for one finished generation -> one of
    'success' | 'numeric' | 'resize' | 'shrink' | 'relaunch'.

    Priority order IS the contract: a numeric abort (77) anywhere
    outranks everything — those params are poisoned no matter what the
    peers report. A self-initiated resize (we sent the SIGTERMs; exits
    carry no new information) outranks shrink. Shrink needs BOTH an
    explicit 78 (a survivor verified a complete slice set) and at least
    one peer actually dead-by-signal — all-78 with nobody dead means the
    whole pod aborted in lockstep and should relaunch at full strength.
    """
    codes = list(codes)
    if any(c == exits.EXIT_NUMERIC for c in codes):
        return "numeric"
    if grow_pending:
        return "resize"
    if all(c == exits.EXIT_OK for c in codes):
        return "success"
    if any(c == exits.EXIT_POD_SHRINK for c in codes):
        dead = sum(1 for c in codes if c is None or c < 0)
        if 0 < dead < len(codes):
            return "shrink"
    return "relaunch"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Child:
    def __init__(self, proc_index: int, popen: subprocess.Popen, log_fh):
        self.proc_index = proc_index
        self.popen = popen
        self.log_fh = log_fh
        self.reported = False  # exit event emitted


# command_builder(proc, nprocs, port, gen) -> (argv, env_overrides)
CommandBuilder = Callable[[int, int, int, int], Tuple[List[str], Dict[str, str]]]


class PodSupervisor:
    """The generation loop (module docstring). `command_builder` renders
    one child's argv + env from (proc_index, nprocs, coordinator_port,
    generation) — the CLI builds it from a `{proc}/{nprocs}/{port}/{gen}`
    template; tests pass closures. `probe_targets` overrides the
    probe_port_base-derived slot->(host, port) map (drills point slots at
    stand-in peers)."""

    def __init__(
        self,
        config: SupervisorConfig,
        command_builder: CommandBuilder,
        *,
        probe_targets: Optional[Dict[int, Tuple[str, int]]] = None,
        probe_fn=probe_healthz,
        events: Optional[EventLog] = None,
        stats: Optional[SupervisorStats] = None,
    ):
        if config.procs < 1:
            raise ValueError(f"procs must be >= 1, got {config.procs}")
        self.cfg = config
        self._build = command_builder
        self.events = events if events is not None else EventLog(config.event_log)
        self.stats = stats if stats is not None else SupervisorStats()
        self._stop = threading.Event()
        self._prober: Optional[HealthProber] = None
        self._probe_fn = probe_fn
        if probe_targets is not None:
            self._probe_targets = dict(probe_targets)
        elif config.probe_port_base:
            self._probe_targets = {
                i: (config.probe_host, config.probe_port_base + i)
                for i in range(config.procs)
            }
        else:
            self._probe_targets = {}

    # -- external control ------------------------------------------------

    def request_stop(self) -> None:
        """Preemption of the supervisor itself (SIGTERM/SIGINT in the
        CLI): SIGTERM the running generation, exit EXIT_PREEMPTED."""
        self._stop.set()

    # -- internals -------------------------------------------------------

    def _emit_probe(self, slot: int, transition: str, result) -> None:
        if transition == "flap":
            self.stats.record_probe_flap()
        elif transition == "ready":
            self.stats.record_probe_ready()
        self.events.emit(
            "probe", slot=slot, transition=transition,
            state=result.state, detail=result.detail[:200],
        )

    def _ensure_prober(self) -> Optional[HealthProber]:
        if self._prober is None and self._probe_targets:
            self._prober = HealthProber(
                self._probe_targets,
                interval_s=self.cfg.probe_interval_s,
                healthy_k=self.cfg.probe_healthy_k,
                hysteresis_s=self.cfg.probe_hysteresis_s,
                probe_fn=self._probe_fn,
                on_transition=self._emit_probe,
            )
            self._prober.start()
        return self._prober

    def _spawn(self, gen: int, members: int, port: int) -> List[_Child]:
        children: List[_Child] = []
        try:
            self._spawn_into(children, gen, members, port)
        except OSError:
            # Partial spawn: never leak the siblings that DID start.
            self._signal_all(children, signal.SIGKILL)
            raise
        self.stats.record_generation(members)
        return children

    def _spawn_into(
        self, children: List[_Child], gen: int, members: int, port: int
    ) -> None:
        for proc in range(members):
            argv, env_over = self._build(proc, members, port, gen)
            env = dict(os.environ)
            env.update(env_over)
            log_fh = None
            out = err = None
            if self.cfg.child_log_dir:
                os.makedirs(self.cfg.child_log_dir, exist_ok=True)
                log_fh = open(
                    os.path.join(
                        self.cfg.child_log_dir,
                        f"gen{gen}_proc{proc}.log",
                    ),
                    "ab",
                )
                out = err = log_fh
            popen = subprocess.Popen(
                argv, env=env, stdout=out, stderr=err,
                start_new_session=True,
            )
            children.append(_Child(proc, popen, log_fh))
            self.events.emit(
                "spawn", gen=gen, proc=proc, members=members, pid=popen.pid
            )

    @staticmethod
    def _signal_all(children: List[_Child], sig: int) -> None:
        for c in children:
            if c.popen.poll() is None:
                try:
                    c.popen.send_signal(sig)
                except OSError:
                    pass  # exited between poll and signal

    def _wait_generation(
        self, children: List[_Child], gen: int, members: int, t_start: float
    ) -> Tuple[List[Optional[int]], bool, int]:
        """Reap one generation. Returns (codes, grow_pending, grow_to).

        Teardown ladder once the first child exits on its own: peers get
        drain_grace_s to take their OWN typed exits (the pod abort
        machinery needs the collective deadline to fire), then SIGTERM,
        then kill_grace_s, then SIGKILL. A self-initiated stop (grow
        resize or request_stop) starts at the SIGTERM rung directly."""
        cfg = self.cfg
        first_exit_t: Optional[float] = None
        term_sent_t: Optional[float] = None
        killed = False
        grow_pending = False
        grow_to = members
        while True:
            alive = 0
            for c in children:
                rc = c.popen.poll()
                if rc is None:
                    alive += 1
                elif not c.reported:
                    c.reported = True
                    if c.log_fh is not None:
                        c.log_fh.close()
                    self.events.emit(
                        "exit", gen=gen, proc=c.proc_index, code=rc,
                        code_name=exits.describe(rc),
                        runtime_s=round(time.monotonic() - t_start, 3),
                    )
            if alive == 0:
                return (
                    [c.popen.returncode for c in children],
                    grow_pending,
                    grow_to,
                )
            now = time.monotonic()
            exited = len(children) - alive
            if exited and first_exit_t is None:
                first_exit_t = now
            # Supervisor preemption: forward the SIGTERM once.
            if self._stop.is_set() and term_sent_t is None:
                self._signal_all(children, signal.SIGTERM)
                term_sent_t = now
            # Health-gated grow: only while running degraded, only once
            # the generation is old enough to own a checkpoint boundary,
            # and never on a generation already winding down.
            if (
                not grow_pending
                and term_sent_t is None
                and exited == 0
                and members < cfg.procs
                and self._prober is not None
                and now - t_start >= cfg.grow_defer_s
            ):
                ready = self._prober.ready_slots()
                if ready:
                    grow_pending = True
                    grow_to = min(cfg.procs, members + len(ready))
                    self.events.emit(
                        "grow_initiated", gen=gen, members=members,
                        target=grow_to, slots=ready,
                    )
                    self._signal_all(children, signal.SIGTERM)
                    term_sent_t = now
            # Escalation ladder.
            if term_sent_t is not None:
                if not killed and now - term_sent_t >= cfg.kill_grace_s:
                    self._signal_all(children, signal.SIGKILL)
                    killed = True
            elif first_exit_t is not None:
                if now - first_exit_t >= cfg.drain_grace_s:
                    self._signal_all(children, signal.SIGTERM)
                    term_sent_t = now
            self._stop.wait(_POLL_S)

    def _give_up(
        self, reason: str, gen: int, members: int,
        codes: Sequence[Optional[int]], detail: str,
    ) -> SupervisorGaveUp:
        report = {
            "reason": reason,
            "detail": detail,
            "generation": gen,
            "members": members,
            "target": self.cfg.procs,
            "last_exit_codes": list(codes),
            "last_exit_names": [exits.describe(c) for c in codes],
            "counters": self.stats.snapshot(),
        }
        path = self.cfg.report_path
        if not path:
            path = (
                self.cfg.event_log + ".gave_up.json"
                if self.cfg.event_log
                else "supervisor_gave_up.json"
            )
        try:
            with open(path, "w") as fh:
                json.dump(report, fh, indent=2)
        except OSError:
            path = ""
        self.events.emit("gave_up", reason=reason, report=path,
                         gen=gen, detail=detail)
        return SupervisorGaveUp(reason, report, path)

    def _finish(self, code: int) -> int:
        if self._prober is not None:
            self._prober.stop()
        self.events.emit(
            "final", code=code, code_name=exits.describe(code),
            **self.stats.snapshot(),
        )
        self.events.close()
        return code

    # -- the generation loop --------------------------------------------

    def run(self) -> int:
        """Supervise until the pod completes (returns 0), the supervisor
        itself is preempted (returns EXIT_PREEMPTED), or a give-up path
        raises SupervisorGaveUp (after emitting final/report)."""
        cfg = self.cfg
        self.events.emit(
            "start", target=cfg.procs,
            config={
                k: getattr(cfg, k)
                for k in (
                    "backoff_base_s", "backoff_max_s", "breaker_failures",
                    "breaker_window_s", "healthy_run_s", "max_numeric",
                    "max_generations", "drain_grace_s", "kill_grace_s",
                    "probe_healthy_k", "probe_hysteresis_s", "grow_defer_s",
                )
            },
        )
        gen = 0
        members = cfg.procs
        consecutive = 0               # consecutive failing generations
        numeric_relaunches = 0
        window: deque = deque()       # failure timestamps (breaker)
        try:
            while True:
                gen += 1
                if cfg.max_generations and gen > cfg.max_generations:
                    self.stats.record_breaker_trip()
                    raise self._give_up(
                        "generation_budget", gen, members, [],
                        f"max_generations={cfg.max_generations} exhausted",
                    )
                if members < cfg.procs:
                    prober = self._ensure_prober()
                    if prober is not None:
                        prober.set_watched(range(members, cfg.procs))
                t_start = time.monotonic()
                try:
                    children = self._spawn(gen, members, _free_port())
                except OSError as e:
                    # A spawn failure is a failing generation, not a
                    # supervisor crash: it feeds backoff + breaker.
                    self.events.emit(
                        "exit", gen=gen, proc=-1, code=None,
                        code_name=f"spawn_error:{e!r}"[:200], runtime_s=0.0,
                    )
                    codes: List[Optional[int]] = [None]
                    grow_pending = False
                    grow_to = members
                else:
                    codes, grow_pending, grow_to = self._wait_generation(
                        children, gen, members, t_start
                    )
                runtime = time.monotonic() - t_start
                if self._stop.is_set():
                    return self._finish(exits.EXIT_PREEMPTED)
                action = classify_generation(codes, grow_pending)
                if action == "success":
                    return self._finish(exits.EXIT_OK)
                if action == "numeric":
                    if numeric_relaunches >= cfg.max_numeric:
                        self.stats.record_numeric_refusal()
                        self.events.emit(
                            "numeric_refusal", gen=gen,
                            budget=cfg.max_numeric,
                        )
                        raise self._give_up(
                            "numeric_abort", gen, members, codes,
                            "exit 77: params presumed poisoned — inspect "
                            "guardrail_* counters before relaunching "
                            f"(budget max_numeric={cfg.max_numeric} spent)",
                        )
                    numeric_relaunches += 1
                    self.stats.record_relaunch()
                    self.events.emit(
                        "relaunch", gen=gen, members=members,
                        reason=f"numeric_abort "
                               f"({numeric_relaunches}/{cfg.max_numeric})",
                    )
                    continue
                if action == "resize":
                    old, members = members, grow_to
                    consecutive = 0
                    self.stats.record_grow()
                    self.events.emit(
                        "grow", gen=gen, members=old, target=members
                    )
                    if self._prober is not None:
                        self._prober.set_watched(
                            range(members, cfg.procs)
                        )
                    continue
                if action == "shrink":
                    dead = sum(1 for c in codes if c is None or c < 0)
                    old, members = members, max(1, members - dead)
                    consecutive = 0
                    self.stats.record_shrink()
                    self.events.emit(
                        "shrink", gen=gen, members=old, target=members
                    )
                    continue
                # relaunch (70/75/76/untyped crash) with backoff+breaker.
                now = time.monotonic()
                if runtime < cfg.healthy_run_s:
                    consecutive += 1
                    window.append(now)
                    while window and now - window[0] > cfg.breaker_window_s:
                        window.popleft()
                    if (
                        cfg.breaker_failures
                        and len(window) >= cfg.breaker_failures
                    ):
                        self.stats.record_breaker_trip()
                        self.events.emit(
                            "breaker", gen=gen,
                            failures=len(window),
                            window_s=cfg.breaker_window_s,
                        )
                        raise self._give_up(
                            "crash_loop", gen, members, codes,
                            f"{len(window)} failing generations within "
                            f"{cfg.breaker_window_s:.0f}s "
                            f"(breaker_failures={cfg.breaker_failures})",
                        )
                else:
                    # A long-lived generation died: fresh incident, not a
                    # crash loop — restart the consecutive count.
                    consecutive = 0
                self.stats.record_relaunch()
                self.events.emit(
                    "relaunch", gen=gen, members=members,
                    reason=",".join(exits.describe(c) for c in codes),
                )
                wait = backoff_for(
                    consecutive, cfg.backoff_base_s, cfg.backoff_max_s
                )
                if wait > 0:
                    self.stats.record_backoff(wait)
                    self.events.emit(
                        "backoff", gen=gen, backoff_s=round(wait, 3),
                        consecutive=consecutive,
                    )
                    if self._stop.wait(wait):
                        return self._finish(exits.EXIT_PREEMPTED)
        except SupervisorGaveUp:
            self._finish(exits.EXIT_SUPERVISOR_GAVE_UP)
            raise
