"""Background rejoin gate: poll lost peers' /healthz until they are
credibly back (docs/OPERATIONS.md "Health-gated rejoin").

A peer slot is READY to rejoin only when BOTH damping conditions hold:

  * K consecutive healthy probes (`healthy_k`) — one lucky scrape of a
    crash-looping host must not trigger a pod-wide stop-the-world resize;
  * the slot has been continuously healthy for `hysteresis_s` — a host
    that flaps at just-under-K cadence still never clears the gate,
    because every unhealthy probe resets BOTH the count and the clock.

The prober only watches the "missing tail" slots the supervisor hands it
(watch/unwatch as membership changes); probing is pull-only and
side-effect-free, so a wedged probe target costs one probe timeout per
interval, nothing more. The thread is a daemon and owns no state the
supervisor's generation loop reads without the lock.

`poll_once()` is the whole decision step, factored out of the thread
loop so tests drive it synchronously with a fake probe_fn — determinism
over sleep-and-hope.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from distributed_ddpg_tpu.obs.probe import ProbeResult, probe_healthz


class _SlotState:
    def __init__(self, now: float):
        self.consecutive = 0          # healthy probes in a row
        self.last_unhealthy = now     # hysteresis clock anchor
        self.was_healthy = False      # for up/flap transition events
        self.ready_reported = False   # emit `ready` once per watch


class HealthProber(threading.Thread):
    """Watch lost-peer slots; `ready_slots()` is the grow gate's input.

    `targets` maps slot index -> (host, port) for every slot of the FULL
    pod; `on_transition(slot, transition, result)` fires on up/flap/ready
    edges only (event-log noise control). `probe_fn` is injectable for
    tests (signature of obs.probe.probe_healthz).
    """

    def __init__(
        self,
        targets: Dict[int, Tuple[str, int]],
        *,
        interval_s: float,
        healthy_k: int,
        hysteresis_s: float,
        probe_fn: Callable[[str, int], ProbeResult] = probe_healthz,
        on_transition: Optional[Callable[[int, str, ProbeResult], None]] = None,
    ):
        super().__init__(name="pod-supervisor-prober", daemon=True)
        self._targets = dict(targets)
        self._interval_s = float(interval_s)
        self._healthy_k = max(1, int(healthy_k))
        self._hysteresis_s = float(hysteresis_s)
        self._probe_fn = probe_fn
        self._on_transition = on_transition
        self._watched: Dict[int, _SlotState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()

    # -- supervisor-facing API (any thread) ------------------------------

    def set_watched(self, slots) -> None:
        """Reconcile the watch set to exactly `slots` (the missing tail
        after a membership change). Newly watched slots start cold;
        slots that remain watched KEEP their damping state."""
        want = set(int(s) for s in slots)
        now = time.monotonic()
        with self._lock:
            for s in list(self._watched):
                if s not in want:
                    del self._watched[s]
            for s in want:
                if s not in self._watched:
                    self._watched[s] = _SlotState(now)

    def ready_slots(self) -> List[int]:
        now = time.monotonic()
        with self._lock:
            return sorted(
                s for s, st in self._watched.items()
                if self._is_ready(st, now)
            )

    def stop(self) -> None:
        self._stop.set()

    # -- decision step ---------------------------------------------------

    def _is_ready(self, st: _SlotState, now: float) -> bool:
        return (
            st.consecutive >= self._healthy_k
            and now - st.last_unhealthy >= self._hysteresis_s
        )

    def poll_once(self) -> None:
        """Probe every watched slot once and update its damping state."""
        with self._lock:
            slots = list(self._watched.keys())
        for slot in slots:
            target = self._targets.get(slot)
            if target is None:
                continue
            result = self._probe_fn(target[0], target[1])
            now = time.monotonic()
            transition = ""
            with self._lock:
                st = self._watched.get(slot)
                if st is None:
                    continue  # unwatched while we probed
                if result.healthy:
                    st.consecutive += 1
                    if not st.was_healthy:
                        transition = "up"
                    st.was_healthy = True
                    if self._is_ready(st, now) and not st.ready_reported:
                        st.ready_reported = True
                        transition = "ready"
                else:
                    if st.was_healthy:
                        transition = "flap"
                    st.consecutive = 0
                    st.last_unhealthy = now
                    st.was_healthy = False
                    st.ready_reported = False
            if transition and self._on_transition is not None:
                self._on_transition(slot, transition, result)

    def run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self._interval_s)
