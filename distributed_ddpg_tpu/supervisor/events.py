"""The supervisor's JSONL event stream (docs/OPERATIONS.md "Supervisor
event log"): one line per decision-bearing transition, same file format
as the training JSONL so `tools.runs summarize` renders it (the
supervision timeline) and `tools.runs merge-trace`-style consumers need
no second parser.

Record shape (every record):

    {"kind": "supervisor", "event": "<name>", "wall_time": <s since
     supervisor start>, "t_unix": <epoch>, ...event fields}

Event names and their extra fields:

    start           target, config (flattened knobs)
    spawn           gen, proc, members, pid
    exit            gen, proc, code, code_name, runtime_s
    shrink          gen, members (old), target (new membership)
    grow_initiated  gen, members, target (stop-the-world SIGTERM sent)
    grow            gen, members (old), target (new membership)
    relaunch        gen, members, reason
    backoff         gen, backoff_s, consecutive
    breaker         gen, failures, window_s
    numeric_refusal gen, budget
    probe           slot, transition (up|flap|ready), state, detail
    gave_up         reason, report (path)
    final           exit code + the full supervisor_* counter snapshot

The final record carries the cumulative `supervisor_*` counters
(metrics.SupervisorStats), so one `tail -1` answers "how turbulent was
this soak". Events are also kept in memory (`self.events`) — the tests'
and the gave-up report's source of truth without re-reading the file.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List


class EventLog:
    """Append-only JSONL writer + in-memory mirror. path='' disables the
    file (events still accumulate in memory). Thread-safe: the prober
    thread emits probe transitions while the generation loop emits
    exits."""

    def __init__(self, path: str = ""):
        self.path = path
        self.events: List[Dict[str, Any]] = []
        self._t0 = time.time()
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1) if path else None

    def emit(self, event: str, **fields: Any) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "kind": "supervisor",
            "event": event,
            "wall_time": round(time.time() - self._t0, 3),
            "t_unix": round(time.time(), 3),
        }
        rec.update(fields)
        with self._lock:
            self.events.append(rec)
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")
        return rec

    def by_event(self, name: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [e for e in self.events if e.get("event") == name]

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            fh.close()
