"""Process-level pod supervisor (docs/OPERATIONS.md supervisor runbook;
docs/RESILIENCE.md exit-code matrix): spawns the N training processes,
dispatches on their typed exit codes (exits.py), and drives the elastic
kill -> shrink -> health-gated grow cycle with no operator in the loop.

  core.py    PodSupervisor: the generation loop, exit-code dispatch,
             exponential backoff, crash-loop circuit breaker, and the
             stop-the-world grow resize
  prober.py  HealthProber: background /healthz polling of lost peers
             with K-consecutive-healthy + hysteresis flap damping
  events.py  the supervisor's own JSONL event stream (spawn/exit/shrink/
             grow/backoff/breaker), rendered by `tools.runs summarize`
             as a supervision timeline

Stdlib only — the supervisor must outlive device-runtime crashes, so it
never imports jax (same rule as tools/runs.py).
"""

from distributed_ddpg_tpu.supervisor.core import (
    PodSupervisor,
    SupervisorConfig,
    SupervisorGaveUp,
    classify_generation,
)
from distributed_ddpg_tpu.supervisor.events import EventLog
from distributed_ddpg_tpu.supervisor.prober import HealthProber

__all__ = [
    "PodSupervisor",
    "SupervisorConfig",
    "SupervisorGaveUp",
    "classify_generation",
    "EventLog",
    "HealthProber",
]
