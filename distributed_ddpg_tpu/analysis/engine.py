"""Rule engine core: module loading, rule registry, suppressions, output.

The engine is deliberately boring: parse every Python file under the
target root with stdlib `ast`, hand each module to every registered
per-module rule, then hand the whole module set (plus the docs tree) to
the cross-file rules. Rules yield `Finding`s; the engine matches them
against `# lint: ok(<rule>)` suppressions and renders JSON + human text.

Suppression grammar (docs/ANALYSIS.md):

    some_call()  # lint: ok(rule-name): reason the invariant holds here
    # lint: ok(rule-a, rule-b): one comment may cover several rules

A suppression covers findings of the named rule(s) whose statement span
includes its physical line (so the comment may sit on any line of a
multi-line call), or — for a comment-only line — findings on the next
non-comment line. The
reason is MANDATORY: a reasonless suppression does not suppress anything
and is itself reported (rule `bad-suppression`), so "silenced because
annoying" can never land without leaving a reviewable sentence behind.
A suppression that matches no finding is reported too (rule
`unused-suppression`): stale escapes must not outlive the code they
excused.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import time
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Engine-level pseudo-rules (not in the registry; always on).
PARSE_ERROR = "parse-error"
BAD_SUPPRESSION = "bad-suppression"
UNUSED_SUPPRESSION = "unused-suppression"

_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*ok\(\s*([a-z0-9_-]+(?:\s*,\s*[a-z0-9_-]+)*)\s*\)"
    r"\s*(?:[:—-]\s*(\S.*))?\s*$"
)


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location. `suppressed` /
    `suppression_reason` are filled in by the engine after matching
    `# lint: ok(...)` comments; rules never set them."""

    rule: str
    path: str          # relative to the lint root, '/'-separated
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    message: str
    end_line: int = 0  # last line of the flagged statement (0: same as line)
    suppressed: bool = False
    suppression_reason: str = ""
    # exact=True: suppressions must sit on the flagged node's OWN lines —
    # no widening to the enclosing statement. For findings anchored to one
    # element of a large literal (a *Stats snapshot dict key, a COMPONENTS
    # tuple entry), where statement-span matching would let one per-field
    # suppression silently cover every sibling's future drift.
    exact: bool = False

    def to_json(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        del d["exact"]  # engine-internal matching detail, not schema
        return d

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col} [{self.rule}]{tag} {self.message}"


@dataclasses.dataclass
class Suppression:
    path: str
    line: int           # line the comment sits on
    covers_line: int    # line whose findings it suppresses
    rules: Tuple[str, ...]
    reason: str
    used: bool = False


class Module:
    """One parsed source file: path (relative to the lint root), raw text,
    line list, and the ast.Module tree (None when the file failed to
    parse — the engine reports `parse-error` and rules skip it).

    `relpath` (root-relative) is what findings report; `rulepath` is what
    path-scoped rules key on: the path relative to the innermost
    `distributed_ddpg_tpu` package dir when one appears in relpath, else
    relpath itself. This keeps the parallel/multihost.py exemption, the
    serve/-prefix typed-error scoping, and the metrics.py lookups correct
    under ANY --root (repo root, package dir, or a bare fixture tree)."""

    def __init__(self, root: Path, path: Path):
        self.abspath = path
        self.relpath = path.relative_to(root).as_posix()
        parts = path.relative_to(root).parts
        self.rulepath = self.relpath
        if "distributed_ddpg_tpu" in parts[:-1]:
            i = len(parts) - 1 - parts[::-1].index("distributed_ddpg_tpu")
            self.rulepath = "/".join(parts[i + 1:])
        self.text = path.read_text(encoding="utf-8", errors="replace")
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        self._stmt_spans: Optional[List[Tuple[int, int]]] = None
        try:
            self.tree = ast.parse(self.text, filename=str(path))
        except SyntaxError as e:
            self.parse_error = e

    def stmt_span(self, line: int) -> Tuple[int, int]:
        """(first, last) line of the innermost SIMPLE statement whose span
        contains `line` — the span suppressions match against, so a finding
        anchored to one expression of a multi-line call (donation-safety's
        read node) is still covered by a comment on the closing-paren line
        or a comment-only line above the statement. Simple statements only:
        extending through compound spans (a class or `if` body) would let a
        suppression deep inside the body mask a header-anchored finding —
        exactly what the class-header anchoring of observability-drift
        findings exists to prevent."""
        if self._stmt_spans is None:
            spans: List[Tuple[int, int]] = []
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    if isinstance(node, ast.stmt) and not hasattr(node, "body"):
                        spans.append(
                            (node.lineno, node.end_lineno or node.lineno)
                        )
            self._stmt_spans = spans
        best, best_size = (line, line), None
        for a, b in self._stmt_spans:
            if a <= line <= b and (best_size is None or b - a < best_size):
                best, best_size = (a, b), b - a
        return best

    def finding(self, rule: str, node: ast.AST, message: str,
                exact: bool = False) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule,
            path=self.relpath,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            end_line=getattr(node, "end_lineno", None) or line,
            exact=exact,
        )

    def suppressions(self) -> List[Suppression]:
        # Real COMMENT tokens only (tokenize, not a line regex): the
        # grammar documented inside a docstring — like the engine's own —
        # must not register as a live suppression.
        out: List[Suppression] = []
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline
            ))
        except (tokenize.TokenError, IndentationError):
            return out
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            i = tok.start[0]
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                # An ok-marker that doesn't parse (missing colon, empty
                # rule list, junk after the paren): record it with no
                # rules so the engine reports it instead of letting the
                # author believe the line is covered.
                if re.search(r"#\s*lint:\s*ok", tok.string):
                    out.append(Suppression(self.relpath, i, i, (), ""))
                continue
            line = self.lines[i - 1]
            rules = tuple(r.strip() for r in m.group(1).split(","))
            reason = (m.group(2) or "").strip()
            # Comment-only line: the suppression covers the next
            # non-comment line (the statement it annotates).
            covers = i
            if line.strip().startswith("#"):
                j = i + 1
                while j <= len(self.lines) and (
                    not self.lines[j - 1].strip()
                    or self.lines[j - 1].strip().startswith("#")
                ):
                    j += 1
                covers = min(j, len(self.lines))
            out.append(Suppression(self.relpath, i, covers, rules, reason))
        return out


class LintContext:
    """What cross-file rules see: every parsed module plus the docs tree.
    `docs_root` is the directory holding OBSERVABILITY.md / RESILIENCE.md
    (repo `docs/`); None when the caller linted a bare file set with no
    docs alongside — doc-coupled rules then stay silent."""

    def __init__(self, root: Path, modules: Sequence[Module],
                 docs_root: Optional[Path]):
        self.root = root
        self.modules = list(modules)
        self.docs_root = docs_root

    def module(self, rulepath: str) -> Optional[Module]:
        for m in self.modules:
            if m.rulepath == rulepath:
                return m
        return None

    def doc_text(self, name: str) -> Optional[str]:
        if self.docs_root is None:
            return None
        p = self.docs_root / name
        if not p.is_file():
            return None
        return p.read_text(encoding="utf-8", errors="replace")


class Rule:
    """Base class: subclass, set `name`/`doc`, implement one (or both) of
    `check_module` / `check_project`, and decorate with @register."""

    name = ""
    doc = ""

    def check_module(self, module: Module, ctx: LintContext) -> Iterable[Finding]:
        return ()

    def check_project(self, ctx: LintContext) -> Iterable[Finding]:
        return ()


RULES: List[Rule] = []


def register(cls):
    """Class decorator: instantiate and add to the global registry. Rule
    names must be unique kebab-case — the suppression grammar and the
    --rules CLI filter key on them."""
    inst = cls()
    if not inst.name or any(r.name == inst.name for r in RULES):
        raise ValueError(f"rule {cls.__name__} needs a unique name")
    RULES.append(inst)
    return cls


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    files: int
    elapsed_s: float
    rules: List[str]

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def to_json(self) -> Dict[str, object]:
        return {
            "version": 1,
            "rules": self.rules,
            "counts": {
                "files": self.files,
                "findings": len(self.unsuppressed),
                "suppressed": len(self.findings) - len(self.unsuppressed),
            },
            "elapsed_s": round(self.elapsed_s, 3),
            "findings": [f.to_json() for f in self.findings],
        }


def _is_test_file(root: Path, path: Path) -> bool:
    """Root-relative test-tree check: the rules enforce NON-TEST hot-path
    discipline (a test's `fired.wait(2)` is fine, and the deliberately
    dirty fixture trees under tests/lint_fixtures/ must never gate a
    repo-root run). Relative to the LINT root, so a fixture tree linted
    AS its own root — whose absolute path contains tests/ — still lints
    in full."""
    try:
        rel = path.relative_to(root)
    except ValueError:
        return False
    return (
        "tests" in rel.parts[:-1]
        or rel.name.startswith("test_")
        or rel.name == "conftest.py"
    )


def _collect_files(root: Path, paths: Sequence[Path]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        if p.is_dir():
            # Directory scans skip test trees; a test file named
            # EXPLICITLY still lints (the author asked for it).
            files.extend(
                q for q in sorted(p.rglob("*.py"))
                if "__pycache__" not in q.parts
                and not _is_test_file(root, q)
            )
        elif p.suffix == ".py":
            files.append(p)
    # De-dup while keeping order (a file passed twice lints once).
    seen = set()
    out = []
    for f in files:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            out.append(f)
    return out


def run_lint(
    root: Path,
    paths: Optional[Sequence[Path]] = None,
    docs_root: Optional[Path] = None,
    rule_names: Optional[Sequence[str]] = None,
) -> LintResult:
    """Lint every .py under `paths` (default: `root` itself). `root`
    anchors relative paths — rules scope on them (e.g. typed-error only
    fires under serve/, transfer/, ...), so fixture trees replicate the
    package layout under their own root. Returns every finding, matched
    against suppressions; callers decide the exit code from
    `result.unsuppressed`."""
    t0 = time.perf_counter()
    root = root.resolve()
    files = _collect_files(root, [p.resolve() for p in (paths or [root])])
    modules = [Module(root, f) for f in files]

    active = [
        r for r in RULES
        if rule_names is None or r.name in rule_names
    ]
    ctx = LintContext(root, modules, docs_root)

    findings: List[Finding] = []
    suppressions: List[Suppression] = []
    for mod in modules:
        if mod.parse_error is not None:
            findings.append(Finding(
                rule=PARSE_ERROR, path=mod.relpath,
                line=mod.parse_error.lineno or 1, col=0,
                message=f"file does not parse: {mod.parse_error.msg}",
            ))
            continue
        suppressions.extend(mod.suppressions())
        for rule in active:
            findings.extend(rule.check_module(mod, ctx))
    for rule in active:
        findings.extend(rule.check_project(ctx))

    # Match suppressions. Reasonless suppressions never suppress — they
    # become findings themselves, and the finding they failed to cover
    # stays live: the gate holds until a reason is written down.
    mod_by_path = {m.relpath: m for m in modules}
    for f in findings:
        # The flagged node's own span, widened to its innermost simple
        # statement: a finding anchored to one sub-expression must still
        # accept the comment on the statement's closing-paren line (or a
        # comment-only line above the statement). `exact` findings skip
        # the widening — one per-field suppression inside a snapshot dict
        # must not cover its siblings.
        start, end = f.line, max(f.end_line, f.line)
        mod = mod_by_path.get(f.path)
        if mod is not None and not f.exact:
            a, b = mod.stmt_span(f.line)
            start, end = min(start, a), max(end, b)
        for s in suppressions:
            if (
                s.path == f.path
                and start <= s.covers_line <= end
                and f.rule in s.rules
            ):
                if not s.reason:
                    s.used = True  # targeted, but invalid: flag it below
                    continue
                s.used = True
                f.suppressed = True
                f.suppression_reason = s.reason
                break
    all_names = {r.name for r in RULES}
    active_names = {r.name for r in active}
    for s in suppressions:
        unknown = [r for r in s.rules if r not in all_names]
        if not s.rules:
            findings.append(Finding(
                rule=BAD_SUPPRESSION, path=s.path, line=s.line, col=0,
                message=(
                    "malformed suppression — it covers nothing; grammar: "
                    "`# lint: ok(<rule>): <why the invariant holds here>`"
                ),
            ))
        elif unknown:
            findings.append(Finding(
                rule=BAD_SUPPRESSION, path=s.path, line=s.line, col=0,
                message=(
                    f"suppression names unknown rule(s) "
                    f"{', '.join(unknown)} — a typo here silently "
                    "suppresses nothing (known: "
                    f"{', '.join(sorted(all_names))})"
                ),
            ))
        elif not s.reason:
            findings.append(Finding(
                rule=BAD_SUPPRESSION, path=s.path, line=s.line, col=0,
                message=(
                    f"suppression of {', '.join(s.rules)} has no reason — "
                    "grammar: `# lint: ok(<rule>): <why the invariant "
                    "holds here>`"
                ),
            ))
        elif not s.used and all(r in active_names for r in s.rules):
            # Only a FULL-registry run (or one covering every rule the
            # comment names) can prove a suppression stale: under a
            # --rules subset the inactive rule simply never fired.
            findings.append(Finding(
                rule=UNUSED_SUPPRESSION, path=s.path, line=s.line, col=0,
                message=(
                    f"suppression of {', '.join(s.rules)} matches no "
                    "finding — the violation it excused is gone; delete it"
                ),
            ))

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(
        findings=findings,
        files=len(modules),
        elapsed_s=time.perf_counter() - t0,
        rules=[r.name for r in active],
    )


def git_changed_files(root: Path, ref: str) -> Optional[List[str]]:
    """Absolute paths of files changed vs `ref` — working-tree diff plus
    untracked (new files must lint before their first commit). None when
    git is unusable (not a repo, bad ref): callers error loudly, a gate
    that can't see the diff must not read as green. Pure subprocess, so
    the --changed-only fast path never imports anything heavy."""
    import subprocess

    def run(cwd: Path, *cmd: str) -> Optional[str]:
        try:
            proc = subprocess.run(
                ["git", "-C", str(cwd), *cmd],
                capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        return proc.stdout if proc.returncode == 0 else None

    top = run(root, "rev-parse", "--show-toplevel")
    if top is None:
        return None
    repo = Path(top.strip())
    # Both listings must be toplevel-relative to join against `repo`, so
    # both run AT the toplevel: `ls-files --others` always prints
    # cwd-relative paths, and `diff --name-only` does too under
    # `diff.relative=true` (from a `root` deeper in the repo either would
    # silently mis-join and drop every changed file).
    diff = run(repo, "diff", "--name-only", ref)
    untracked = run(repo, "ls-files", "--others", "--exclude-standard")
    if diff is None or untracked is None:
        return None
    names = [
        line.strip()
        for line in (diff + "\n" + untracked).splitlines()
        if line.strip()
    ]
    seen = set()
    out = []
    for n in names:
        p = str(repo / n)
        if p not in seen:
            seen.add(p)
            out.append(p)
    return out


def render_human(result: LintResult) -> str:
    out = [f.render() for f in result.findings]
    n_bad = len(result.unsuppressed)
    n_sup = len(result.findings) - n_bad
    out.append(
        f"{result.files} files, {len(result.rules)} rules, "
        f"{n_bad} finding{'s' if n_bad != 1 else ''} "
        f"({n_sup} suppressed) in {result.elapsed_s:.2f}s"
    )
    return "\n".join(out)


def write_json(result: LintResult, path: Path) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(result.to_json(), indent=1) + "\n",
                    encoding="utf-8")
