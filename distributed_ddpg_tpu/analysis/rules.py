"""The repo-specific rule set (docs/ANALYSIS.md has the catalog).

Every rule encodes one architectural invariant a previous PR paid for.
They are deliberately narrow: each matches the concrete AST shape of the
bug class it guards, not a general style opinion — a finding should read
as "this line can reproduce a known outage", never as taste.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from distributed_ddpg_tpu.analysis.engine import (
    Finding,
    LintContext,
    Module,
    Rule,
    register,
)

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as 'a.b.c'; None for anything with a
    non-name root (subscripts, calls)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_FOLDABLE_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.Div: lambda a, b: a / b if b else None,
}


def numeric_literal(node: ast.AST) -> Optional[float]:
    """The value of a literal int/float expression (incl. unary minus and
    constant-only arithmetic like `10 * 60` — the natural spelling of a
    600 s deadline must not slip past timeout-discipline); None for
    names, calls, and anything genuinely computed."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = numeric_literal(node.operand)
        return None if inner is None else -inner
    if isinstance(node, ast.BinOp):
        fold = _FOLDABLE_BINOPS.get(type(node.op))
        left = numeric_literal(node.left)
        right = numeric_literal(node.right)
        if fold is None or left is None or right is None:
            return None
        return fold(left, right)
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return float(node.value)
    return None


def keyword_arg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _in_package_dirs(relpath: str, dirs: Sequence[str]) -> bool:
    return any(relpath.startswith(d + "/") for d in dirs)


# ---------------------------------------------------------------------------
# 1. collective-discipline
# ---------------------------------------------------------------------------

_MULTIHOST_MODULE = "parallel/multihost.py"
_COLLECTIVE_LEAVES = (
    "psum", "pmean", "pmax", "pmin",
    "all_gather", "all_to_all", "ppermute",
)
_COLLECTIVE_LAX = tuple("lax." + leaf for leaf in _COLLECTIVE_LEAVES)
# Modules allowed to BUILD collectives into jitted programs: the mesh /
# learner-program layer and the fused device ops. Everywhere else a raw
# lax collective is either dead code or a host-side hang waiting for a
# deadline that only multihost.py provides.
_COLLECTIVE_BUILDER_DIRS = ("parallel", "ops")


@register
class CollectiveDiscipline(Rule):
    """Every host-initiated DCN collective must ride the audited,
    deadline-guarded entry points in parallel/multihost.py (PR 6): a raw
    multihost_utils / jax.distributed call anywhere else reintroduces the
    eternal-gloo-block failure mode PodPeerLost exists to kill. Raw lax
    collectives (psum & co) are confined to the jit-building layers
    (parallel/, ops/) — outside a jitted program they are a different
    bug (traced-op-outside-trace) with the same fix: go through the
    framework."""

    name = "collective-discipline"
    doc = (
        "DCN collectives only via parallel/multihost.py; raw lax "
        "collectives only in the jit-building layers (parallel/, ops/)"
    )

    def check_module(self, module: Module, ctx: LintContext) -> Iterable[Finding]:
        if module.rulepath == _MULTIHOST_MODULE or module.tree is None:
            return
        # Resolve import bindings first, so `from jax.lax import psum` /
        # `from jax import lax as l` can't smuggle a collective past the
        # spelled-out `lax.psum` match.
        direct: Set[str] = set()
        lax_mods: Set[str] = {"lax", "jax.lax"}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == "jax.lax":
                    for a in node.names:
                        if a.name in _COLLECTIVE_LEAVES:
                            direct.add(a.asname or a.name)
                elif node.module == "jax":
                    for a in node.names:
                        if a.name == "lax" and a.asname:
                            lax_mods.add(a.asname)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax.lax" and a.asname:
                        lax_mods.add(a.asname)
        yield from self._walk(module, module.tree, 0, direct, lax_mods)

    def _walk(self, module: Module, node: ast.AST, fn_depth: int,
              direct: Set[str], lax_mods: Set[str]) -> Iterable[Finding]:
        for child in ast.iter_child_nodes(node):
            d = fn_depth
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                d += 1
            yield from self._check_node(module, child, fn_depth, direct,
                                        lax_mods)
            yield from self._walk(module, child, d, direct, lax_mods)

    def _check_node(self, module: Module, node: ast.AST, fn_depth: int,
                    direct: Set[str], lax_mods: Set[str]) -> Iterable[Finding]:
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith("jax.experimental.multihost_utils"):
                    yield module.finding(
                        self.name, node,
                        "import of jax.experimental.multihost_utils "
                        "outside parallel/multihost.py — use "
                        "multihost.allgather_scalar / beat_allgather "
                        "(deadline-guarded, PodPeerLost-typed)",
                    )
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            names = {a.name for a in node.names}
            if mod.startswith("jax.experimental.multihost_utils") or (
                mod == "jax.experimental" and "multihost_utils" in names
            ):
                yield module.finding(
                    self.name, node,
                    "import of jax.experimental.multihost_utils outside "
                    "parallel/multihost.py — use the audited multihost "
                    "entry points instead",
                )
        elif isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            if name.endswith("distributed.initialize") or \
                    name == "distributed.shutdown" or \
                    name.endswith("jax.distributed.shutdown"):
                yield module.finding(
                    self.name, node,
                    f"{name}() outside parallel/multihost.py — the pod "
                    "bootstrap must stay idempotent and centralized "
                    "(multihost.initialize)",
                )
            elif name.startswith("multihost_utils."):
                yield module.finding(
                    self.name, node,
                    f"raw {name}() call — an unguarded DCN collective "
                    "blocks forever on peer loss; route through "
                    "multihost.allgather_scalar / call_with_deadline",
                )
            else:
                leaf = name.rsplit(".", 1)[-1]
                prefix = name.rsplit(".", 1)[0] if "." in name else ""
                is_collective = (
                    any(name == c or name.endswith("." + c)
                        for c in _COLLECTIVE_LAX)
                    or name in direct
                    or (leaf in _COLLECTIVE_LEAVES and prefix in lax_mods)
                )
                # fn_depth >= 2 ⇒ inside a def nested in another def: the
                # shard_map/jit program-body closure shape, which is a
                # jit-building site wherever it lives.
                if is_collective and not _in_package_dirs(
                    module.rulepath, _COLLECTIVE_BUILDER_DIRS
                ) and fn_depth < 2:
                    yield module.finding(
                        self.name, node,
                        f"raw {leaf}() outside the "
                        "jit-building layers (parallel/, ops/) — "
                        "collectives belong inside the compiled "
                        "learner/mesh programs",
                    )


# ---------------------------------------------------------------------------
# 2. timeout-discipline
# ---------------------------------------------------------------------------

# Literals >= this many seconds are deadlines (must be named knobs);
# smaller literals are poll cadences inside re-checking loops, which are
# the documented idiom (prefetch/batcher condvar ticks).
TIMEOUT_LITERAL_FLOOR_S = 1.0

_BLOCKING_ATTRS = ("result", "get", "wait", "join", "sleep")


@register
class TimeoutDiscipline(Rule):
    """No inline literal deadline on a blocking wait (PR 10: a hardcoded
    `ticket.result(timeout=600)` stalled a wedged pod for 10 silent
    minutes). Deadlines must be named — a config knob, a multihost-derived
    bound (beat_result_timeout_s), or a documented module constant — so
    every wait's budget is auditable in one place. Sub-second literals are
    poll cadences inside re-checking loops and stay allowed."""

    name = "timeout-discipline"
    doc = (
        "no literal timeout >= 1s in .result()/.get()/.wait()/.join()/"
        "time.sleep() — route through a named knob"
    )

    def check_module(self, module: Module, ctx: LintContext) -> Iterable[Finding]:
        if module.tree is None:
            return
        # Bare-name bindings of the blocking callables (`from time import
        # sleep`, `from concurrent.futures import wait`): same semantics
        # as their attribute forms, same rule.
        bare: Dict[str, str] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == "time":
                    for a in node.names:
                        if a.name == "sleep":
                            bare[a.asname or a.name] = "sleep"
                elif node.module == "concurrent.futures":
                    for a in node.names:
                        if a.name == "wait":
                            bare[a.asname or a.name] = "futures_wait"
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id in bare:
                sem = bare[func.id]
                kw = keyword_arg(node, "timeout")
                if kw is not None:
                    value = numeric_literal(kw)
                elif sem == "sleep" and node.args:
                    value = numeric_literal(node.args[0])
                elif sem == "futures_wait" and len(node.args) >= 2:
                    value = numeric_literal(node.args[1])
                else:
                    value = None
                if value is not None and value >= TIMEOUT_LITERAL_FLOOR_S:
                    yield module.finding(
                        self.name, node,
                        f"literal {value:g}s timeout in {func.id}() — "
                        "name it (config knob, "
                        "multihost.beat_result_timeout_s, or a documented "
                        "module constant); inline deadlines are how the "
                        "600s silent stall shipped",
                    )
                continue
            if not isinstance(func, ast.Attribute):
                continue
            attr = func.attr
            if attr not in _BLOCKING_ATTRS:
                continue
            value: Optional[float] = None
            kw = keyword_arg(node, "timeout")
            if kw is not None:
                value = numeric_literal(kw)
            elif attr == "get":
                # queue.get's positionals are (block, timeout): the
                # deadline is args[1], and only when args[0] is a literal
                # bool — `d.get(key, default)` must never read as one.
                if len(node.args) >= 2 and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, bool):
                    value = numeric_literal(node.args[1])
            elif node.args:
                value = numeric_literal(node.args[0])
            if value is not None and value >= TIMEOUT_LITERAL_FLOOR_S:
                target = dotted(func) or f"<expr>.{attr}"
                yield module.finding(
                    self.name, node,
                    f"literal {value:g}s timeout in {target}() — name it "
                    "(config knob, multihost.beat_result_timeout_s, or a "
                    "documented module constant); inline deadlines are how "
                    "the 600s silent stall shipped",
                )


# ---------------------------------------------------------------------------
# 3. donation-safety
# ---------------------------------------------------------------------------


def _int_tuple_kwarg(call: ast.Call, name: str) -> Optional[Tuple[int, ...]]:
    """Literal int-tuple value of keyword `name` on `call` (scalar, tuple,
    or list literal of ints — donate_argnums/static_argnums shapes), None
    when absent or computed. Shared by the donation-safety rule and
    progrules' recompile-hazard so literal-parsing hardening (constant
    folding etc.) lands in one place."""
    kw = keyword_arg(call, name)
    if kw is None:
        return None
    if isinstance(kw, (ast.Tuple, ast.List)):
        out = []
        for el in kw.elts:
            v = numeric_literal(el)
            if v is None or int(v) != v:
                return None
            out.append(int(v))
        return tuple(out)
    v = numeric_literal(kw)
    return (int(v),) if v is not None and int(v) == v else None


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The literal donate_argnums of a jax.jit(...) call, or None."""
    return _int_tuple_kwarg(call, "donate_argnums")


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    if isinstance(node, ast.Call):
        name = dotted(node.func) or ""
        if name in ("jit", "jax.jit", "pjit", "jax.experimental.pjit.pjit"):
            return node
    return None


def _own_statements(fn: ast.AST) -> Iterable[ast.stmt]:
    """Every statement in `fn`'s own body, NOT descending into nested
    function/class definitions — a nested helper's `return jax.jit(...)`
    belongs to the helper, not to the enclosing method."""
    stack = list(getattr(fn, "body", []))
    while stack:
        stmt = stack.pop()
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for field in stmt._fields:
            val = getattr(stmt, field, None)
            if not isinstance(val, list):
                continue
            stack.extend(v for v in val if isinstance(v, ast.stmt))
            for v in val:  # except-handlers wrap their own stmt lists
                if isinstance(v, ast.excepthandler):
                    stack.extend(v.body)


class _DonationScan:
    """Per-module registry of 'known donated callsites': names (locals and
    self-attributes) bound — via plain or annotated assignment — to
    jax.jit(..., donate_argnums=...) results, including the
    `donate = partial(jax.jit, donate_argnums=...)` factory idiom AND the
    local-def factory idiom (`def _jit_chunk(fn): return jax.jit(fn,
    donate_argnums=(0, 1, 4))` — the parallel/learner.py shape whose
    multi-arg donation tuples must be tracked through the helper). Values
    map callee -> donated positional indices. Aliases of a tracked name
    (`self.f = self.g`) are NOT chased — deliberately narrow, like every
    rule here."""

    @staticmethod
    def _binding(node: ast.AST) -> Optional[Tuple[List[ast.expr], ast.expr]]:
        """(targets, value) for plain and annotated assignments."""
        if isinstance(node, ast.Assign):
            return node.targets, node.value
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            return [node.target], node.value
        return None

    def __init__(self, tree: ast.Module):
        self.donated: Dict[str, Tuple[int, ...]] = {}
        factories: Dict[str, Tuple[int, ...]] = {}
        # Two passes so a factory defined after first use still resolves
        # (order in a class body is not execution order).
        for node in ast.walk(tree):
            # Local-def factory: a helper whose own `return` hands back a
            # jax.jit(..., donate_argnums=...) — `_jit_per_chunk` in
            # parallel/learner.py. Calling it binds the target to the
            # FULL donated tuple (e.g. (0, 1, 4, 9)), so a later read of
            # ANY donated position is flagged, not just arg 0.
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for stmt in _own_statements(node):
                    if isinstance(stmt, ast.Return) and stmt.value is not None:
                        jc = _jit_call(stmt.value)
                        pos = _donated_positions(jc) if jc is not None else None
                        if pos:
                            factories[node.name] = pos
                continue
            bind = self._binding(node)
            if bind is None:
                continue
            targets, value = bind
            call = value if isinstance(value, ast.Call) else None
            if call is None:
                continue
            fname = dotted(call.func) or ""
            if fname in ("partial", "functools.partial") and call.args:
                inner = dotted(call.args[0]) or ""
                if inner in ("jit", "jax.jit"):
                    pos = _donated_positions(call)
                    if pos:
                        for t in targets:
                            tn = dotted(t)
                            if tn:
                                factories[tn] = pos
        for node in ast.walk(tree):
            bind = self._binding(node)
            if bind is None:
                continue
            targets, bound = bind
            values = [bound]
            if isinstance(bound, ast.IfExp):
                values = [bound.body, bound.orelse]
            for value in values:
                pos: Optional[Tuple[int, ...]] = None
                jc = _jit_call(value)
                if jc is not None:
                    pos = _donated_positions(jc)
                elif isinstance(value, ast.Call):
                    fname = dotted(value.func) or ""
                    pos = factories.get(fname)
                if pos:
                    for t in targets:
                        tn = dotted(t)
                        if tn:
                            self.donated[tn] = pos


@register
class DonationSafety(Rule):
    """A buffer passed at a donated position of a jitted call is DEAD the
    moment the call dispatches — XLA owns (and will overwrite) its memory.
    Reading it afterwards without re-binding is the PR-9 TrainState
    pointer-re-swap bug class: works on CPU, corrupts silently on TPU
    where donation actually aliases. The rule tracks names bound to
    jax.jit(..., donate_argnums=...) within a module and flags any load of
    a donated argument after the call, before a re-bind. Same-statement
    re-binds (`state = step(state)`) are the sanctioned idiom and pass."""

    name = "donation-safety"
    doc = (
        "no read of a variable after it was passed at a donated position "
        "of a known donated-jit callsite, without an intervening re-bind"
    )

    def check_module(self, module: Module, ctx: LintContext) -> Iterable[Finding]:
        if module.tree is None:
            return ()
        scan = _DonationScan(module.tree)
        if not scan.donated:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(module, node, scan.donated, findings)
        return findings

    # -- statement-linear dataflow (single pass, control flow flattened:
    #    conservative about order, silent about loops re-entering — the
    #    bug class this guards is straight-line dispatch code) ----------

    def _scan_function(self, module, fn, donated, findings) -> None:
        dead: Dict[str, Tuple[str, int]] = {}  # name -> (callee, line)
        self._scan_body(module, fn.body, donated, dead, findings)

    def _scan_body(self, module, stmts, donated, dead, findings) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs run later, under different state
            if isinstance(stmt, ast.Assign):
                self._scan_expr(module, stmt.value, donated, dead, findings)
                for t in stmt.targets:
                    self._clear_target(t, dead)
            elif isinstance(stmt, ast.AnnAssign):
                if stmt.value is not None:
                    self._scan_expr(module, stmt.value, donated, dead,
                                    findings)
                self._clear_target(stmt.target, dead)
            elif isinstance(stmt, ast.AugAssign):
                self._scan_expr(module, stmt.value, donated, dead, findings)
                self._scan_expr(module, stmt.target, donated, dead, findings)
                self._clear_target(stmt.target, dead)
            elif isinstance(stmt, ast.For):
                self._scan_expr(module, stmt.iter, donated, dead, findings)
                self._clear_target(stmt.target, dead)
                self._scan_body(module, stmt.body, donated, dead, findings)
                self._scan_body(module, stmt.orelse, donated, dead, findings)
            elif isinstance(stmt, ast.If):
                # Branch-aware: a branch that cannot fall through (ends in
                # return/raise/break/continue) keeps its donated-dead set
                # to itself — the guard_enabled early-return idiom must
                # not poison the straight-line path after it.
                self._scan_expr(module, stmt.test, donated, dead, findings)
                body_dead = dict(dead)
                self._scan_body(module, stmt.body, donated, body_dead,
                                findings)
                else_dead = dict(dead)
                self._scan_body(module, stmt.orelse, donated, else_dead,
                                findings)
                dead.clear()
                if not self._terminates(stmt.body):
                    dead.update(body_dead)
                if not self._terminates(stmt.orelse):
                    dead.update(else_dead)
            elif isinstance(stmt, ast.While):
                self._scan_expr(module, stmt.test, donated, dead, findings)
                self._scan_body(module, stmt.body, donated, dead, findings)
                self._scan_body(module, stmt.orelse, donated, dead, findings)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self._scan_expr(module, item.context_expr, donated, dead,
                                    findings)
                    if item.optional_vars is not None:
                        self._clear_target(item.optional_vars, dead)
                self._scan_body(module, stmt.body, donated, dead, findings)
            elif isinstance(stmt, ast.Try):
                self._scan_body(module, stmt.body, donated, dead, findings)
                for h in stmt.handlers:
                    self._scan_body(module, h.body, donated, dead, findings)
                self._scan_body(module, stmt.orelse, donated, dead, findings)
                self._scan_body(module, stmt.finalbody, donated, dead,
                                findings)
            else:
                for expr in ast.iter_child_nodes(stmt):
                    if isinstance(expr, ast.expr):
                        self._scan_expr(module, expr, donated, dead, findings)

    @staticmethod
    def _terminates(stmts) -> bool:
        """True when the block cannot fall through to the next statement."""
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue)
        )

    def _scan_expr(self, module, expr, donated, dead, findings) -> None:
        if isinstance(expr, ast.Call):
            self._scan_expr(module, expr.func, donated, dead, findings)
            for a in expr.args:
                self._scan_expr(module, a, donated, dead, findings)
            for kw in expr.keywords:
                self._scan_expr(module, kw.value, donated, dead, findings)
            callee = dotted(expr.func)
            pos = donated.get(callee or "")
            if pos:
                for i in pos:
                    if i < len(expr.args):
                        argname = dotted(expr.args[i])
                        if argname:
                            dead[argname] = (callee, expr.lineno)
            return
        name = dotted(expr)
        if name is not None and isinstance(expr, (ast.Name, ast.Attribute)) \
                and isinstance(getattr(expr, "ctx", None), ast.Load):
            for key, (callee, line) in dead.items():
                if name == key or name.startswith(key + "."):
                    findings.append(module.finding(
                        self.name, expr,
                        f"`{name}` read after being passed at a donated "
                        f"position of {callee}() (line {line}) with no "
                        "re-bind — the buffer is deleted/aliased after "
                        "dispatch (the PR-9 TrainState re-swap bug class); "
                        "re-bind the result or snapshot before the call",
                    ))
                    return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._scan_expr(module, child, donated, dead, findings)

    def _clear_target(self, target, dead) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._clear_target(el, dead)
            return
        if isinstance(target, ast.Starred):
            self._clear_target(target.value, dead)
            return
        name = dotted(target)
        if name:
            for key in [k for k in dead
                        if k == name or k.startswith(name + ".")]:
                del dead[key]


# ---------------------------------------------------------------------------
# 4. typed-error
# ---------------------------------------------------------------------------

_TYPED_ERROR_DIRS: Dict[str, str] = {
    "serve": "ServeOverload / ServeDispatchError / ServeTimeout",
    "transfer": "TransferError",
    "replay": "IngestError / ReplayUsageError",
    "actors": "DeviceActorError / faults.InjectedFault / ValueError",
    "parallel": "PodPeerLost / PrefetchError / PrefetchTimeout",
}


@register
class TypedErrorContract(Rule):
    """Subsystem code may not raise bare RuntimeError/Exception: every
    subsystem has a typed family that callers catch to pick a recovery
    path (degrade-to-local on ServeTimeout, clean pod abort on
    PodPeerLost, bounded restart past IngestError...). A bare
    RuntimeError is caught by nobody's recovery logic and by everybody's
    blanket handler — the worst of both."""

    name = "typed-error"
    doc = (
        "no `raise RuntimeError/Exception` inside serve/, transfer/, "
        "replay/, actors/, parallel/ — use the subsystem's typed family"
    )

    def check_module(self, module: Module, ctx: LintContext) -> Iterable[Finding]:
        if module.tree is None:
            return
        subsystem = module.rulepath.split("/", 1)[0]
        family = _TYPED_ERROR_DIRS.get(subsystem)
        if family is None or "/" not in module.rulepath:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = dotted(exc.func) if isinstance(exc, ast.Call) else dotted(exc)
            if name in ("RuntimeError", "Exception"):
                yield module.finding(
                    self.name, node,
                    f"raise {name} in {subsystem}/ — use the subsystem's "
                    f"typed error family ({family}) so recovery paths can "
                    "catch it",
                )


# ---------------------------------------------------------------------------
# 5. lock-discipline
# ---------------------------------------------------------------------------

_LOCK_NAMES = ("dispatch_lock",)
# Host-side blocking waits; jax.block_until_ready is deliberately ABSENT:
# holding dispatch_lock across the device barrier IS the donation-safety
# mechanism (replay/device.py drain_pending).
_LOCK_BLOCKING_ATTRS = ("result", "wait", "join", "sleep")
_COLLECTIVE_ENTRYPOINTS = (
    "allgather_scalar", "beat_allgather", "call_with_deadline",
    "startup_barrier", "elect_resume_step", "wait_beat_ticket",
    "process_allgather", "sync_ship",
)


@register
class LockDiscipline(Rule):
    """dispatch_lock serializes device dispatch against the ingest
    shipper's donate-and-swap. Blocking on a host primitive — or worse,
    issuing a pod collective — while holding it deadlocks the trainer the
    first time the other side of the wait needs the lock (and a
    collective under the lock couples a local wedge to every peer's
    deadline). Collectives run BEFORE taking the lock (sync_ship's
    beat_allgather does exactly this)."""

    name = "lock-discipline"
    doc = (
        "no blocking wait (.result/.wait/.join/sleep/queue-shaped .get) "
        "or pod collective under dispatch_lock"
    )

    def check_module(self, module: Module, ctx: LintContext) -> Iterable[Finding]:
        if module.tree is None:
            return
        # Dedupe by location: a dispatch_lock `with` nested inside another
        # one is visited both by the outer scan's recursion and by its own
        # ast.walk hit — the same blocking call must report once.
        seen: Set[Tuple[int, int, str]] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(self._is_dispatch_lock(i.context_expr)
                       for i in node.items):
                continue
            for f in self._scan_block(module, node.body):
                key = (f.line, f.col, f.message)
                if key not in seen:
                    seen.add(key)
                    yield f

    def _is_dispatch_lock(self, expr: ast.expr) -> bool:
        name = dotted(expr)
        if name and any(name == n or name.endswith("." + n)
                        for n in _LOCK_NAMES):
            return True
        # The learner takes the same lock through its helper
        # (parallel/learner.py _ingest_lock(device_replay)).
        if isinstance(expr, ast.Call):
            fname = dotted(expr.func) or ""
            return fname.endswith("_ingest_lock")
        return False

    def _scan_block(self, module: Module, stmts) -> Iterable[Finding]:
        for stmt in stmts:
            yield from self._scan_node(module, stmt)

    def _scan_node(self, module: Module, node: ast.AST) -> Iterable[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # deferred execution: not under the lock
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            is_block = False
            bound: Optional[float] = None
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                kw = keyword_arg(node, "timeout")
                if attr in _LOCK_BLOCKING_ATTRS:
                    is_block = True
                    bound = numeric_literal(kw) if kw is not None else (
                        numeric_literal(node.args[0]) if node.args else None
                    )
                elif attr == "get":
                    # queue.get shapes only — a bare call, a literal-bool
                    # block flag, or keyword-only args. dict.get(key, ...)
                    # always passes a non-bool key first and never waits.
                    bool_flag = bool(
                        node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, bool)
                    )
                    is_block = not node.args or bool_flag
                    if bool_flag and node.args[0].value is False:
                        is_block = False  # block=False: a poll
                    blk = keyword_arg(node, "block")
                    if isinstance(blk, ast.Constant) and blk.value is False:
                        is_block = False
                    if kw is not None:
                        bound = numeric_literal(kw)
                    elif bool_flag and len(node.args) >= 2:
                        bound = numeric_literal(node.args[1])
            if is_block:
                # .result(timeout=0.0) / .get(timeout=0.0) is a poll.
                if bound is None or bound != 0.0:
                    yield module.finding(
                        self.name, node,
                        f"blocking {name or leaf}() under dispatch_lock — "
                        "the shipper/learner on the other side of this "
                        "wait needs the lock; wait outside the critical "
                        "section",
                    )
            elif leaf in _COLLECTIVE_ENTRYPOINTS or \
                    name.startswith("multihost."):
                yield module.finding(
                    self.name, node,
                    f"collective {name or leaf}() under dispatch_lock "
                    "— a peer-coupled wait under a local lock wedges "
                    "the pod; gather first, then take the lock "
                    "(sync_ship's beat_allgather ordering)",
                )
        for child in ast.iter_child_nodes(node):
            yield from self._scan_node(module, child)


# ---------------------------------------------------------------------------
# 6. observability-drift
# ---------------------------------------------------------------------------

_FIELD_RE = re.compile(r"^[a-z][a-z0-9_]*_[a-z0-9_]+$")


def _doc_field_patterns(doc_text: str) -> List[re.Pattern]:
    """Compile the doc's field tokens into matchers. Tokens may use the
    doc shorthand `a_b_c/d/e` (suffix alternatives) and `<cls>` template
    segments (match any one field segment)."""
    patterns: List[re.Pattern] = []
    for token in re.findall(r"[a-z][a-z0-9_/<>]*", doc_text):
        for cand in _expand_slash(token):
            if "<" in cand:
                rx = re.escape(cand)
                # re.escape stopped escaping <> in Python 3.7: accept the
                # template marker with or without the backslashes.
                rx = re.sub(r"\\?<[a-z_]+\\?>", r"[a-z0-9_]+", rx)
                patterns.append(re.compile(rx + r"$"))
    return patterns


def _expand_slash(token: str) -> List[str]:
    """`a_b_c/d/e` → [a_b_c, a_b_d, a_b_e]: each alternative replaces the
    base's LAST segment, whatever its own segment count — the doc row
    `transfer_pool_buffers/fence_waits` covers transfer_pool_fence_waits."""
    if "/" not in token:
        return [token]
    parts = token.split("/")
    base = parts[0]
    out = [base]
    segs = base.split("_")
    for p in parts[1:]:
        if not p:
            continue
        out.append("_".join(segs[:-1] + [p]) if len(segs) > 1 else p)
    return out


def _doc_mentions(field: str, plain_tokens: Set[str],
                  patterns: List[re.Pattern]) -> bool:
    if field in plain_tokens:
        return True
    return any(p.match(field) for p in patterns)


@register
class ObservabilityDrift(Rule):
    """The metrics schema, its documentation, and its renderer must move
    together: every field family a `*Stats` class emits in metrics.py
    needs a row in docs/OBSERVABILITY.md and a renderer reference in
    tools/runs.py — an undocumented counter is write-only telemetry
    (exactly how the replay_*/pod_* families drifted before this rule).
    Folded in: every fault component registered in faults.py must appear
    in docs/RESILIENCE.md's failure matrix, so the chaos grammar and the
    recovery documentation cannot diverge."""

    name = "observability-drift"
    doc = (
        "metrics.py *Stats fields must appear in docs/OBSERVABILITY.md "
        "and tools/runs.py; faults.py components must appear in "
        "docs/RESILIENCE.md's failure matrix"
    )

    def check_project(self, ctx: LintContext) -> Iterable[Finding]:
        yield from self._check_stats_fields(ctx)
        yield from self._check_fault_components(ctx)

    # -- metrics fields ------------------------------------------------

    def _check_stats_fields(self, ctx: LintContext) -> Iterable[Finding]:
        metrics = ctx.module("metrics.py")
        if metrics is None or metrics.tree is None or ctx.docs_root is None:
            # No docs tree at all (bare file set): doc-coupled checks stay
            # silent — only a MISSING file inside an existing docs dir is
            # a finding.
            return
        doc_text = ctx.doc_text("OBSERVABILITY.md")
        runs = ctx.module("tools/runs.py")
        if doc_text is None:
            yield Finding(
                rule=self.name, path=metrics.relpath, line=1, col=0,
                message="docs/OBSERVABILITY.md not found next to the "
                        "package — the JSONL schema has no documentation "
                        "to check against",
            )
            return
        plain_tokens = {
            t for tok in re.findall(r"[a-z][a-z0-9_/<>]*", doc_text)
            for t in _expand_slash(tok) if "<" not in t
        }
        patterns = _doc_field_patterns(doc_text)
        runs_text = runs.text if runs is not None else ""

        for cls in metrics.tree.body:
            if not isinstance(cls, ast.ClassDef) or \
                    not cls.name.endswith("Stats"):
                continue
            fields = self._snapshot_fields(cls)
            families: Set[str] = set()
            for field, node in fields:
                families.add(field.split("_", 1)[0] + "_")
                if not _doc_mentions(field, plain_tokens, patterns):
                    # exact: the snapshot dict is ONE simple statement —
                    # statement-span suppression matching would let a
                    # single per-field escape cover every sibling field's
                    # future drift. The comment must sit on the key's line.
                    yield metrics.finding(
                        self.name, node,
                        f"{cls.name} emits `{field}` but "
                        "docs/OBSERVABILITY.md has no row for it — "
                        "document the field (or its `<cls>` template) in "
                        "the JSONL schema table",
                        exact=True,
                    )
            for fam in sorted(families):
                if runs_text and fam not in runs_text:
                    # Anchored to the class HEADER line only (not the
                    # ClassDef's full span): a field-level suppression
                    # inside the body must never mask this class-level
                    # finding via span matching.
                    yield Finding(
                        rule=self.name, path=metrics.relpath,
                        line=cls.lineno, col=cls.col_offset,
                        message=(
                            f"{cls.name}'s `{fam}*` family has no renderer "
                            "reference in tools/runs.py — summarize/compare "
                            "would silently drop the whole family"
                        ),
                    )

    def _snapshot_fields(self, cls: ast.ClassDef) -> List[Tuple[str, ast.AST]]:
        """Literal string keys of dicts built inside the class's
        snapshot() method — the emitted JSONL field names. f-string keys
        (per-class templates) are covered by the doc's `<cls>` rows and
        skipped here."""
        out: List[Tuple[str, ast.AST]] = []
        for item in cls.body:
            if isinstance(item, ast.FunctionDef) and item.name == "snapshot":
                for node in ast.walk(item):
                    if isinstance(node, ast.Dict):
                        for k in node.keys:
                            if isinstance(k, ast.Constant) and \
                                    isinstance(k.value, str) and \
                                    _FIELD_RE.match(k.value):
                                out.append((k.value, k))
                    elif isinstance(node, ast.Subscript) and \
                            isinstance(node.ctx, ast.Store) and \
                            isinstance(node.slice, ast.Constant) and \
                            isinstance(node.slice.value, str) and \
                            _FIELD_RE.match(node.slice.value):
                        out.append((node.slice.value, node))
        return out

    # -- fault components ----------------------------------------------

    def _check_fault_components(self, ctx: LintContext) -> Iterable[Finding]:
        faults = ctx.module("faults.py")
        if faults is None or faults.tree is None or ctx.docs_root is None:
            return
        doc_text = ctx.doc_text("RESILIENCE.md")
        components: List[Tuple[str, ast.AST]] = []
        for node in faults.tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "COMPONENTS"
                for t in node.targets
            ):
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    for el in node.value.elts:
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str):
                            components.append((el.value, el))
        if not components:
            return
        if doc_text is None:
            yield Finding(
                rule=self.name, path=faults.relpath, line=1, col=0,
                message="docs/RESILIENCE.md not found — the fault grammar "
                        "has no failure matrix to check against",
            )
            return
        # The matrix section: from its heading to the next same-level one.
        m = re.search(r"^## Failure matrix.*?(?=^## )", doc_text,
                      re.MULTILINE | re.DOTALL)
        matrix = m.group(0) if m else doc_text
        for comp, node in components:
            if not re.search(rf"\b{re.escape(comp)}\s*:", matrix):
                # exact, like the snapshot-field findings: COMPONENTS is
                # one tuple statement — a suppression on one entry's line
                # must not cover its siblings.
                yield faults.finding(
                    self.name, node,
                    f"fault component `{comp}` (faults.py COMPONENTS) has "
                    "no `"
                    f"{comp}:...` spec row in docs/RESILIENCE.md's "
                    "failure matrix — every injectable fault needs its "
                    "detection/recovery/artifact row",
                    exact=True,
                )


# ---------------------------------------------------------------------------
# 8. exit-code-literal
# ---------------------------------------------------------------------------

# The typed codes (exits.py). Untyped statuses (sys.exit(1), argparse's
# 2) are not the contract's business and stay unflagged.
_TYPED_EXIT_CODES = frozenset({70, 75, 76, 77, 78, 79})
_EXITS_MODULE = "exits.py"
_EXIT_CALL_LEAVES = ("exit", "_exit", "SystemExit")


@register
class ExitCodeLiteral(Rule):
    """The typed exit codes (70/75/76/77/78/79) are a cross-process
    CONTRACT: train, the watchdog, the chaos children, and the pod
    supervisor all key recovery decisions off them (docs/RESILIENCE.md
    exit-code matrix). Before exits.py they lived as scattered literals
    — and one drifted copy turns a shrink-ready exit (relaunch smaller,
    adopt the slices) into an unknown crash (relaunch blindly). Every
    typed exit must go through the distributed_ddpg_tpu.exits constants;
    only exits.py itself may spell the numbers."""

    name = "exit-code-literal"
    doc = (
        "no bare typed exit-code literal (70/75/76/77/78/79) in "
        "sys.exit/os._exit/SystemExit or EXIT_*-named assignments "
        "outside exits.py — import distributed_ddpg_tpu.exits"
    )

    def check_module(self, module: Module, ctx: LintContext) -> Iterable[Finding]:
        if module.tree is None or module.rulepath == _EXITS_MODULE:
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                name = dotted(node.func) or ""
                leaf = name.rsplit(".", 1)[-1]
                if leaf not in _EXIT_CALL_LEAVES or not node.args:
                    continue
                val = node.args[0]
                if (
                    isinstance(val, ast.Constant)
                    and isinstance(val.value, int)
                    and not isinstance(val.value, bool)
                    and val.value in _TYPED_EXIT_CODES
                ):
                    yield module.finding(
                        self.name, node,
                        f"bare typed exit code {val.value} in "
                        f"{name or leaf}() — import the named constant "
                        "from distributed_ddpg_tpu.exits "
                        "(docs/RESILIENCE.md exit-code matrix)",
                    )
            elif isinstance(node, ast.Assign):
                val = node.value
                if not (
                    isinstance(val, ast.Constant)
                    and isinstance(val.value, int)
                    and not isinstance(val.value, bool)
                    and val.value in _TYPED_EXIT_CODES
                ):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and "EXIT" in tgt.id.upper():
                        yield module.finding(
                            self.name, node,
                            f"local exit-code constant {tgt.id} = "
                            f"{val.value} shadows the one-place contract "
                            "— import it from distributed_ddpg_tpu.exits",
                        )
