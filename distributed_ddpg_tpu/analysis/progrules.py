"""Program-level static rules: the AST half of the program-contract
analyzer (analysis/programs.py; docs/ANALYSIS.md "Layer 2").

The dynamic analyzer traces the compiled programs; this module holds the
jit-KEY hazards that are visible without tracing anything — shapes that
make XLA recompile the same program over and over, which on a pod means
every replica pays the multi-second compile inside the training loop
(and on the serve path, inside a request deadline). One rule, four
concrete shapes, all of which have shipped somewhere as "why is the TPU
idle 40% of the time":

1. a `jax.jit(...)` (or `partial(jax.jit, ...)` factory) call inside a
   `for`/`while` body — inline, or as a decorator on a def, since a
   decorator executes at definition time, i.e. per iteration — every
   iteration builds a fresh callable, and the jit cache keys on the
   function OBJECT, so each one retraces and recompiles. Worse when the
   closure captures the loop variable: the baked-in Python scalar forces
   one compile per distinct value.
2. a jit built and invoked in one expression inside a function
   (`jax.jit(fn)(x)`): the wrapper is rebuilt — and the program
   retraced — on every call of the enclosing function.
3. an unhashable literal (list/dict/set) passed at a static position of
   a tracked `jax.jit(..., static_argnums=...)` callsite: dispatch
   raises TypeError the first time that path runs — on the pod, at beat
   cadence.
4. a `jax.jit(...)` inside the TRACED body callable of
   `lax.fori_loop` / `lax.while_loop` / `lax.scan` (inline lambda, a
   named def passed as the body, or a jit handed directly as the body
   argument): the body executes under trace, so the nested jit
   re-enters the jit machinery on every (re)composition of the
   enclosing program — the compile-once superstep contract
   (parallel/superstep.py) requires the loop body to stay jit-free,
   with the one jit wrapping the whole loop.

Registered into the same registry as rules.py, so `tools.lint`, the
suppression grammar, and `--rules recompile-hazard` all apply; the
proganalyze CLI runs it alongside the traced checks.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from distributed_ddpg_tpu.analysis.engine import (
    Finding,
    LintContext,
    Module,
    Rule,
    register,
)
from distributed_ddpg_tpu.analysis.rules import (
    _DonationScan,
    _int_tuple_kwarg,
    _jit_call,
    dotted,
)


_JIT_NAMES = ("jit", "jax.jit", "pjit", "jax.experimental.pjit.pjit")


def _jit_like_call(node: ast.AST) -> Optional[ast.Call]:
    """jax.jit(...) itself, or the partial(jax.jit, ...) factory shape."""
    jc = _jit_call(node)
    if jc is not None:
        return jc
    if isinstance(node, ast.Call):
        name = dotted(node.func) or ""
        if name in ("partial", "functools.partial") and node.args:
            inner = dotted(node.args[0]) or ""
            if inner in ("jit", "jax.jit"):
                return node
    return None


def _static_positions(call: ast.Call) -> Tuple[int, ...]:
    """Literal static_argnums of a jit call, () when absent/computed."""
    return _int_tuple_kwarg(call, "static_argnums") or ()


class _StaticJitScan:
    """Names bound to jax.jit(..., static_argnums=...) results — the
    static-position twin of rules._DonationScan, kept deliberately
    narrow the same way (plain/annotated assigns, no alias chasing;
    the binding shapes come from _DonationScan._binding)."""

    def __init__(self, tree: ast.Module):
        self.static: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(tree):
            bind = _DonationScan._binding(node)
            if bind is None:
                continue
            targets, value = bind
            jc = _jit_call(value)
            if jc is None:
                continue
            pos = _static_positions(jc)
            if pos:
                for t in targets:
                    tn = dotted(t)
                    if tn:
                        self.static[tn] = pos


_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


# Traced-loop callsites and the arg positions holding traced callables:
# fori_loop(lower, upper, BODY, init); while_loop(COND, BODY, init);
# scan(BODY, init, xs). Bare `scan` is deliberately absent — the name is
# too generic to claim without a lax/jax.lax qualifier (host-side scan
# helpers exist); `fori_loop`/`while_loop` are distinctive enough bare.
_TRACED_LOOP_BODY_ARGS: Dict[str, Tuple[int, ...]] = {}
for _base, _pos in (("fori_loop", (2,)), ("while_loop", (0, 1)),
                    ("scan", (0,))):
    for _prefix in ("lax.", "jax.lax."):
        _TRACED_LOOP_BODY_ARGS[_prefix + _base] = _pos
_TRACED_LOOP_BODY_ARGS["fori_loop"] = (2,)
_TRACED_LOOP_BODY_ARGS["while_loop"] = (0, 1)


def _walk_skipping_deferred(stmt: ast.stmt) -> Iterable[ast.AST]:
    """ast.walk minus the bodies of nested def/lambda: a def or lambda
    inside a loop DEFERS execution, so a jit call in its body runs when
    the helper is called (possibly once — the ProgramSpec-builder
    idiom), not per iteration. Decorators and class bodies still
    descend: both execute at definition time, i.e. per iteration —
    `@jax.jit` on a def in a loop body builds a fresh callable every
    pass exactly like an inline jit call."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            continue
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))


@register
class RecompileHazard(Rule):
    """Jit-key hazards: shapes that silently turn one compile into a
    compile-per-call (module docstring). The finding always names the
    hazard AND the sanctioned idiom — hoist the jit, cache per shape
    (replay/device.py's `_get_insert` dict), or make the static arg
    hashable."""

    name = "recompile-hazard"
    doc = (
        "no jax.jit inside a loop body, no jit-and-call in one "
        "expression inside a function, no unhashable literal at a "
        "static_argnums position, and no jit inside the traced body "
        "callable of lax.fori_loop/while_loop/scan"
    )

    def check_module(self, module: Module, ctx: LintContext) -> Iterable[Finding]:
        if module.tree is None:
            return
        statics = _StaticJitScan(module.tree).static
        fndefs: Dict[str, ast.FunctionDef] = {
            n.name: n
            for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

        def findings():
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.For, ast.While)):
                    yield from self._scan_loop(module, node)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._scan_inline_jit(module, node)
                if isinstance(node, ast.Call):
                    yield from self._check_static_args(module, node, statics)
                    yield from self._scan_traced_body(module, node, fndefs)

        # ast.walk visits nested loops/defs once per ancestor scan — the
        # same hazard must report once. Messages can differ across scans
        # (only the innermost loop's scan sees its loop variable in the
        # closure), so dedup on position and keep the richest message.
        best: Dict[Tuple[int, int], Finding] = {}
        order: List[Tuple[int, int]] = []
        for f in findings():
            key = (f.line, f.col)
            cur = best.get(key)
            if cur is None:
                order.append(key)
                best[key] = f
            elif len(f.message) > len(cur.message):
                best[key] = f
        for key in order:
            yield best[key]

    # -- shape 1: jit built inside a loop body -------------------------

    def _scan_loop(self, module: Module, loop) -> Iterable[Finding]:
        loop_vars: Set[str] = set()
        if isinstance(loop, ast.For):
            for n in ast.walk(loop.target):
                if isinstance(n, ast.Name):
                    loop_vars.add(n.id)
        for stmt in loop.body + loop.orelse:
            for node in _walk_skipping_deferred(stmt):
                # A BARE `@jax.jit` decorator on a def in the loop body is
                # the same hazard with no Call node to match: the decorator
                # executes at definition time, i.e. per iteration. (Call-
                # shaped decorators — `@jax.jit(...)`, `@partial(jax.jit,
                # ...)` — flow through the walk and match below.)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if (not isinstance(dec, ast.Call)
                                and (dotted(dec) or "") in _JIT_NAMES):
                            yield module.finding(
                                self.name, dec,
                                f"@{dotted(dec)} on a def inside a loop "
                                "body — the decorator runs at definition "
                                "time, so each iteration builds a fresh "
                                "jitted callable that retraces and "
                                "recompiles; hoist the jitted helper out "
                                "of the loop",
                            )
                    continue
                jc = _jit_like_call(node)
                if jc is None or not isinstance(node, ast.Call):
                    continue
                captured = self._captured_loop_var(jc, loop_vars)
                extra = (
                    f" — and the jitted closure captures loop variable "
                    f"`{captured}` as a baked-in Python scalar, one "
                    "recompile per distinct value"
                    if captured else ""
                )
                yield module.finding(
                    self.name, node,
                    "jax.jit() inside a loop body — each iteration builds "
                    "a fresh callable and the jit cache keys on the "
                    "function object, so the same program retraces and "
                    "recompiles every pass; hoist the jit out of the loop "
                    "or cache per static shape (the replay _get_insert "
                    f"dict idiom){extra}",
                )

    @staticmethod
    def _captured_loop_var(jc: ast.Call, loop_vars: Set[str]) -> Optional[str]:
        if not loop_vars or not jc.args:
            return None
        target = jc.args[0]
        if isinstance(target, ast.Lambda):
            for n in ast.walk(target.body):
                if isinstance(n, ast.Name) and n.id in loop_vars:
                    return n.id
        return None

    # -- shape 2: jit-and-invoke in one expression ---------------------

    def _scan_inline_jit(self, module: Module, fn) -> Iterable[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            # Only a DIRECT jax.jit(...) call invoked in place counts:
            # `partial(jax.jit, ...)(fn)` merely builds the wrapper (the
            # sanctioned bind-once factory idiom) — no program is traced
            # by the outer call.
            jc = _jit_call(node.func)
            if jc is not None and isinstance(node.func, ast.Call):
                yield module.finding(
                    self.name, node,
                    "jit built and invoked in one expression "
                    "(`jax.jit(fn)(...)`) inside a function — the wrapper "
                    "is rebuilt and the program retraced on every call of "
                    "the enclosing function; bind the jitted callable "
                    "once (module level or __init__) and dispatch through "
                    "the binding",
                )

    # -- shape 4: jit inside a traced loop body ------------------------

    def _scan_traced_body(self, module: Module, call: ast.Call,
                          fndefs: Dict[str, ast.FunctionDef]
                          ) -> Iterable[Finding]:
        """jax.jit inside the body callable of lax.fori_loop / while_loop
        / scan. The body is TRACED — a nested jit there re-enters the jit
        machinery on every (re)composition of the enclosing program. The
        compile-once superstep (parallel/superstep.py) depends on this
        staying clean: one jit around the whole loop, a jit-free body
        inside it."""
        name = dotted(call.func) or ""
        positions = _TRACED_LOOP_BODY_ARGS.get(name)
        if not positions:
            return
        site = name.rsplit(".", 1)[-1]
        for i in positions:
            if i >= len(call.args):
                continue
            body = call.args[i]
            # The body argument IS a jit: `fori_loop(0, n, jax.jit(f), c)`.
            if _jit_like_call(body) is not None:
                yield module.finding(
                    self.name, body,
                    f"jit-wrapped callable passed as the traced body of "
                    f"lax.{site}() — the loop body executes under trace, "
                    "so the nested jit re-enters the jit cache on every "
                    "composition of the enclosing program; keep the body "
                    "jit-free and jit the function that CONTAINS the loop",
                )
                continue
            # Inline lambda body, or a named def resolved in this module.
            target = None
            if isinstance(body, ast.Lambda):
                target = body.body
            elif isinstance(body, ast.Name) and body.id in fndefs:
                target = fndefs[body.id]
            if target is None:
                continue
            scan_root = (
                [s for s in target.body]
                if isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef))
                else [target]
            )
            for stmt in scan_root:
                for node in _walk_skipping_deferred(stmt):
                    hazard = None
                    if (isinstance(node, ast.Call)
                            and _jit_like_call(node) is not None):
                        hazard = node
                    elif isinstance(node,
                                    (ast.FunctionDef, ast.AsyncFunctionDef)):
                        for dec in node.decorator_list:
                            if (not isinstance(dec, ast.Call)
                                    and (dotted(dec) or "") in _JIT_NAMES):
                                hazard = dec
                    if hazard is not None:
                        yield module.finding(
                            self.name, hazard,
                            f"jax.jit inside the traced body of "
                            f"lax.{site}() — the body runs under trace, so "
                            "the nested jit re-traces on every composition "
                            "of the enclosing program (and defeats the "
                            "compile-once loop contract); hoist the jit "
                            "out and close over the plain function",
                        )

    # -- shape 3: unhashable literal at a static position --------------

    def _check_static_args(self, module: Module, call: ast.Call,
                           statics: Dict[str, Tuple[int, ...]]
                           ) -> Iterable[Finding]:
        callee = dotted(call.func)
        pos = statics.get(callee or "")
        if not pos:
            return
        for i in pos:
            if i < len(call.args) and isinstance(call.args[i], _UNHASHABLE):
                kind = type(call.args[i]).__name__.lower().replace("comp", " comprehension")
                yield module.finding(
                    self.name, call.args[i],
                    f"{kind} literal passed at static position {i} of "
                    f"{callee}() — static jit args must be hashable "
                    "(dispatch raises TypeError the first time this path "
                    "runs); pass a tuple / frozen value instead",
                )
