"""Invariant lint engine: AST rules that enforce the repo's cross-cutting
architectural contracts (docs/ANALYSIS.md).

Five PRs of hard-won invariants — every DCN collective rides the audited
deadline-wrapped entry point in parallel/multihost.py (PR 6), host<->device
traffic goes through the transfer scheduler (PR 5), donated buffers are
never read after dispatch without a re-bind (the PR-9 pointer re-swap bug
class), no blocking wait carries an inline hardcoded timeout (the PR-10
silent 600 s stall) — were enforced only by reviewer memory. TorchBeast
(arXiv 1910.03552) and the Podracer architectures (arXiv 2104.06272) both
locate distributed-RL correctness in exactly these cross-cutting
discipline rules, which makes them the right target for a custom static
pass rather than more tests: a rule fires on the NEXT violation, not the
next outage.

Pure stdlib (ast/re/json) — importing this package must never initialize
JAX; the engine runs in CI gates and on laptops in well under 5 seconds.

    python -m distributed_ddpg_tpu.tools.lint          # human output
    scripts/lint_gate.sh                               # CI gate (exit 2)
"""

from distributed_ddpg_tpu.analysis.engine import (
    Finding,
    LintResult,
    Module,
    Rule,
    RULES,
    register,
    run_lint,
)
from distributed_ddpg_tpu.analysis import rules as _rules  # registers RULES
from distributed_ddpg_tpu.analysis import progrules as _progrules  # noqa: F401 (registers recompile-hazard)

__all__ = [
    "Finding",
    "LintResult",
    "Module",
    "Rule",
    "RULES",
    "register",
    "run_lint",
]
