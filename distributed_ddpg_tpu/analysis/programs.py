"""Layer-2 program-contract analyzer: jaxpr/lowering-level verification
of the compiled training programs (docs/ANALYSIS.md "Layer 2").

The PR-11 lint engine checks SOURCE — but the invariants that actually
kill a pod live in the COMPILED programs. Replicas fork when their
collective op order diverges (the PodPeerLost/exit-76 class; Podracer's
SPMD discipline, PAPERS.md arXiv 2104.06272), and donation that silently
fails to alias doubles HBM on exactly the buffers sharded replay (D4PG
scale, arXiv 1804.08617) was built to shrink. This module abstractly
traces every hot jitted program — `jax.make_jaxpr` + `.lower()`, never
executing or compiling anything — and checks the artifact:

1. **donation-aliasing** — every leaf of every `donate_argnums` entry
   must be able to alias an output in the lowered computation
   (`tf.aliasing_output` in the StableHLO signature, or a
   `jax.buffer_donor` with a type-matching output for XLA to pair it
   with). A donated-but-unaliasable buffer is a finding, not a silent
   2x HBM cost.
2. **collective-order fingerprint** — the ordered sequence of
   psum/all-gather/ppermute-family primitives in the traced jaxpr
   (including nested scan/pjit/shard_map bodies), canonicalized and
   compared against golden files in tests/golden_programs/. Any reorder
   across a PR is a reviewed golden diff, never an accident. This pins
   the collectives the programs EXPLICITLY stage (shard_map bodies,
   the sharded-replay exchange); collectives the SPMD partitioner
   inserts at compile time are downstream of this jaxpr and follow it
   deterministically.
3. **beat-group consistency** — program variants that must share pod
   beat order (the guarded vs unguarded chunk, dispatched
   interchangeably at the same lockstep site) must have IDENTICAL
   collective subsequences.
4. **host-callback leak** — no `pure_callback`/`io_callback`/
   `debug_callback` primitives in any hot program: a host round-trip
   inside a lockstep program couples every peer's beat to one host's
   scheduler.

Program specs come from cheap `program_specs()` hooks on each subsystem
that owns a jitted program (parallel/learner.py, replay/device.py,
actors/device_pool.py, serve/server.py, ondevice.py) — each builds its
hot programs tiny (8-wide batches, 16-wide hiddens, chunks of 2) under
the 2-device CPU probe mesh. jit is lazy, so building costs tracing
only; the whole live-tree run stays under a 30 s CPU budget
(tests/test_programs.py pins it).

This module imports jax — it is NOT part of the jax-free lint path.
The static half (jit-key hazards) lives in progrules.py instead.

    python -m distributed_ddpg_tpu.tools.proganalyze            # check
    python -m distributed_ddpg_tpu.tools.proganalyze --update-golden
    scripts/proganalyze_gate.sh                                 # CI gate
"""

from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
import json
import re
import time
import warnings
from collections import Counter
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

# Collective primitives whose ORDER is the pod contract: every process
# must stage these identically or the pod's device-op streams fork.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmin", "pmax", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter",
})
# Host round-trips that must never appear inside a hot program.
CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback",
})

# The probe mesh every spec builds under: 2 data-parallel CPU devices —
# the smallest mesh where sharded placement and collectives are real.
PROBE_MESH_DEVICES = 2


class ProgramBuildError(RuntimeError):
    """A program spec failed to construct its jitted program (reported as
    a build-error finding — a spec that cannot build must gate)."""


@dataclasses.dataclass
class BuiltProgram:
    """One constructed jitted program plus the example arguments to trace
    it with. `donated` mirrors the jit callsite's donate_argnums — the
    spec owner keeps them in sync (they sit lines apart in the source),
    and the donation-aliasing check verifies the LOWERED artifact agrees."""

    fn: Callable
    args: Tuple
    donated: Tuple[int, ...] = ()


@dataclasses.dataclass
class ProgramSpec:
    """Registry entry: a named factory for one hot jitted program.
    `owner` is the package-relative module the program lives in (what
    findings and --changed-only scoping report); `beat_group` marks
    variants that must share pod beat order."""

    name: str
    owner: str
    build: Callable[[], BuiltProgram]
    beat_group: Optional[str] = None


@dataclasses.dataclass
class ProgramFinding:
    program: str
    check: str    # donation-aliasing | collective-order | beat-group |
                  # host-callback | build-error | stale-golden
    message: str

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.program} [{self.check}] {self.message}"


@dataclasses.dataclass
class ProgramReport:
    findings: List[ProgramFinding]
    programs: List[Dict[str, object]]
    updated: List[str]
    elapsed_s: float

    def to_json(self) -> Dict[str, object]:
        return {
            "version": 1,
            "counts": {
                "programs": len(self.programs),
                "findings": len(self.findings),
            },
            "elapsed_s": round(self.elapsed_s, 3),
            "updated": self.updated,
            "programs": self.programs,
            "findings": [f.to_json() for f in self.findings],
        }


# ---------------------------------------------------------------------------
# probe environment (shared by every program_specs() hook)
# ---------------------------------------------------------------------------


def probe_mesh(model_axis: int = 1):
    """The tiny CPU mesh every spec builds under: (data=2, model=1) by
    default; model_axis=2 gives the (data=2, model=2) TP probe mesh the
    `.tp` spec variants build under (docs/MESH.md — a collective reorder
    under the 2D mesh must be a reviewed golden diff, not a pod fork).
    The CLI forces a multi-device CPU platform before importing jax
    (tools/proganalyze.py); under pytest, tests/conftest.py already did."""
    from distributed_ddpg_tpu.parallel import mesh as mesh_lib

    need = PROBE_MESH_DEVICES * model_axis
    devices = jax.devices("cpu")
    if len(devices) < need:
        raise ProgramBuildError(
            f"program specs need >= {need} CPU devices for "
            "the probe mesh; run under XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 (the proganalyze "
            "CLI sets this itself)"
        )
    return mesh_lib.make_mesh(
        PROBE_MESH_DEVICES, model_axis, devices=devices[:need]
    )


def probe_config(**overrides):
    """Tiny-but-real DDPGConfig for spec builds: every dimension shrunk
    so tracing is milliseconds, nothing else changed — the program
    STRUCTURE (op order, donation, collectives) is what ships."""
    from distributed_ddpg_tpu.config import DDPGConfig

    base = dict(
        env_id="Pendulum-v1",
        batch_size=8,
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        replay_capacity=64,
        seed=0,
    )
    base.update(overrides)
    return DDPGConfig(**base)


# ---------------------------------------------------------------------------
# tracing: collective order + callback leaks from the jaxpr
# ---------------------------------------------------------------------------


def _canon_axes(params: Dict) -> str:
    axes = params.get("axes")
    if axes is None:
        axes = params.get("axis_name")
    if axes is None:
        return ""
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return ",".join(str(a) for a in axes)


def _walk_jaxpr(jaxpr, collectives: List[str], callbacks: List[str],
                counts: List[int]) -> None:
    """Depth-first, in-equation order — the deterministic canonical order
    of the traced program. Nested jaxprs (pjit, scan, while, cond,
    shard_map, custom_* ...) are found generically through eqn params."""
    for eqn in jaxpr.eqns:
        counts[0] += 1
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMITIVES:
            axes = _canon_axes(eqn.params)
            collectives.append(f"{name}[{axes}]" if axes else name)
        elif name in CALLBACK_PRIMITIVES:
            callbacks.append(name)
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (list, tuple)) else (val,)):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    _walk_jaxpr(inner, collectives, callbacks, counts)
                elif hasattr(sub, "eqns"):
                    _walk_jaxpr(sub, collectives, callbacks, counts)


def trace_program(built: BuiltProgram, traced=None):
    """(collectives, callbacks, n_eqns) from an abstract trace — no
    compile, no execution. Pass a precomputed `jit(fn).trace(*args)`
    stage to reuse ONE abstract trace across this check and the
    donation-aliasing lowering (tracing dominates the gate's runtime);
    the walk descends nested jaxprs generically, so the traced stage's
    body jaxpr and make_jaxpr's pjit-wrapped one fingerprint alike."""
    if traced is not None:
        closed = traced.jaxpr
    else:
        closed = jax.make_jaxpr(built.fn)(*built.args)
    collectives: List[str] = []
    callbacks: List[str] = []
    counts = [0]
    _walk_jaxpr(closed.jaxpr, collectives, callbacks, counts)
    return collectives, callbacks, counts[0]


def fingerprint(collectives: Sequence[str]) -> str:
    blob = "\n".join(collectives).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:16]


# ---------------------------------------------------------------------------
# lowering: donation aliasing
# ---------------------------------------------------------------------------

_MLIR_DTYPES = {
    "float32": "f32", "float64": "f64", "float16": "f16",
    "bfloat16": "bf16", "int64": "i64", "int32": "i32", "int16": "i16",
    "int8": "i8", "uint64": "ui64", "uint32": "ui32", "uint16": "ui16",
    "uint8": "ui8", "bool": "i1",
}


def _leaf_mlir_type(leaf) -> str:
    dt = _MLIR_DTYPES.get(np.dtype(getattr(leaf, "dtype", np.float32)).name,
                          "?")
    shape = tuple(getattr(leaf, "shape", ()))
    return "x".join([str(d) for d in shape] + [dt])


def _main_signature(text: str) -> Tuple[str, str]:
    """(args, results) segments of the lowered module's public @main func
    — the only place XLA records input-output aliasing and donation."""
    i = text.find("@main(")
    if i < 0:
        return "", ""
    depth = 0
    args_seg = None
    for j in range(i + len("@main"), len(text)):
        c = text[j]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                args_seg = text[i:j + 1]
                rest = text[j + 1:]
                break
    if args_seg is None:
        return text[i:], ""
    m = re.match(r"\s*->\s*", rest)
    if not m:
        return args_seg, ""
    rest = rest[m.end():]
    if rest.startswith("("):
        depth = 0
        for j, c in enumerate(rest):
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
                if depth == 0:
                    return args_seg, rest[:j + 1]
        return args_seg, rest
    return args_seg, rest.split("{", 1)[0]


def check_donation_aliasing(built: BuiltProgram,
                            traced=None) -> Tuple[int, int, List[str]]:
    """(donated_leaves, aliasable_leaves, missing_types): lower the
    program (no compile) and verify every donated leaf will alias an
    output. Two attribute shapes prove it: `tf.aliasing_output` (jax
    resolved the pairing at lowering — only donated buffers carry it) and
    `jax.buffer_donor` (jax deferred the pairing to XLA — the shard_map/
    sharded-output path), which counts only while an output of the SAME
    tensor type remains to pair with: XLA aliases donor buffers by type
    match, so a donor with no matching output is exactly the silent-2x
    case this check exists for. The comparison is by type multiset —
    positional arg-index mapping is deliberately avoided (lowering may
    hoist closure constants into extra args)."""
    if not built.donated:
        return 0, 0, []
    with warnings.catch_warnings():
        # An unaliased donation warns at lower time; the WARNING is noise
        # here — the structured finding is the signal.
        warnings.simplefilter("ignore")
        # A precomputed trace stage lowers WITHOUT re-tracing — the whole
        # point of threading it through from analyze().
        lowered = (traced.lower() if traced is not None
                   else built.fn.lower(*built.args))
    args_seg, out_seg = _main_signature(lowered.as_text())
    parts = re.split(r"(?=%arg\d+:)", args_seg)
    aliased_types: List[str] = []
    donor_types: List[str] = []
    for p in parts:
        m = re.match(r"%arg\d+: tensor<([^>]*)>", p)
        if not m:
            continue
        if "tf.aliasing_output" in p:
            aliased_types.append(m.group(1))
        elif "jax.buffer_donor" in p:
            donor_types.append(m.group(1))
    out_types = re.findall(r"tensor<([^>]*)>", out_seg)
    donated_leaves: List[str] = []
    for i in built.donated:
        if not 0 <= i < len(built.args):
            # The spec's hand-maintained `donated` tuple drifted from the
            # example args: a silently-skipped index would make the check
            # vacuous for exactly that buffer, so it gates (analyze()
            # reports the raise as a build-error finding).
            raise ProgramBuildError(
                f"donated index {i} out of range for {len(built.args)} "
                "example args — the spec's `donated` tuple drifted from "
                "its jit callsite's donate_argnums"
            )
        donated_leaves.extend(
            _leaf_mlir_type(l) for l in jax.tree.leaves(built.args[i])
        )
    explicit = Counter(aliased_types)
    donor_ok = Counter(donor_types) & (Counter(out_types) - explicit)
    missing = Counter(donated_leaves) - explicit - donor_ok
    missing_list = sorted(t for t, n in missing.items() for _ in range(n))
    n_ok = len(donated_leaves) - sum(missing.values())
    return len(donated_leaves), n_ok, missing_list


# ---------------------------------------------------------------------------
# golden fingerprints
# ---------------------------------------------------------------------------


def golden_path(golden_dir: Path, name: str) -> Path:
    return golden_dir / (name + ".json")


def load_golden(golden_dir: Path, name: str) -> Optional[Dict]:
    p = golden_path(golden_dir, name)
    if not p.is_file():
        return None
    try:
        return json.loads(p.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError):
        return {}


def write_golden(golden_dir: Path, name: str,
                 collectives: Sequence[str]) -> None:
    golden_dir.mkdir(parents=True, exist_ok=True)
    golden_path(golden_dir, name).write_text(
        json.dumps(
            {
                "program": name,
                "collectives": list(collectives),
                "fingerprint": fingerprint(collectives),
            },
            indent=1,
        ) + "\n",
        encoding="utf-8",
    )


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------


def analyze(
    specs: Sequence[ProgramSpec],
    golden_dir: Path,
    update_golden: bool = False,
    only: Optional[Sequence[str]] = None,
    sweep_stale: bool = True,
) -> ProgramReport:
    """Run every check over `specs`. `only` filters by program name
    (exact or fnmatch glob) — a scoped run skips the stale-golden sweep,
    since unmatched goldens belong to programs it never looked at.
    `sweep_stale=False` disables the sweep AND the --update-golden prune
    even unscoped: an alternate registry (the CLI's --specs) covers none
    of the live programs, so against the default golden dir the sweep
    would flag — and the prune would DELETE — every committed golden."""
    t0 = time.perf_counter()
    scoped = only is not None or not sweep_stale
    if only is not None:
        specs = [
            s for s in specs
            if any(fnmatch.fnmatch(s.name, pat) for pat in only)
        ]
    findings: List[ProgramFinding] = []
    programs: List[Dict[str, object]] = []
    updated: List[str] = []
    by_group: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}

    for spec in specs:
        try:
            built = spec.build()
            # One abstract trace serves both checks when the program is
            # donated AND jitted (fixture specs may hand a bare callable
            # with donated=() where only make_jaxpr applies).
            traced = (built.fn.trace(*built.args)
                      if built.donated and hasattr(built.fn, "trace")
                      else None)
            collectives, callbacks, n_eqns = trace_program(built, traced)
            donated_leaves, aliased, missing = check_donation_aliasing(
                built, traced)
        except Exception as e:  # a spec that cannot build must gate
            findings.append(ProgramFinding(
                spec.name, "build-error",
                f"program spec failed to build/trace: {e!r:.400}",
            ))
            continue
        fp = fingerprint(collectives)
        programs.append({
            "name": spec.name,
            "owner": spec.owner,
            "beat_group": spec.beat_group,
            "collectives": collectives,
            "fingerprint": fp,
            "eqns": n_eqns,
            "donated_args": list(built.donated),
            "donated_leaves": donated_leaves,
            "aliased_leaves": aliased,
        })
        if spec.beat_group:
            by_group.setdefault(spec.beat_group, []).append(
                (spec.name, tuple(collectives))
            )

        if aliased < donated_leaves:
            findings.append(ProgramFinding(
                spec.name, "donation-aliasing",
                f"{donated_leaves - aliased} of {donated_leaves} donated "
                "buffer leaves failed to alias any output in the lowered "
                f"program (unaliased: {', '.join(missing) or '?'}) — "
                "donation without aliasing is a silent 2x HBM cost on "
                "exactly the buffers it was meant to recycle; align the "
                "donated input's shape/dtype with an output or drop it "
                "from donate_argnums",
            ))
        for cb in sorted(set(callbacks)):
            findings.append(ProgramFinding(
                spec.name, "host-callback",
                f"`{cb}` primitive embedded in the hot program "
                f"({callbacks.count(cb)}x) — a host round-trip inside a "
                "jitted training program couples every pod peer's beat "
                "to one host's Python scheduler; hoist the callback out "
                "of the compiled path (debug prints included)",
            ))

        if update_golden:
            prev = load_golden(golden_dir, spec.name)
            if prev is None or prev.get("collectives") != collectives:
                updated.append(spec.name)
            write_golden(golden_dir, spec.name, collectives)
        else:
            golden = load_golden(golden_dir, spec.name)
            if golden is None:
                findings.append(ProgramFinding(
                    spec.name, "collective-order",
                    "no golden fingerprint committed for this program — "
                    "run `python -m distributed_ddpg_tpu.tools."
                    "proganalyze --update-golden` and review/commit the "
                    "golden diff",
                ))
            elif golden.get("collectives") != collectives:
                findings.append(ProgramFinding(
                    spec.name, "collective-order",
                    "collective order diverged from the committed golden "
                    f"(golden: {golden.get('collectives')} -> traced: "
                    f"{collectives}) — on a pod this is exactly how "
                    "replicas fork into PodPeerLost/exit-76; if the "
                    "reorder is intentional, re-run with --update-golden "
                    "and review the golden diff",
                ))

    for group, members in sorted(by_group.items()):
        if len({seq for _, seq in members}) > 1:
            detail = "; ".join(
                f"{name}: [{', '.join(seq) or 'none'}]"
                for name, seq in members
            )
            findings.append(ProgramFinding(
                members[0][0], "beat-group",
                f"beat group '{group}' variants disagree on collective "
                f"order ({detail}) — these programs dispatch at the SAME "
                "lockstep site, so a pod mixing them forks its device-op "
                "order",
            ))

    if not scoped and not update_golden and golden_dir.is_dir():
        known = {s.name for s in specs}
        for p in sorted(golden_dir.glob("*.json")):
            if p.stem not in known:
                findings.append(ProgramFinding(
                    p.stem, "stale-golden",
                    f"golden file {p.name} matches no registered program "
                    "spec — a renamed/removed program must retire its "
                    "golden (delete it, or re-run --update-golden which "
                    "prunes stale files)",
                ))
    if update_golden and not scoped and golden_dir.is_dir():
        known = {s.name for s in specs}
        for p in sorted(golden_dir.glob("*.json")):
            if p.stem not in known:
                p.unlink()
                updated.append(f"-{p.stem}")

    return ProgramReport(
        findings=findings,
        programs=programs,
        updated=updated,
        elapsed_s=time.perf_counter() - t0,
    )


# ---------------------------------------------------------------------------
# the default registry
# ---------------------------------------------------------------------------

# Modules exposing a program_specs() hook; --changed-only scoping in the
# CLI keys on the owner paths these specs declare.
SPEC_MODULES = (
    "distributed_ddpg_tpu.parallel.learner",
    "distributed_ddpg_tpu.parallel.megastep",
    "distributed_ddpg_tpu.parallel.superstep",
    "distributed_ddpg_tpu.replay.device",
    "distributed_ddpg_tpu.actors.device_pool",
    "distributed_ddpg_tpu.serve.server",
    "distributed_ddpg_tpu.ondevice",
)


def default_specs() -> List[ProgramSpec]:
    """Every registered hot program in the live tree (the subsystem
    program_specs() hooks), name-deduplicated and order-stable."""
    import importlib

    specs: List[ProgramSpec] = []
    for modname in SPEC_MODULES:
        mod = importlib.import_module(modname)
        specs.extend(mod.program_specs())
    names = [s.name for s in specs]
    dupes = [n for n, c in Counter(names).items() if c > 1]
    if dupes:
        raise ValueError(f"duplicate program spec names: {dupes}")
    return specs


def render_human(report: ProgramReport) -> str:
    out = [f.render() for f in report.findings]
    n = len(report.findings)
    if report.updated:
        out.append(f"updated goldens: {', '.join(report.updated)}")
    out.append(
        f"{len(report.programs)} programs, {n} finding"
        f"{'s' if n != 1 else ''} in {report.elapsed_s:.2f}s"
    )
    return "\n".join(out)


def write_report(report: ProgramReport, path: Path) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(report.to_json(), indent=1) + "\n",
                    encoding="utf-8")
