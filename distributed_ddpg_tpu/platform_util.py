"""One shared platform-selection guard for every entry point.

This image's site customization programmatically rewrites JAX's platform
selection after import, so exporting JAX_PLATFORMS alone is NOT honored —
the value must be re-asserted through jax.config after importing jax.
Every CLI/benchmark entry point (train.main, ladder.main, bench.py,
__graft_entry__.py) calls this before its first JAX operation; keeping it
in one place keeps the workaround from drifting between copies.
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> None:
    """Re-assert the JAX_PLATFORMS env var (when set) via jax.config, which
    survives site customizations that override plain env-var selection.
    Must run before the first operation that initializes an XLA backend."""
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
