"""Stall watchdog: failure detection for the device-bound hot loop
(SURVEY.md §5 'Failure detection' row).

The actor side already has heartbeats + respawn (actors/pool.py) because
workers are stateless. The LEARNER side's failure mode is different: every
device interaction (`device_get`, dispatch, even PJRT client creation on a
tunneled TPU) is a potentially-unbounded blocking call with no timeout
parameter, so a wedged device/transport turns the trainer into a silent
hang — observed in-round as a `jax.device_get` that never returned after
the remote tunnel dropped. A hang is the worst outcome for a driver-managed
run: a crash gets retried/diagnosed, a hang eats the whole wall-clock
budget.

`Watchdog` converts that hang into a loud, debuggable crash. When progress
stops advancing for `timeout_s` it:

  1. writes a STRUCTURED stall report (`stall_report.json`: every thread's
     stack as JSON, last progress value, seconds stalled) plus — when the
     flight recorder (trace.py) is enabled — `stall_trace.json`, the
     last-N-seconds cross-thread timeline, into `stall_dir`. Both writes
     are best-effort: a full disk must not mask the stall itself;
  2. dumps every thread's stack to stderr (faulthandler — shows exactly
     which device call wedged) and hard-exits via `os._exit` (the default
     `on_stall`). `os._exit` is deliberate: normal teardown would block on
     the same wedged device (pool.stop syncs, AsyncSaver waits), and
     atexit handlers of a wedged PJRT client can hang too.

Step 1 is what turns "exit 70 + a wall of stacks" into a diagnosable
artifact set: the trace answers what the shipper/prefetcher/eval threads
were doing in the seconds BEFORE the learner thread wedged, which the
stack dump (a single instant) cannot.

Enabled by `config.watchdog_s > 0` (train.py wires it around train_jax's
whole device lifetime, including learner construction and the first
params d2h — both observed wedge points).

Coverage note: this watchdog catches LEARNER-side wedges (device calls
that never return). Two adjacent failure modes are owned elsewhere and
exit differently (docs/RESILIENCE.md exit-code contract): a HOST-initiated
pod collective whose peer died is bounded by the pod collective deadline
(parallel/multihost.py PodPeerLost -> coordinated clean abort, exit 76) —
keep pod_collective_timeout_s well under watchdog_s so peer loss surfaces
as the resumable 76, with this watchdog's 70 as the backstop for
collectives INSIDE jitted dispatch, which no host-side deadline can
bound. An actor-side stall — workers heartbeating but
producing no experience — is invisible to it, because the warmup/cap
loops beat every iteration whether or not rows moved. That blind spot is
covered twice over: PER-WORKER by the pool monitor's zero-rows detector
(config.actor_no_progress_s — a worker that heartbeats but delivers no
rows past the threshold is respawned through the same backoff/quarantine
path as a dead one; actors/pool.py), and FLEET-WIDE by train.py's
secondary deadline (no ingest at all for 10x watchdog_s raises a loud
RuntimeError on the healthy learner thread). The first post-warmup
dispatch gets a one-time `grant()` so its XLA compile isn't killed as a
false stall."""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Callable, Optional

from distributed_ddpg_tpu import trace

# EX_SOFTWARE: internal failure, distinguishable from OOM/kill. The code
# itself lives in the one-place exit contract (exits.py).
from distributed_ddpg_tpu.exits import EXIT_WATCHDOG_STALL as _EXIT_CODE

# stop() reap bound for the watchdog thread. The thread polls _stop every
# poll tick, so this only trips when the watchdog itself is wedged mid-
# artifact-write — and then the daemon flag reaps it at exit anyway.
_STOP_JOIN_S = 5.0


def _default_on_stall(timeout_s: float) -> None:
    sys.stderr.write(
        f"\n=== watchdog: no trainer progress for {timeout_s:.0f}s — "
        "dumping all thread stacks and aborting (a blocking device call "
        f"has likely wedged; exit code {_EXIT_CODE}) ===\n"
    )
    sys.stderr.flush()
    faulthandler.dump_traceback(all_threads=True)
    os._exit(_EXIT_CODE)


class Watchdog:
    """Fire `on_stall` if `progress()` stops changing for `timeout_s`.

    `progress` must be cheap, thread-safe, and must never touch the device
    (a device call inside the watchdog would wedge the watchdog with the
    thing it watches) — an int counter bumped by the supervised loop is the
    intended shape.

    `stall_dir`: where the structured stall artifacts land before
    `on_stall` runs (stall_report.json + stall_trace.json — see module
    docstring). None disables artifact writing (unit tests of the bare
    firing logic). `trace_window_s` bounds the exported timeline to the
    run-up to the stall.
    """

    def __init__(
        self,
        timeout_s: float,
        progress: Callable[[], object],
        on_stall: Optional[Callable[[], None]] = None,
        stall_dir: Optional[str] = None,
        trace_window_s: float = 30.0,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self._timeout_s = timeout_s
        self._progress = progress
        self._on_stall = on_stall or (lambda: _default_on_stall(timeout_s))
        self._stall_dir = stall_dir
        self._trace_window_s = trace_window_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._grant_deadline = 0.0
        self._grant_lock = threading.Lock()
        # Paths written by the stall path; exposed so a custom on_stall
        # (tests, alternative supervisors) can pick the artifacts up.
        self.stall_artifacts: dict = {}

    def grant(self, extra_s: float) -> None:
        """Suppress firing until `extra_s` seconds from NOW (wall-clock
        deadline, not beat-relative): progress beats between grant() and the
        protected long call must not consume the allowance — the caller
        can't always avoid beating in between. Used for the first
        post-warmup learner dispatch, which includes the full XLA compile
        of the chunk program — worst-case compile (large nets, multihost
        meshes) can exceed a `timeout_s` tuned for steady-state dispatch
        latency, and a compile killed as a false stall exits 70 exactly
        like a real wedge."""
        with self._grant_lock:
            self._grant_deadline = max(
                self._grant_deadline, time.monotonic() + float(extra_s)
            )

    def start(self) -> "Watchdog":
        self._thread = threading.Thread(
            target=self._run, name="stall-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=_STOP_JOIN_S)

    def _write_stall_artifacts(self, last_value, stalled_s: float) -> None:
        """Best-effort structured stall dump BEFORE on_stall (which, by
        default, os._exits). trace.stall_report never raises."""
        if self._stall_dir is None:
            return
        self.stall_artifacts = trace.stall_report(
            self._stall_dir,
            reason=(
                f"watchdog: no trainer progress for {self._timeout_s:.0f}s"
            ),
            timeout_s=self._timeout_s,
            window_s=self._trace_window_s,
            extra={
                "last_progress_value": repr(last_value),
                "stalled_s": round(stalled_s, 3),
            },
        )
        if self.stall_artifacts:
            sys.stderr.write(
                "watchdog: stall artifacts written: "
                + ", ".join(sorted(self.stall_artifacts.values()))
                + "\n"
            )
            sys.stderr.flush()

    def _run(self) -> None:
        last = self._progress()
        last_change = time.monotonic()
        # Poll well inside the timeout so a stall is detected within
        # ~1.25x timeout_s worst-case.
        poll = max(0.05, self._timeout_s / 4.0)
        while not self._stop.wait(poll):
            now_val = self._progress()
            now = time.monotonic()
            if now_val != last:
                last = now_val
                last_change = now
            elif now - last_change >= self._timeout_s:
                with self._grant_lock:
                    granted = now < self._grant_deadline
                if not granted:
                    # Telemetry plane first (obs/health.py): /healthz must
                    # read `draining` while the artifacts below are being
                    # written — the last scrape a supervisor gets from a
                    # wedged process should say "terminal", not "healthy".
                    # Latched, never raises; broad except because the
                    # stall path must not gain failure modes.
                    try:
                        from distributed_ddpg_tpu.obs import health

                        health.get().drain(
                            "watchdog stall: no trainer progress for "
                            f"{now - last_change:.0f}s"
                        )
                    except Exception:
                        pass
                    self._write_stall_artifacts(last, now - last_change)
                    self._on_stall()
                    return
