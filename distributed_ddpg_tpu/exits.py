"""The typed exit-code contract, in ONE place (docs/RESILIENCE.md).

A supervising driver keys recovery decisions off nothing but the child's
exit status, so these numbers are a cross-process API: train.py raises
them, watchdog.py hard-exits with one, the chaos/pod test children
assert on them, and supervisor/core.py dispatches on them. Before this
module they were scattered literals (train.py, watchdog.py, tests) — one
drifted copy turns "shrink-ready, relaunch smaller" into "unknown
crash, relaunch blindly". The `exit-code-literal` lint rule
(analysis/rules.py) now rejects any new bare typed literal outside this
file.

The contract, in supervisor-action order:

  EXIT_OK (0)               budget complete, clean teardown. Done.
  EXIT_WATCHDOG_STALL (70)  EX_SOFTWARE: no trainer progress for
                            watchdog_s — a blocking device call wedged.
                            State on disk is whatever the last cadence
                            checkpoint holds; relaunch-in-place with
                            backoff.
  EXIT_PREEMPTED (75)       EX_TEMPFAIL: SIGTERM landed; one emergency
                            checkpoint written. Fully resumable —
                            relaunch-in-place.
  EXIT_POD_DEGRADED (76)    a pod PEER died/hung mid-collective
                            (PodPeerLost) and NO verified replay slice
                            set exists. Emergency checkpoint written;
                            relaunch the WHOLE pod (same dirs — the
                            resume election restores one common step).
  EXIT_NUMERIC (77)         guardrails exhausted the rollback budget;
                            params presumed poisoned, NO checkpoint
                            written. Do NOT blindly relaunch — inspect
                            guardrail_* counters first.
  EXIT_POD_SHRINK (78)      peer lost AND a complete, digest-verified
                            all-writer slice set is on disk — relaunch
                            at ANY M (including without the lost host);
                            slice adoption reshards replay and the run
                            continues typed-degraded until a grow.
  EXIT_SUPERVISOR_GAVE_UP (79)
                            the supervisor itself refused to continue —
                            crash-loop circuit breaker tripped or a
                            numeric abort exceeded supervisor_max_numeric.
                            A structured SupervisorGaveUp report (JSON)
                            says why; a human decides next.

Negative statuses (as subprocess reports them) are deaths by signal and
are NOT part of the contract — `describe()` names them for event logs.
"""

from __future__ import annotations

import signal

EXIT_OK = 0
EXIT_WATCHDOG_STALL = 70
EXIT_PREEMPTED = 75
EXIT_POD_DEGRADED = 76
EXIT_NUMERIC = 77
EXIT_POD_SHRINK = 78
EXIT_SUPERVISOR_GAVE_UP = 79

# Event-log / report names for the typed codes (supervisor/events.py,
# tools/runs.py supervision timeline).
NAMES = {
    EXIT_OK: "ok",
    EXIT_WATCHDOG_STALL: "watchdog_stall",
    EXIT_PREEMPTED: "preempted",
    EXIT_POD_DEGRADED: "pod_degraded",
    EXIT_NUMERIC: "numeric_abort",
    EXIT_POD_SHRINK: "pod_shrink_ready",
    EXIT_SUPERVISOR_GAVE_UP: "supervisor_gave_up",
}


def describe(code) -> str:
    """Human/event-log name for a subprocess returncode: typed contract
    names for the codes above, `signal:SIGKILL`-style for deaths by
    signal (negative, as subprocess reports them), `exit:<n>` for
    untyped statuses, `unknown` for a still-running child (None)."""
    if code is None:
        return "unknown"
    code = int(code)
    if code < 0:
        try:
            return f"signal:{signal.Signals(-code).name}"
        except ValueError:
            return f"signal:{-code}"
    return NAMES.get(code, f"exit:{code}")
