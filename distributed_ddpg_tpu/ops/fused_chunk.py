"""Pallas TPU megakernel: K full DDPG learner steps in ONE kernel launch,
with every parameter tensor resident in VMEM for the whole chunk.

Motivation (SURVEY.md §3.3 hot loop): at DDPG scale (2x256 MLPs, batch 64)
the XLA scan path is bound by parameter HBM traffic — each step re-reads and
re-writes params, targets, and both Adam moments (~5 MB/step), roughly half
the measured 11 us/step on v5e-1. This kernel walks the chunk as a grid of
K steps whose param/target/moment blocks have CONSTANT index maps, so Mosaic
fetches them into VMEM once, revisits them across all K grid steps, and
writes them back to HBM once at the end (the standard accumulator pattern).
Only the K minibatches stream from HBM (~11 KB/step), double-buffered by the
pallas pipeline.

The forward/backward math is written out by hand (trace-time Python loops
over layers; everything stays in VMEM):

  critic loss   L_c = mean(w * (r + disc * Q'(s', mu'(s')) - Q(s,a))^2)
  actor  loss   L_a = -mean(Q(s, mu(s)))          (DPG; bwd through the
                                                   critic to the action)
  Adam (ops/optim.py formulas, bias correction from the carried count)
  Polyak        t <- tau * p + (1 - tau) * t      (ops/polyak.py)

Semantics match learner.make_learner_step exactly: both gradients are taken
against the PRE-update params of the step; tests/test_fused_chunk.py pins the
kernel to the XLA scan path over a whole chunk.

D4PG (C51, ops/losses.py:111-160 semantics) runs in the same kernel: the
critic head emits num_atoms logits, the categorical projection is computed
in-kernel as an unrolled accumulation over atoms — proj += p'[:, i:i+1] *
relu(1 - |tz[:, i:i+1] - z|/dz), the triangular-kernel form of the
lower/upper-neighbor mass split, rank-2 throughout so Mosaic never sees a
3D tensor — and the hand-written backward uses the closed-form categorical
cotangents (softmax(logits) - proj for the critic CE; -p * (z - E[Z]) / B
for the actor's expected-value head).

SAC (ops/losses.py sac_critic_loss / sac_actor_loss semantics) runs in the
same kernel too: the Gaussian head's [mean | log_std] split, the tanh
soft-clamp of log_std, reparameterized sampling (the per-step standard
normals stream in pre-drawn from the scan path's exact fold_in key stream,
like TD3's smoothing noise), the tanh-squash log-prob, the entropy-
corrected twin-critic TD target, and the learned temperature's scalar Adam
all execute in-kernel; the hand-written actor backward routes the min-Q
gate with reduce_min's tie-splitting vjp and chains d(log pi)/du =
2*scale*t*(1-t^2)/g through the squash correction.

Mixed precision (config.compute_dtype='bfloat16') casts matmul operands to
bf16 with f32 accumulation (`preferred_element_type`), forward AND backward,
mirroring models/mlp._dense; params, Adam state, and activations stay f32.

Supported envelope (callers must check `supported(config)`):
  - action_insert_layer == 1, critic_l2 == 0
  - any MLP depths/widths that fit VMEM (the DDPG/D4PG families all do)

On non-TPU backends the kernel runs in pallas interpret mode: numerics are
identical, speed is not (the XLA scan path remains the CPU choice).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import math

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.ops.optim import B1, B2, EPS
from distributed_ddpg_tpu.types import TrainState, OptState

_LOG_B1 = math.log(B1)
_LOG_B2 = math.log(B2)
_LOG_2PI = math.log(2.0 * math.pi)
# Tanh-squash log-det guard — MUST match losses._TANH_EPS for parity.
_TANH_EPS = 1e-6

# Fixed order in which a params tree (tuple of {"w","b"} dicts) is flattened
# into the kernel's ref list: w0, b0, w1, b1, ...  Biases ride as (1, F) rows
# so every ref is rank-2 (TPU VMEM wants >= 2D; (F,) -> (1, F) is layout-free).


def _flatten(params) -> list:
    out = []
    for layer in params:
        out.append(layer["w"])
        out.append(layer["b"].reshape(1, -1))
    return out


def _unflatten(flat: Sequence[Any], like) -> Tuple:
    layers = []
    for i, layer in enumerate(like):
        layers.append(
            {"w": flat[2 * i], "b": flat[2 * i + 1].reshape(layer["b"].shape)}
        )
    return tuple(layers)


def _flatten_twin(params) -> list:
    """TD3 ensemble tree (leaves [2, ...]) -> member-0 layers then member-1
    layers, every ref rank-2 (Mosaic never sees the ensemble axis)."""
    out = []
    for m in range(2):
        for layer in params:
            out.append(layer["w"][m])
            out.append(layer["b"][m].reshape(1, -1))
    return out


def _unflatten_twin(flat: Sequence[Any], like) -> Tuple:
    n = len(like)
    members = []
    for m in range(2):
        layers = []
        for i in range(n):
            layers.append(
                {
                    "w": flat[m * 2 * n + 2 * i],
                    "b": flat[m * 2 * n + 2 * i + 1].reshape(
                        like[i]["b"].shape[1:]
                    ),
                }
            )
        members.append(tuple(layers))
    return jax.tree.map(
        lambda a, b: jnp.stack([a, b]), members[0], members[1]
    )


def state_vmem_bytes(config: DDPGConfig, obs_dim: int, act_dim: int) -> int:
    """f32 bytes of the kernel's VMEM-resident state: 8 copies of each net's
    tensors (params, targets, mu, nu for actor+critic). The pipeline holds
    input AND output blocks for each, so callers should budget ~2x this."""

    def net(dims, extra_in=0):
        total = 0
        for i in range(len(dims) - 1):
            d_in = dims[i] + (extra_in if i == 1 else 0)
            total += d_in * dims[i + 1] + dims[i + 1]
        return total

    # obs/act enter the actor/critic input dims; action rides into critic
    # layer 1 (action_insert_layer == 1 inside the supported envelope).
    # The C51 head widens the critic output to num_atoms logits; the TD3
    # twin ensemble doubles every critic tensor; SAC doubles both the
    # actor head ([mean | log_std]) and the critic (its own ensemble).
    out = config.num_atoms if config.distributional else 1
    head = 2 * act_dim if config.sac else act_dim
    a = net([obs_dim, *config.actor_hidden, head])
    c = net([obs_dim, *config.critic_hidden, out], extra_in=act_dim)
    if config.twin_critic or config.sac:
        c *= 2
    return 4 * (4 * a + 4 * c)


# Conservative VMEM budget for the resident state (of ~16 MB/core): leaves
# room for the doubled in/out blocks, batch stream buffers, and activations.
VMEM_STATE_BUDGET = 6 * 1024 * 1024


def fits_vmem(config: DDPGConfig, obs_dim: int, act_dim: int) -> bool:
    return state_vmem_bytes(config, obs_dim, act_dim) <= VMEM_STATE_BUDGET


def supported(config: DDPGConfig) -> bool:
    return (
        config.action_insert_layer == 1
        and config.critic_l2 == 0.0
        and not config.fused_update
        and config.compute_dtype in ("float32", "bfloat16")
        # The hand-written backward assumes the action-insert layer (1) is
        # not the critic's output layer, i.e. at least 2 hidden layers.
        and len(config.critic_hidden) >= 2
        and len(config.actor_hidden) >= 1
        # The C51 projection unrolls num_atoms accumulation steps at trace
        # time; cap it so a pathological config can't explode the kernel.
        and (not config.distributional or config.num_atoms <= 256)
    )


def _sq(tree_leaves) -> Any:
    return sum(jnp.sum(x * x) for x in tree_leaves)


def _make_kernel(
    n_actor: int, n_critic: int, batch: int, chunk: int, config,
    sac_target_entropy: float | None = None,
):
    """Builds the kernel body. n_actor/n_critic = number of linear layers.
    `sac_target_entropy` is the trace-time scalar the wrapper resolves with
    the scan path's exact rule (learner.make_learner_step sac_step)."""
    tau = float(config.tau)
    lr_a = float(config.actor_lr)
    lr_c = float(config.critic_lr)
    inv_b = 1.0 / float(batch)
    inv_k = 1.0 / float(chunk)
    na2, nc2 = 2 * n_actor, 2 * n_critic
    distributional = bool(config.distributional)
    num_atoms = int(config.num_atoms)
    v_min, v_max = float(config.v_min), float(config.v_max)
    dz_atom = (v_max - v_min) / (num_atoms - 1)
    twin = bool(config.twin_critic)
    policy_delay = int(config.policy_delay)
    has_noise = twin and config.target_noise > 0.0
    sac = bool(config.sac)
    autotune = sac and bool(config.sac_autotune)
    # SAC log_std soft clamp: log_std = m0 + hw * (tanh(raw) + 1)
    # (models/mlp.actor_gaussian_apply).
    m0 = float(config.sac_log_std_min)
    hw = 0.5 * (float(config.sac_log_std_max) - m0)
    # Per-member critic ref count vs the total across the TD3/SAC ensemble.
    nct = nc2 * (2 if (twin or sac) else 1)
    # Resident temperature refs: log_alpha, plus its Adam mu/nu when learned.
    n_alpha = (3 if autotune else 1) if sac else 0

    # Mixed precision: cast matmul operands to bf16, accumulate f32 —
    # forward and backward alike (mirrors models/mlp._dense). Everything
    # outside the dots (activations, Adam, Polyak, projection) stays f32.
    if config.compute_dtype == "bfloat16":
        cast = lambda x: x.astype(jnp.bfloat16)  # noqa: E731
    else:
        cast = lambda x: x  # noqa: E731

    def _mm(a, b):
        return jnp.dot(cast(a), cast(b), preferred_element_type=jnp.float32)

    def _dW(x, dz):
        # x: [B, in], dz: [B, out] -> [in, out]; contract the batch dim
        # without materializing a transpose.
        return jax.lax.dot_general(
            cast(x), cast(dz), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def _dx(dz, w):
        # dz: [B, out], w: [in, out] -> [B, in]; contract out dims.
        return jax.lax.dot_general(
            cast(dz), cast(w), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def kernel(*refs):
        it = iter(range(len(refs)))

        def take(n):
            return [refs[next(it)] for _ in range(n)]

        (count_ref,) = take(1)
        obs_r, act_r, rew_r, disc_r, nobs_r, wgt_r, scale_r, off_r = take(8)
        if distributional:
            (z_ref,) = take(1)  # categorical support, (1, num_atoms)
        if has_noise:
            (eps_r,) = take(1)  # target-smoothing noise stream, [K, B, act]
        if sac:
            # Pre-drawn standard normals: critic-target draw a'~pi(.|s')
            # and actor-pass draw a~pi(.|s), one [K, B, act] stream each.
            eps_next_r, eps_cur_r = take(2)
        actor_in = take(na2)
        critic_in = take(nct)
        t_actor_in = take(na2)
        t_critic_in = take(nct)
        amu_in, anu_in = take(na2), take(na2)
        cmu_in, cnu_in = take(nct), take(nct)
        alpha_in = take(n_alpha)
        td_out, met_out = take(2)
        actor_o = take(na2)
        critic_o = take(nct)
        t_actor_o = take(na2)
        t_critic_o = take(nct)
        amu_o, anu_o = take(na2), take(na2)
        cmu_o, cnu_o = take(nct), take(nct)
        alpha_o = take(n_alpha)

        def cm(group, m):
            """Member m's ref slice of a critic group (whole group when not
            an ensemble — the ensemble axis was flattened into the ref
            list)."""
            return (
                group[m * nc2 : (m + 1) * nc2] if (twin or sac) else group
            )

        k = pl.program_id(0)

        # Step 0: seed the VMEM-resident state blocks from the inputs. They
        # are revisited (constant index maps) for the rest of the grid, so
        # every later step reads/writes the output blocks only.
        @pl.when(k == 0)
        def _seed():
            for src, dst in zip(
                actor_in + critic_in + t_actor_in + t_critic_in
                + amu_in + anu_in + cmu_in + cnu_in + alpha_in,
                actor_o + critic_o + t_actor_o + t_critic_o
                + amu_o + anu_o + cmu_o + cnu_o + alpha_o,
            ):
                dst[...] = src[...]

        def W(group, i):
            return group[2 * i][...]

        def Bv(group, i):
            return group[2 * i + 1][...]

        obs = obs_r[0]
        action = act_r[0]
        rew = rew_r[0]
        disc = disc_r[0]
        nobs = nobs_r[0]
        wgt = wgt_r[0]
        scale = scale_r[...]
        offset = off_r[...]

        # ---- forwards ----------------------------------------------------
        def actor_fwd(group, x):
            """Returns (u, cache) where cache = (pre-acts h_i, activations)."""
            acts = [x]
            for i in range(n_actor - 1):
                z = _mm(acts[-1], W(group, i)) + Bv(group, i)
                acts.append(jnp.maximum(z, 0.0))
            z = _mm(acts[-1], W(group, n_actor - 1)) + Bv(group, n_actor - 1)
            t = jnp.tanh(z)
            return t * scale + offset, (acts, t)

        def critic_fwd(group, x, a):
            """Classic DDPG: action enters at layer 1 (split-weight trick —
            layer 1's weight rows [0:F) multiply the features, rows [F:F+A)
            multiply the action; same math as concat([h, a]) @ W)."""
            acts = [x]
            z0 = _mm(x, W(group, 0)) + Bv(group, 0)
            h0 = jnp.maximum(z0, 0.0)
            acts.append(h0)
            w1 = W(group, 1)
            f = h0.shape[-1]
            z1 = _mm(h0, w1[:f]) + _mm(a, w1[f:]) + Bv(group, 1)
            h1 = jnp.maximum(z1, 0.0)
            acts.append(h1)
            for i in range(2, n_critic - 1):
                z = _mm(acts[-1], W(group, i)) + Bv(group, i)
                acts.append(jnp.maximum(z, 0.0))
            q = _mm(acts[-1], W(group, n_critic - 1)) + Bv(group, n_critic - 1)
            return q, acts  # q: [B, 1]

        def critic_bwd(group, acts, a, dq_in, wgrads: bool):
            """Backprop dq through the critic. With wgrads, returns
            (param grads aligned with group order, d_action); without, only
            d_action is computed (the actor pass needs no critic dW — skips
            n_critic batch-contraction matmuls per step)."""
            grads = [None] * nc2
            dz = dq_in
            for i in range(n_critic - 1, 1, -1):
                if wgrads:
                    grads[2 * i] = _dW(acts[i], dz)
                    grads[2 * i + 1] = jnp.sum(dz, axis=0, keepdims=True)
                dh = _dx(dz, W(group, i))
                dz = dh * (acts[i] > 0.0)
            # layer 1 (split weights)
            w1 = W(group, 1)
            f = acts[1].shape[-1]
            da = _dx(dz, w1[f:])
            if not wgrads:
                return None, da
            grads[2] = jnp.concatenate(
                [_dW(acts[1], dz), _dW(a, dz)], axis=0
            )
            grads[3] = jnp.sum(dz, axis=0, keepdims=True)
            dh0 = _dx(dz, w1[:f])
            dz0 = dh0 * (acts[1] > 0.0)
            # layer 0
            grads[0] = _dW(acts[0], dz0)
            grads[1] = jnp.sum(dz0, axis=0, keepdims=True)
            return grads, da

        def mlp_bwd(group, acts, dz):
            """Plain-MLP backward from the output-layer cotangent dz
            ([B, out]); returns param grads aligned with the group order.
            Shared by the deterministic actor (after its tanh chain) and
            the SAC Gaussian head (whose output layer is linear)."""
            grads = [None] * na2
            grads[2 * (n_actor - 1)] = _dW(acts[n_actor - 1], dz)
            grads[2 * (n_actor - 1) + 1] = jnp.sum(dz, axis=0, keepdims=True)
            for i in range(n_actor - 2, -1, -1):
                dh = _dx(dz, W(group, i + 1))
                dz = dh * (acts[i + 1] > 0.0)
                grads[2 * i] = _dW(acts[i], dz)
                grads[2 * i + 1] = jnp.sum(dz, axis=0, keepdims=True)
            return grads

        def adam_only(n2, p_o, mu_o, nu_o, grads, lr, t_step):
            # B^t as exp(t*log(B)) — Mosaic has no powf with a traced
            # exponent (fails to legalize 'math.powf' on real TPU).
            bc1 = 1.0 - jnp.exp(t_step * jnp.float32(_LOG_B1))
            bc2 = 1.0 - jnp.exp(t_step * jnp.float32(_LOG_B2))
            for j in range(n2):
                g = grads[j]
                m = B1 * mu_o[j][...] + (1.0 - B1) * g
                v = B2 * nu_o[j][...] + (1.0 - B2) * (g * g)
                mu_o[j][...] = m
                nu_o[j][...] = v
                p_o[j][...] = p_o[j][...] - lr * (m / bc1) / (
                    jnp.sqrt(v / bc2) + EPS
                )

        def polyak_only(n2, p_o, t_o):
            for j in range(n2):
                t_o[j][...] = tau * p_o[j][...] + (1.0 - tau) * t_o[j][...]

        def emit(td, step_metrics):
            """Write the per-step TD block and accumulate the chunk-MEAN
            metrics into the revisited (1, len(METRIC_KEYS)) block — see
            the layout rationale in the DDPG tail below."""
            td_out[0] = td
            assert len(step_metrics) == met_out.shape[-1]
            vals = jnp.stack(step_metrics).reshape(1, -1) * inv_k

            @pl.when(k == 0)
            def _met_seed():
                met_out[...] = vals

            @pl.when(k > 0)
            def _met_acc():
                met_out[...] = met_out[...] + vals

        if sac:
            # ==== SAC branch (losses.sac_critic_loss / sac_actor_loss ====
            # ==== + learner.sac_step semantics), then early return     ====
            A = scale.shape[-1]

            def gauss_fwd(group, x):
                """Gaussian head: relu MLP, linear [mean | log_std_raw]
                output, tanh soft-clamp of log_std onto [min, max]
                (models/mlp.actor_gaussian_apply). Returns
                (mean, log_std, tr, acts) with tr = tanh(raw) cached for
                the clamp's backward."""
                acts = [x]
                for i in range(n_actor - 1):
                    z = _mm(acts[-1], W(group, i)) + Bv(group, i)
                    acts.append(jnp.maximum(z, 0.0))
                zL = _mm(acts[-1], W(group, n_actor - 1)) + Bv(
                    group, n_actor - 1
                )
                mean = zL[:, :A]
                tr = jnp.tanh(zL[:, A:])
                log_std = m0 + hw * (tr + 1.0)
                return mean, log_std, tr, acts

            def sample(mean, log_std, eps):
                """Reparameterized tanh-Gaussian draw + log-prob
                (losses.sac_sample with the normal pre-drawn): because
                u = mean + std*eps, (u-mean)/std == eps exactly, so the
                Gaussian term needs no u."""
                std = jnp.exp(log_std)
                u = mean + std * eps
                t = jnp.tanh(u)
                a_env = t * scale + offset
                g = scale * (1.0 - t * t) + _TANH_EPS
                lp_dim = (
                    -0.5 * (eps * eps) - log_std - 0.5 * _LOG_2PI
                    - jnp.log(g)
                )
                lp = jnp.sum(lp_dim, axis=-1, keepdims=True)  # [B, 1]
                return std, t, a_env, g, lp

            la = alpha_o[0][...]  # (1, 1) resident log_alpha
            alpha = jnp.exp(la[0, 0])

            # ---- critic update: y = r + disc*(minQ' - alpha*logpi') ----
            meanN, log_stdN, _, _ = gauss_fwd(actor_o, nobs)
            _, _, aN, _, lpN = sample(meanN, log_stdN, eps_next_r[0])
            qt0, _ = critic_fwd(cm(t_critic_o, 0), nobs, aN)
            qt1, _ = critic_fwd(cm(t_critic_o, 1), nobs, aN)
            y = rew + disc * (jnp.minimum(qt0, qt1) - alpha * lpN)
            q0, acts0 = critic_fwd(cm(critic_o, 0), obs, action)
            q1_, acts1 = critic_fwd(cm(critic_o, 1), obs, action)
            td0 = y - q0
            td1 = y - q1_
            td = 0.5 * (td0 + td1)  # PER proxy: ensemble-mean TD
            # L = mean over [2, B] of w * td^2 -> dL/dq_m = -w * td_m / B.
            closs = (
                jnp.sum(wgt * td0 * td0) + jnp.sum(wgt * td1 * td1)
            ) * (0.5 * inv_b)
            c_grads0, _ = critic_bwd(
                cm(critic_o, 0), acts0, action, (-inv_b) * wgt * td0,
                wgrads=True,
            )
            c_grads1, _ = critic_bwd(
                cm(critic_o, 1), acts1, action, (-inv_b) * wgt * td1,
                wgrads=True,
            )

            # ---- actor update: L = E[alpha*logpi(a|s) - min_m Q_m(s,a)],
            # a = tanh(mean + std*eps)*scale + offset, pre-update critics.
            meanC, log_stdC, trC, a_acts = gauss_fwd(actor_o, obs)
            epsC = eps_cur_r[0]
            stdC, tC, aC, gC, lpC = sample(meanC, log_stdC, epsC)
            q_pi0, pia0 = critic_fwd(cm(critic_o, 0), obs, aC)
            q_pi1, pia1 = critic_fwd(cm(critic_o, 1), obs, aC)
            qmin = jnp.minimum(q_pi0, q_pi1)
            mean_lp = jnp.sum(lpC) * inv_b
            aloss = alpha * mean_lp - jnp.sum(qmin) * inv_b
            # Min gate with reduce_min's tie-splitting vjp (the scan path's
            # jnp.min over the member axis): equal rows split the cotangent.
            lt = (q_pi0 < q_pi1).astype(jnp.float32)
            gt = (q_pi0 > q_pi1).astype(jnp.float32)
            gate0 = lt + 0.5 * (1.0 - lt - gt)
            gate1 = 1.0 - gate0
            _, daA = critic_bwd(
                cm(critic_o, 0), pia0, aC, (-inv_b) * gate0, wgrads=False
            )
            _, daB = critic_bwd(
                cm(critic_o, 1), pia1, aC, (-inv_b) * gate1, wgrads=False
            )
            da = daA + daB
            # d(logpi)/du through the squash correction: lp's Gaussian term
            # is eps-only (see sample()), so only -log(g) carries u;
            # d(-log g)/du = 2*scale*t*(1-t^2)/g. The action path adds
            # da/du = scale*(1-t^2).
            dlp_row = alpha * inv_b  # dL/dlp per row (actor loss mean)
            one_m_t2 = 1.0 - tC * tC
            du = da * scale * one_m_t2 + dlp_row * (
                2.0 * scale * tC * one_m_t2 / gC
            )
            dmean = du  # du/dmean = 1
            # dlp/dlog_std (direct) = -1 per dim; du/dlog_std = std*eps.
            dlog_std = du * stdC * epsC - dlp_row
            # Soft clamp backward: log_std = m0 + hw*(tanh(raw)+1).
            draw = dlog_std * (hw * (1.0 - trC * trC))
            dzL = jnp.concatenate([dmean, draw], axis=-1)  # [B, 2A]
            a_grads = mlp_bwd(actor_o, a_acts, dzL)

            # ---- Adam (critic, actor), Polyak (both targets — SAC's math
            # has no target actor, but the slot trails for state parity
            # with the scan path), temperature Adam when autotuned.
            c_t = (count_ref[1] + k + 1).astype(jnp.float32)
            adam_only(nc2, cm(critic_o, 0), cm(cmu_o, 0), cm(cnu_o, 0),
                      c_grads0, lr_c, c_t)
            adam_only(nc2, cm(critic_o, 1), cm(cmu_o, 1), cm(cnu_o, 1),
                      c_grads1, lr_c, c_t)
            a_t = (count_ref[0] + k + 1).astype(jnp.float32)
            adam_only(na2, actor_o, amu_o, anu_o, a_grads, lr_a, a_t)
            polyak_only(nct, critic_o, t_critic_o)
            polyak_only(na2, actor_o, t_actor_o)
            if autotune:
                # J(log_alpha) = -log_alpha*(E[logpi]+H*): exact scalar
                # gradient, Adam at critic_lr (learner.sac_step).
                al_g = -(mean_lp + jnp.float32(sac_target_entropy))
                al_t = (count_ref[3] + k + 1).astype(jnp.float32)
                bc1 = 1.0 - jnp.exp(al_t * jnp.float32(_LOG_B1))
                bc2 = 1.0 - jnp.exp(al_t * jnp.float32(_LOG_B2))
                m_a = B1 * alpha_o[1][...] + (1.0 - B1) * al_g
                v_a = B2 * alpha_o[2][...] + (1.0 - B2) * (al_g * al_g)
                alpha_o[1][...] = m_a
                alpha_o[2][...] = v_a
                alpha_o[0][...] = la - lr_c * (m_a / bc1) / (
                    jnp.sqrt(v_a / bc2) + EPS
                )

            emit(
                td,
                [
                    closs,
                    aloss,
                    alpha * mean_lp - aloss,  # = E[minQ] (scan's mean_q)
                    jnp.sum(jnp.abs(td)) * inv_b,
                    jnp.sqrt(_sq(c_grads0) + _sq(c_grads1)),
                    jnp.sqrt(_sq(a_grads)),
                ],
            )
            return

        # Target path (no grads).
        u_t, _ = actor_fwd(t_actor_o, nobs)

        if twin:
            # ---- TD3 clipped double-Q (losses.td3_critic_loss) ----------
            if has_noise:
                # eps arrives pre-scaled AND pre-clipped (the wrapper draws
                # it from the same fold_in(seed, step) stream the scan path
                # uses, so the two paths are bit-comparable); only the
                # action-box clip happens here.
                na = jnp.clip(
                    u_t + eps_r[0], offset - scale, offset + scale
                )
            else:
                na = u_t
            qt0, _ = critic_fwd(cm(t_critic_o, 0), nobs, na)
            qt1, _ = critic_fwd(cm(t_critic_o, 1), nobs, na)
            y = rew + disc * jnp.minimum(qt0, qt1)
            q0, acts0 = critic_fwd(cm(critic_o, 0), obs, action)
            q1_, acts1 = critic_fwd(cm(critic_o, 1), obs, action)
            td0 = y - q0
            td1 = y - q1_
            # PER proxy: ensemble-mean TD (losses.td3_critic_loss).
            td = 0.5 * (td0 + td1)
            # L = mean over [2, B] of w * td^2 -> dL/dq_m = -w * td_m / B.
            closs = (
                jnp.sum(wgt * td0 * td0) + jnp.sum(wgt * td1 * td1)
            ) * (0.5 * inv_b)
            c_grads0, _ = critic_bwd(
                cm(critic_o, 0), acts0, action, (-inv_b) * wgt * td0,
                wgrads=True,
            )
            c_grads1, _ = critic_bwd(
                cm(critic_o, 1), acts1, action, (-inv_b) * wgt * td1,
                wgrads=True,
            )
            c_grads = c_grads0 + c_grads1  # aligned with the twin flatten
        else:
            q_t, _ = critic_fwd(t_critic_o, nobs, u_t)
            q, c_acts = critic_fwd(critic_o, obs, action)

        if not twin and distributional:
            # ---- C51 critic loss (losses.py:111-160 semantics) ----------
            # q / q_t are [B, A] logit heads. Stable softmax over atoms.
            z = z_ref[...]  # (1, A)
            m_t = jnp.max(q_t, axis=-1, keepdims=True)
            e_t = jnp.exp(q_t - m_t)
            p_t = e_t / jnp.sum(e_t, axis=-1, keepdims=True)
            # Projection of the Bellman-shifted target distribution onto
            # the support, accumulated atom-by-atom (unrolled, rank-2):
            # the triangular kernel relu(1 - |tz_i - z_j|/dz) IS the
            # lower/upper-neighbor mass split of the classic projection
            # (exact also when tz lands on an atom: weight 1 there, 0
            # elsewhere). proj is constant w.r.t. online params — the
            # target path carries no gradient, so forward-only is enough.
            tz = jnp.clip(rew + disc * z, v_min, v_max)  # [B, A]
            proj = jnp.zeros_like(q)
            for i in range(num_atoms):
                tri = jnp.maximum(
                    0.0, 1.0 - jnp.abs(tz[:, i : i + 1] - z) / dz_atom
                )
                proj = proj + p_t[:, i : i + 1] * tri
            m_q = jnp.max(q, axis=-1, keepdims=True)
            e_q = jnp.exp(q - m_q)
            sum_q = jnp.sum(e_q, axis=-1, keepdims=True)
            p_q = e_q / sum_q
            logp = q - (m_q + jnp.log(sum_q))
            ce = -jnp.sum(proj * logp, axis=-1, keepdims=True)  # [B, 1]
            closs = jnp.sum(wgt * ce) * inv_b
            # PER proxy (losses.py docstring): E[Z_target] - E[Z].
            mean_q_b = jnp.sum(p_q * z, axis=-1, keepdims=True)
            td = jnp.sum(proj * z, axis=-1, keepdims=True) - mean_q_b
            # d(mean(w * ce))/dlogits = w/B * (softmax(logits) - proj)
            dq = (p_q - proj) * (wgt * inv_b)
        elif not twin:
            # ---- TD(0) critic loss --------------------------------------
            y = rew + disc * q_t
            td = y - q
            closs = jnp.sum(wgt * td * td) * inv_b
            # L_c = mean(w * td^2); dL/dq = -2/B * w * td
            dq = (-2.0 * inv_b) * wgt * td

        if not twin:
            c_grads, _ = critic_bwd(critic_o, c_acts, action, dq, wgrads=True)

        # ---- actor forward + backward (through the pre-update critic) ----
        # TD3: through critic member 0 only (the convention); cm() is the
        # whole group when not twin.
        u, (a_acts, t_u) = actor_fwd(actor_o, obs)
        q_pi, pi_acts = critic_fwd(cm(critic_o, 0), obs, u)
        if distributional:
            # L_a = -mean(E[Z(s, mu(s))]), E[Z] = sum_j softmax(logits)_j z_j.
            # Softmax jacobian gives the closed-form cotangent:
            # dL/dlogits_j = -(1/B) * p_j * (z_j - E[Z]).
            m_pi = jnp.max(q_pi, axis=-1, keepdims=True)
            e_pi = jnp.exp(q_pi - m_pi)
            p_pi = e_pi / jnp.sum(e_pi, axis=-1, keepdims=True)
            q_exp = jnp.sum(p_pi * z, axis=-1, keepdims=True)  # [B, 1]
            dq_pi = (-inv_b) * p_pi * (z - q_exp)
            aloss = -jnp.sum(q_exp) * inv_b
        else:
            # dL_a/dq = -1/B
            dq_pi = jnp.full_like(q_pi, -inv_b)
            aloss = -jnp.sum(q_pi) * inv_b
        _, da = critic_bwd(cm(critic_o, 0), pi_acts, u, dq_pi, wgrads=False)

        def actor_bwd(group, acts, t_out, da_in):
            # Chain through the tanh*scale output, then the shared MLP bwd.
            return mlp_bwd(group, acts, da_in * scale * (1.0 - t_out * t_out))

        a_grads = actor_bwd(actor_o, a_acts, t_u, da)

        # ---- Adam + Polyak, all in VMEM ---------------------------------
        # count_ref = [actor_count0, critic_count0, step0 (, alpha_count0
        # for SAC autotune)]: each net's bias correction follows ITS OWN
        # carried Adam count (they only coincide when the TrainState has
        # always stepped both nets together); step0 drives the TD3
        # delayed-update schedule. (adam_only/polyak_only are defined above
        # the SAC branch, which returns early.)
        def apply(n2, p_o, t_o, mu_o, nu_o, grads, lr, count0):
            adam_only(
                n2, p_o, mu_o, nu_o, grads, lr,
                (count0 + k + 1).astype(jnp.float32),
            )
            polyak_only(n2, p_o, t_o)

        if twin:
            # Critic ensemble steps every grid step; actor + ALL target
            # nets step on the TD3 delay schedule (matches the scan path's
            # lax.cond at state.step % delay == 0, with state.step = step0
            # + k pre-increment). Actor Adam bias correction follows the
            # number of REAL actor updates: with f(n) = ceil(n / delay)
            # counting multiples of delay below n, updates inside the chunk
            # before grid step k number f(step0+k) - f(step0).
            c_t = (count_ref[1] + k + 1).astype(jnp.float32)
            adam_only(nc2, cm(critic_o, 0), cm(cmu_o, 0), cm(cnu_o, 0),
                      c_grads0, lr_c, c_t)
            adam_only(nc2, cm(critic_o, 1), cm(cmu_o, 1), cm(cnu_o, 1),
                      c_grads1, lr_c, c_t)
            step0 = count_ref[2]
            do_update = ((step0 + k) % policy_delay) == 0

            def f_updates(n):
                return (n + policy_delay - 1) // policy_delay

            a_t = (
                count_ref[0] + f_updates(step0 + k) - f_updates(step0) + 1
            ).astype(jnp.float32)

            @pl.when(do_update)
            def _delayed():
                adam_only(na2, actor_o, amu_o, anu_o, a_grads, lr_a, a_t)
                polyak_only(na2, actor_o, t_actor_o)
                polyak_only(nct, critic_o, t_critic_o)
        else:
            apply(nc2, critic_o, t_critic_o, cmu_o, cnu_o, c_grads, lr_c,
                  count_ref[1])
            apply(na2, actor_o, t_actor_o, amu_o, anu_o, a_grads, lr_a,
                  count_ref[0])

        # ---- outputs -----------------------------------------------------
        # Order must match learner.METRIC_KEYS; the wrapper sizes the metric
        # block from len(METRIC_KEYS) and emit() asserts this stack agrees.
        # The chunk MEAN is accumulated in-kernel into a (1, 6) output whose
        # block IS the whole array (constant index map) — a per-step (K, 6)
        # output would need a (1, 6) block over K rows, which violates
        # Mosaic's layout rule (second-to-last block dim must be divisible
        # by 8 or equal the array dim; the round-2 TPU bench died on exactly
        # that, VERDICT.md Weak #1). Grid steps run sequentially on TPU, so
        # read-modify-write accumulation over the revisited block is sound.
        a_norm = jnp.sqrt(_sq(a_grads))
        if twin and policy_delay > 1:
            # Scan-path cond reports actor_grad_norm = 0 on skipped steps.
            a_norm = jnp.where(
                ((count_ref[2] + k) % policy_delay) == 0, a_norm, 0.0
            )
        emit(
            td,
            [
                closs,
                aloss,
                -aloss,
                jnp.sum(jnp.abs(td)) * inv_b,
                jnp.sqrt(_sq(c_grads)),
                a_norm,
            ],
        )

    return kernel


def runs_native() -> bool:
    """True when the current backend compiles pallas TPU kernels natively;
    elsewhere the kernel runs in interpret mode (correct, far slower)."""
    return jax.default_backend() in ("tpu", "axon")


def td3_noise_base_key(config: DDPGConfig):
    """The TD3 smoothing-noise base key. MUST stay identical to
    learner.make_learner_step's td3_base_key — the kernel wrapper and the
    fused-mesh path pre-draw from this stream to stay bit-comparable with
    the scan path."""
    return jax.random.PRNGKey(config.seed ^ 0x7D3AF)


def td3_noise_eps(config: DDPGConfig, step0, chunk: int, batch: int,
                  act_dim: int, device_fold=None):
    """Pre-draw a chunk's target-smoothing noise [K, B, act], scaled and
    clipped, from fold_in(base, global_step) — the scan path's exact
    stream. `device_fold` (e.g. lax.axis_index under shard_map) folds a
    per-device term AFTER the step fold, matching the scan path's
    axis_name handling so sharded chunks draw iid noise per replica."""
    base = td3_noise_base_key(config)
    keys = jax.vmap(lambda s_: jax.random.fold_in(base, s_))(
        step0 + jnp.arange(chunk)
    )
    if device_fold is not None:
        keys = jax.vmap(lambda kk: jax.random.fold_in(kk, device_fold))(keys)
    return jax.vmap(
        lambda kk: jnp.clip(
            config.target_noise * jax.random.normal(kk, (batch, act_dim)),
            -config.target_noise_clip,
            config.target_noise_clip,
        )
    )(keys)


def sac_noise_base_key(config: DDPGConfig):
    """The SAC sampling-noise base key. MUST stay identical to
    learner.make_learner_step's sac_base_key for bit-comparability."""
    return jax.random.PRNGKey(config.seed ^ 0x5AC0)


def sac_noise_eps(config: DDPGConfig, step0, chunk: int, batch: int,
                  act_dim: int, device_fold=None):
    """Pre-draw a chunk's SAC standard normals: (eps_next, eps_cur), each
    [K, B, act], from the scan path's exact stream — key =
    fold_in(base, global_step) (then the device fold, mirroring the
    axis_name fold in learner.sac_step), split into the critic-target draw
    and the actor draw, `normal(key, (B, act))` each. Because
    u = mean + std*eps with eps independent of params, streaming the
    pre-drawn eps is exactly equivalent to sampling inside the step."""
    base = sac_noise_base_key(config)
    keys = jax.vmap(lambda s_: jax.random.fold_in(base, s_))(
        step0 + jnp.arange(chunk)
    )
    if device_fold is not None:
        keys = jax.vmap(lambda kk: jax.random.fold_in(kk, device_fold))(keys)

    def draw(kk):
        k_next, k_cur = jax.random.split(kk)
        return (
            jax.random.normal(k_next, (batch, act_dim)),
            jax.random.normal(k_cur, (batch, act_dim)),
        )

    return jax.vmap(draw)(keys)


def make_fused_chunk_fn(
    config: DDPGConfig,
    obs_dim: int,
    act_dim: int,
    action_scale,
    action_offset=0.0,
    chunk_size: int = 8,
    interpret: bool | None = None,
):
    """Returns jittable (state, batches[K, B, width]) ->
    (new_state, td[K, B], metrics{6 scalars}) running the whole chunk in one
    pallas launch. `batches` is the packed wire format (types.pack_batch_np
    layout); callers gather it from replay storage however they like."""
    if not supported(config):
        raise ValueError(
            "fused chunk kernel envelope: action_insert_layer=1, "
            "critic_l2=0, fused_update=False, >=2 critic hidden layers, "
            ">=1 actor hidden, num_atoms<=256 when distributional"
        )
    if not fits_vmem(config, obs_dim, act_dim):
        raise ValueError(
            f"fused chunk kernel: VMEM-resident state would be "
            f"{state_vmem_bytes(config, obs_dim, act_dim)} bytes "
            f"(budget {VMEM_STATE_BUDGET}); use the XLA scan path "
            f"(fused_chunk='off') for nets this large"
        )
    K = int(chunk_size)
    B = int(config.batch_size)
    o, a = int(obs_dim), int(act_dim)
    interp = (not runs_native()) if interpret is None else interpret
    scale = jnp.broadcast_to(
        jnp.asarray(action_scale, jnp.float32), (1, a)
    )
    offset = jnp.broadcast_to(
        jnp.asarray(action_offset, jnp.float32), (1, a)
    )
    z_row = (
        jnp.linspace(
            config.v_min, config.v_max, config.num_atoms, dtype=jnp.float32
        ).reshape(1, -1)
        if config.distributional
        else None
    )
    twin = bool(config.twin_critic)
    has_noise = twin and config.target_noise > 0.0
    sac = bool(config.sac)
    autotune = sac and bool(config.sac_autotune)
    if sac:
        from distributed_ddpg_tpu.ops.losses import sac_target_entropy

        tgt_h = sac_target_entropy(config.target_entropy, a, action_scale)
    else:
        tgt_h = None

    from distributed_ddpg_tpu.learner import METRIC_KEYS

    def run(state: TrainState, batches, eps=None):
        n_actor = len(state.actor_params)
        n_critic = len(state.critic_params)
        na2, nc2 = 2 * n_actor, 2 * n_critic

        obs = batches[..., :o]
        act = batches[..., o : o + a]
        rew = batches[..., o + a : o + a + 1]
        disc = batches[..., o + a + 1 : o + a + 2]
        nobs = batches[..., o + a + 2 : 2 * o + a + 2]
        wgt = batches[..., 2 * o + a + 2 : 2 * o + a + 3]

        flat_c = _flatten_twin if (twin or sac) else _flatten
        state_flat = (
            _flatten(state.actor_params)
            + flat_c(state.critic_params)
            + _flatten(state.target_actor_params)
            + flat_c(state.target_critic_params)
            + _flatten(state.actor_opt.mu)
            + _flatten(state.actor_opt.nu)
            + flat_c(state.critic_opt.mu)
            + flat_c(state.critic_opt.nu)
        )
        if sac:
            # Resident temperature: log_alpha (+ its Adam moments when
            # learned), as (1, 1) VMEM blocks like every other tensor.
            state_flat = state_flat + [state.log_alpha.reshape(1, 1)]
            if autotune:
                state_flat = state_flat + [
                    state.alpha_opt.mu.reshape(1, 1),
                    state.alpha_opt.nu.reshape(1, 1),
                ]

        if has_noise and eps is None:
            # Pre-draw the whole chunk's smoothing noise [K, B, act] from
            # the scan path's exact key stream (fold_in per global step),
            # pre-scaled and pre-clipped; it streams into the kernel like
            # the minibatches (~KB per step). Callers with a device axis
            # (fused-mesh) pass their own axis-folded eps instead.
            eps = td3_noise_eps(config, state.step, K, B, a)
        elif sac and eps is None:
            # SAC: (eps_next, eps_cur) standard-normal streams, same
            # fold_in discipline (sac_noise_eps docstring).
            eps = sac_noise_eps(config, state.step, K, B, a)
        elif not (has_noise or sac):
            eps = None

        def stream_spec(d):
            return pl.BlockSpec(
                (1, B, d), lambda k: (k, 0, 0), memory_space=pltpu.VMEM
            )

        def pinned_spec(arr):
            nd = len(arr.shape)
            return pl.BlockSpec(
                arr.shape, lambda k: (0,) * nd, memory_space=pltpu.VMEM
            )

        in_specs = (
            [pl.BlockSpec(memory_space=pltpu.SMEM)]
            + [stream_spec(o), stream_spec(a), stream_spec(1), stream_spec(1),
               stream_spec(o), stream_spec(1)]
            + [pinned_spec(scale), pinned_spec(offset)]
            + ([pinned_spec(z_row)] if z_row is not None else [])
            + (
                [stream_spec(a), stream_spec(a)]
                if sac
                else ([stream_spec(a)] if eps is not None else [])
            )
            + [pinned_spec(x) for x in state_flat]
        )
        out_specs = (
            [
                pl.BlockSpec(
                    (1, B, 1), lambda k: (k, 0, 0), memory_space=pltpu.VMEM
                ),
                # Chunk-mean metrics: the block is the whole (1, 6) array
                # (constant index map, accumulated across grid steps in the
                # kernel) — Mosaic-legal, unlike a (1, 6) block over (K, 6).
                pl.BlockSpec(
                    (1, len(METRIC_KEYS)), lambda k: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
            ]
            + [pinned_spec(x) for x in state_flat]
        )
        out_shape = (
            [
                jax.ShapeDtypeStruct((K, B, 1), jnp.float32),
                jax.ShapeDtypeStruct((1, len(METRIC_KEYS)), jnp.float32),
            ]
            + [jax.ShapeDtypeStruct(x.shape, x.dtype) for x in state_flat]
        )

        kernel = _make_kernel(
            n_actor, n_critic, B, K, config, sac_target_entropy=tgt_h
        )
        counts = [state.actor_opt.count, state.critic_opt.count, state.step]
        if autotune:
            counts.append(state.alpha_opt.count)
        count0 = jnp.stack(counts).astype(jnp.int32)
        support_args = (z_row,) if z_row is not None else ()
        if sac:
            eps_args = tuple(eps)  # (eps_next, eps_cur)
        else:
            eps_args = (eps,) if eps is not None else ()
        outs = pl.pallas_call(
            kernel,
            grid=(K,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interp,
        )(
            count0, obs, act, rew, disc, nobs, wgt, scale, offset,
            *support_args, *eps_args, *state_flat,
        )

        td = outs[0][..., 0]
        met = outs[1][0]
        flat = list(outs[2:])
        unflat_c = _unflatten_twin if (twin or sac) else _unflatten
        nct = nc2 * (2 if (twin or sac) else 1)
        i = 0
        actor_p = _unflatten(flat[i : i + na2], state.actor_params); i += na2
        critic_p = unflat_c(flat[i : i + nct], state.critic_params); i += nct
        t_actor = _unflatten(flat[i : i + na2], state.actor_params); i += na2
        t_critic = unflat_c(flat[i : i + nct], state.critic_params); i += nct
        amu = _unflatten(flat[i : i + na2], state.actor_params); i += na2
        anu = _unflatten(flat[i : i + na2], state.actor_params); i += na2
        cmu = unflat_c(flat[i : i + nct], state.critic_params); i += nct
        cnu = unflat_c(flat[i : i + nct], state.critic_params); i += nct
        new_log_alpha, new_alpha_opt = state.log_alpha, state.alpha_opt
        if sac:
            new_log_alpha = flat[i].reshape(()); i += 1
            if autotune:
                new_alpha_opt = OptState(
                    mu=flat[i].reshape(()),
                    nu=flat[i + 1].reshape(()),
                    count=state.alpha_opt.count + K,
                )
                i += 2

        if twin and config.policy_delay > 1:
            # Actor count advances only on real updates: multiples of
            # policy_delay in [step0, step0 + K).
            d = config.policy_delay
            f = lambda n: (n + d - 1) // d  # noqa: E731
            a_inc = f(state.step + K) - f(state.step)
        else:
            a_inc = K
        new_state = TrainState(
            actor_params=actor_p,
            critic_params=critic_p,
            target_actor_params=t_actor,
            target_critic_params=t_critic,
            actor_opt=OptState(
                mu=amu, nu=anu, count=state.actor_opt.count + a_inc
            ),
            critic_opt=OptState(mu=cmu, nu=cnu, count=state.critic_opt.count + K),
            step=state.step + K,
            log_alpha=new_log_alpha,
            alpha_opt=new_alpha_opt,
        )
        metrics = {k_: met[j] for j, k_ in enumerate(METRIC_KEYS)}
        return new_state, td, metrics

    return run
