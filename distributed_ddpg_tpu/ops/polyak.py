"""Polyak (soft) target-network update: target <- tau*online + (1-tau)*target.

In the reference this is a set of TF assign ops executed against
parameter-server variables every train step — a network round trip
(SURVEY.md §3.4). Here it is a pure pytree lerp fused into the jitted
learner step: zero boundary crossings.
"""

from __future__ import annotations

import jax


def polyak_update(online, target, tau):
    return jax.tree.map(lambda o, t: tau * o + (1.0 - tau) * t, online, target)
