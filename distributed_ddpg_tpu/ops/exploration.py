"""Shared vectorized exploration + env-step body for the two on-device
rollout loops — the fused monolith (`ondevice.py`) and the device-actor
pool (`actors/device_pool.py`).

Both backends advance E vmapped JAX envs per scan iteration with the same
semantics: per-env OU noise (or SAC's on-device tanh-Gaussian sampling),
a = clip(mu(s) + ou * scale, bounds), optional uniform-warmup override,
vmapped `env.step` with auto-reset, and the packed transition rows in
`types.pack_batch_np` column order with the bootstrap discount folding
TRUE termination (`gamma * (1 - terminated)`; time-limit truncation keeps
bootstrapping — the jax_envs.StepOut contract). Keeping the body in one
place means an exploration fix or a wire-format change cannot silently
diverge the two backends; only the params source and the warmup-gate
basis (replay-ring fill vs the pool's own step counter) differ, and both
ride in as arguments.

PRNG discipline: the caller's `key` ALWAYS splits 4 ways
(next, ou/sac-sample, env, uniform) in this order, whether or not the
SAC/warmup branches consume their splits — that is what lets a
host-stepped parity reference (tests/test_device_actors.py) replay the
exact stream, and it keeps existing seeds' streams stable across both
backends.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_ddpg_tpu.models.mlp import actor_apply


def vector_env_step(
    cfg,
    env,
    num_envs: int,
    params,
    env_state,
    obs,
    ou,
    key,
    scale,
    offset,
    low,
    high,
    warmup_active=None,
):
    """One vectorized exploration step over `num_envs` envs.

    `warmup_active`: None = no uniform-warmup override compiled in
    (static off); else a traced bool[] — where True, actions are drawn
    uniformly from the action box instead of the policy (each backend
    supplies its own gate basis).

    Returns `(next_key, new_ou, action, out, rows)` where `out` is the
    vmapped StepOut, `new_ou` is the OU state with done envs reset to the
    mean, and `rows` is the packed f32[num_envs, D] transition block."""
    E = num_envs
    next_key, k_ou, k_env, k_uni = jax.random.split(key, 4)
    if cfg.sac:
        # SAC explores by sampling its own tanh-Gaussian on device; the
        # OU state rides along untouched (zeros — worker.py parity).
        from distributed_ddpg_tpu.models.mlp import actor_gaussian_apply
        from distributed_ddpg_tpu.ops import losses as losses_lib

        mean, log_std = actor_gaussian_apply(
            params, obs, cfg.sac_log_std_min, cfg.sac_log_std_max
        )
        sampled, _ = losses_lib.sac_sample(
            mean, log_std, k_ou, scale, offset
        )
        action = jnp.clip(sampled, low, high)
        new_ou = ou
    else:
        new_ou = (
            ou
            + cfg.ou_theta * (0.0 - ou) * cfg.ou_dt
            + cfg.ou_sigma
            * jnp.sqrt(cfg.ou_dt)
            * jax.random.normal(k_ou, ou.shape, jnp.float32)
        )
        action = jnp.clip(
            actor_apply(params, obs, scale, offset) + new_ou * scale,
            low,
            high,
        )
    if warmup_active is not None:
        action = jnp.where(
            warmup_active,
            jax.random.uniform(
                k_uni, action.shape, jnp.float32, minval=low, maxval=high
            ),
            action,
        )
    out = jax.vmap(env.step)(env_state, action, jax.random.split(k_env, E))
    # Packed rows in types.pack_batch_np order; discount 0 where the env
    # truly terminated, truncation keeps bootstrapping.
    discount = cfg.gamma * (
        1.0 - jnp.broadcast_to(out.terminated, (E,)).astype(jnp.float32)
    )
    rows = jnp.concatenate(
        [
            obs,
            action,
            out.reward[:, None],
            discount[:, None],
            out.boot_obs,
            jnp.ones((E, 1), jnp.float32),
        ],
        axis=-1,
    )
    new_ou = jnp.where(out.done[:, None], 0.0, new_ou)
    return next_key, new_ou, action, out, rows
