"""Ornstein-Uhlenbeck exploration noise (SURVEY.md §2 #6).

Per-worker, CPU-side, reset per episode — identical role to the reference's
`ou_noise.py` [RECALL]. Vectorized over an arbitrary leading shape so one
process can drive a batched vector env. theta=0.15, sigma=0.2 defaults from
the DDPG paper (SURVEY.md §2 #8).

dx = theta * (mu - x) * dt + sigma * sqrt(dt) * N(0, 1)
"""

from __future__ import annotations

import numpy as np


class OUNoise:
    def __init__(
        self,
        shape,
        theta: float = 0.15,
        sigma: float = 0.2,
        mu: float = 0.0,
        dt: float = 1.0,
        seed: int = 0,
    ):
        self.shape = tuple(np.atleast_1d(shape))
        self.theta = theta
        self.sigma = sigma
        self.mu = mu
        self.dt = dt
        self._rng = np.random.default_rng(seed)
        self.state = np.full(self.shape, mu, dtype=np.float32)

    def reset(self, mask=None):
        """Reset to the mean. `mask` (bool, leading dims) resets only those
        rows — used when individual envs in a vector env terminate."""
        if mask is None:
            self.state[...] = self.mu
        else:
            self.state[np.asarray(mask)] = self.mu

    def __call__(self) -> np.ndarray:
        noise = self._rng.standard_normal(self.shape).astype(np.float32)
        self.state = (
            self.state
            + self.theta * (self.mu - self.state) * self.dt
            + self.sigma * np.sqrt(self.dt) * noise
        )
        return self.state
