"""Explicit Adam, tree-level.

Written out (rather than hidden behind an optimizer-library object) for three
reasons tied to this framework's contract:
1. the numpy `native` backend must produce bit-comparable updates
   (BASELINE.json:5) — same formulas, same order of operations;
2. the pallas fused Adam+Polyak kernel (ops/fused_update.py) needs the
   scalar math exposed;
3. the whole update lives inside the one jitted learner step — there is no
   optimizer.apply_gradients host round trip like the reference's
   parameter-server path (SURVEY.md §3.3).

Formulation matches optax.adam defaults (b1=0.9, b2=0.999, eps=1e-8,
eps_root=0): bias-corrected moments, eps added outside the sqrt.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_ddpg_tpu.types import OptState

B1 = 0.9
B2 = 0.999
EPS = 1e-8


def adam_update(params, grads, opt: OptState, lr):
    """One Adam step. Returns (new_params, new_opt)."""
    count = opt.count + 1
    c = count.astype(jnp.float32)
    bc1 = 1.0 - B1 ** c
    bc2 = 1.0 - B2 ** c
    mu = jax.tree.map(lambda m, g: B1 * m + (1.0 - B1) * g, opt.mu, grads)
    nu = jax.tree.map(lambda v, g: B2 * v + (1.0 - B2) * (g * g), opt.nu, grads)
    new_params = jax.tree.map(
        lambda p, m, v: p - lr * (m / bc1) / (jnp.sqrt(v / bc2) + EPS),
        params,
        mu,
        nu,
    )
    return new_params, OptState(mu=mu, nu=nu, count=count)
