from distributed_ddpg_tpu.ops.optim import adam_update
from distributed_ddpg_tpu.ops.polyak import polyak_update
from distributed_ddpg_tpu.ops import losses

__all__ = ["adam_update", "polyak_update", "losses"]
