"""Pallas TPU kernel: fused Adam + Polyak parameter update.

The optimizer update is HBM-bandwidth-bound: per leaf it reads params, both
Adam moments, grads, and the Polyak target, and writes four of them. Done as
separate ops that is 9 HBM round trips over the parameter footprint; fused
into one VPU pass it is 5 reads + 4 writes with every intermediate kept in
VMEM — and the Polyak lerp (SURVEY.md §3.4) rides along for free.

The whole param tree is raveled to one flat f32 vector (a no-op layout
change under XLA), padded to the f32 (8, 128) tile, processed by a single
grid of row blocks, and unraveled. Scalars that change per step (lr, the
two Adam bias corrections, tau) enter through SMEM.

`fused_adam_polyak` is numerically identical to ops.optim.adam_update +
ops.polyak.polyak_update (same formulas, same order); tests/test_fused.py
enforces equivalence (bit-exact on real TPU too). On non-TPU backends the
kernel runs in pallas interpret mode, so the feature degrades in speed,
never in availability.

When to enable: only for LARGE parameter trees. Measured on v5e-1 at the
default DDPG scale (2x256 MLPs, ~200KB params) the ravel/pad/unravel around
the kernel outweighs the HBM-round-trip savings — 17.3k steps/s fused vs
28.1k unfused at chunk=200 — which is why config.fused_update defaults to
False. The crossover favors the kernel once the parameter footprint is
MB-scale (where the 9->1 HBM pass reduction dominates).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_ddpg_tpu.ops.optim import B1, B2, EPS
from distributed_ddpg_tpu.types import OptState

_LANES = 128
_SUBLANES = 8
_BLOCK_ROWS = 256  # rows of 128 lanes per grid step (128KB/operand in VMEM)


def _kernel(scal_ref, p_ref, m_ref, v_ref, g_ref, t_ref,
            p_out, m_out, v_out, t_out):
    lr = scal_ref[0]
    bc1 = scal_ref[1]
    bc2 = scal_ref[2]
    tau = scal_ref[3]
    g = g_ref[:]
    m = B1 * m_ref[:] + (1.0 - B1) * g
    v = B2 * v_ref[:] + (1.0 - B2) * (g * g)
    p = p_ref[:] - lr * (m / bc1) / (jnp.sqrt(v / bc2) + EPS)
    p_out[:] = p
    m_out[:] = m
    v_out[:] = v
    t_out[:] = tau * p + (1.0 - tau) * t_ref[:]


def _should_interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_flat(flat_p, flat_m, flat_v, flat_g, flat_t, scalars, interpret=False):
    n = flat_p.shape[0]
    rows = -(-n // _LANES)
    rows_padded = -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS
    pad = rows_padded * _LANES - n

    def shape2d(x):
        return jnp.pad(x, (0, pad)).reshape(rows_padded, _LANES)

    grid = rows_padded // _BLOCK_ROWS
    block = pl.BlockSpec(
        (_BLOCK_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    scal_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    out_shape = jax.ShapeDtypeStruct((rows_padded, _LANES), jnp.float32)
    p2, m2, v2, t2 = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[scal_spec, block, block, block, block, block],
        out_specs=[block, block, block, block],
        out_shape=[out_shape] * 4,
        interpret=interpret,
    )(scalars, shape2d(flat_p), shape2d(flat_m), shape2d(flat_v),
      shape2d(flat_g), shape2d(flat_t))

    def unshape(x):
        return x.reshape(-1)[:n]

    return unshape(p2), unshape(m2), unshape(v2), unshape(t2)


def fused_adam_polyak(params, grads, opt: OptState, targets, lr, tau):
    """One fused step: (params, opt) <- Adam(params, grads, opt, lr);
    targets <- tau * new_params + (1 - tau) * targets.
    Returns (new_params, new_opt, new_targets)."""
    from jax.flatten_util import ravel_pytree

    flat_p, unravel = ravel_pytree(params)
    flat_m, _ = ravel_pytree(opt.mu)
    flat_v, _ = ravel_pytree(opt.nu)
    flat_g, _ = ravel_pytree(grads)
    flat_t, _ = ravel_pytree(targets)

    count = opt.count + 1
    c = count.astype(jnp.float32)
    scalars = jnp.stack(
        [
            jnp.asarray(lr, jnp.float32),
            1.0 - B1 ** c,
            1.0 - B2 ** c,
            jnp.asarray(tau, jnp.float32),
        ]
    )
    p, m, v, t = _fused_flat(
        flat_p, flat_m, flat_v, flat_g, flat_t, scalars,
        interpret=_should_interpret(),
    )
    return (
        unravel(p),
        OptState(mu=unravel(m), nu=unravel(v), count=count),
        unravel(t),
    )
