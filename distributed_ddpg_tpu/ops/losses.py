"""DDPG / D4PG losses (SURVEY.md §3.3; DDPG arXiv 1509.02971, D4PG arXiv 1804.08617).

- Critic: squared TD error against the bootstrapped target
  y = r + discount * Q'(s', mu'(s')), where `discount` already folds
  gamma^n * (1 - done) for n-step returns (types.Batch).
- Actor: deterministic policy gradient, implemented as the scalar loss
  -mean(Q(s, mu(s))) so `jax.grad` produces grad_theta mu(s) * grad_a Q.
- Distributional critic (D4PG): categorical projection of the target
  distribution onto a fixed support (C51-style), cross-entropy loss.

All functions are pure and shape-static so they trace once under jit.
PER importance weights enter as `batch.weight`; per-sample TD errors are
returned for host-side priority updates (SURVEY.md §2 #7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_ddpg_tpu.models.mlp import actor_apply, critic_apply
from distributed_ddpg_tpu.types import Batch


def td_targets(batch: Batch, next_q):
    return batch.reward + batch.discount * next_q


def critic_loss(
    critic_params,
    target_actor_params,
    target_critic_params,
    batch: Batch,
    action_scale,
    action_insert_layer: int = 1,
    l2: float = 0.0,
    action_offset=0.0,
    mm_dtype=None,
):
    """Weighted MSE TD loss. Returns (loss, td_errors[B])."""
    next_action = actor_apply(
        target_actor_params, batch.next_obs, action_scale, action_offset, mm_dtype
    )
    next_q = critic_apply(
        target_critic_params, batch.next_obs, next_action, action_insert_layer, mm_dtype
    )
    y = jax.lax.stop_gradient(td_targets(batch, next_q))
    q = critic_apply(critic_params, batch.obs, batch.action, action_insert_layer, mm_dtype)
    td = y - q
    loss = jnp.mean(batch.weight * jnp.square(td))
    if l2 > 0.0:
        loss = loss + l2 * sum(
            jnp.sum(jnp.square(layer["w"])) for layer in critic_params
        )
    return loss, td


def actor_loss(
    actor_params,
    critic_params,
    batch: Batch,
    action_scale,
    action_insert_layer: int = 1,
    action_offset=0.0,
    mm_dtype=None,
):
    """DPG loss: ascend Q(s, mu(s))."""
    action = actor_apply(actor_params, batch.obs, action_scale, action_offset, mm_dtype)
    q = critic_apply(critic_params, batch.obs, action, action_insert_layer, mm_dtype)
    return -jnp.mean(q)


# ---------------------------------------------------------------------------
# Twin critic (TD3, arXiv 1802.09477)
# ---------------------------------------------------------------------------


def td3_critic_loss(
    critic_params,
    target_actor_params,
    target_critic_params,
    batch: Batch,
    action_scale,
    noise_key,
    noise_std: float,
    noise_clip: float,
    action_insert_layer: int = 1,
    l2: float = 0.0,
    action_offset=0.0,
    mm_dtype=None,
):
    """Clipped double-Q TD loss: min-over-ensemble Bellman target with
    target-policy smoothing. `critic_params` leaves carry a leading
    ensemble axis of 2 (learner.init_train_state stacks them); the apply
    is vmapped over it — one batched program on the MXU, not two
    sequential critics. Loss is the MEAN of the two critics' weighted
    MSEs (lr-invariant vs the sum the paper writes), plus `l2` weight
    decay over both ensemble members (matching critic_loss). Returns
    (loss, td_proxy[B]) where the proxy is the ensemble-mean TD error
    (PER priorities)."""
    next_action = actor_apply(
        target_actor_params, batch.next_obs, action_scale, action_offset, mm_dtype
    )
    if noise_std > 0.0:
        eps = jnp.clip(
            noise_std * jax.random.normal(noise_key, next_action.shape),
            -noise_clip,
            noise_clip,
        )
        lo = action_offset - action_scale
        hi = action_offset + action_scale
        next_action = jnp.clip(next_action + eps, lo, hi)
    ensemble = lambda p, o, a: jax.vmap(
        lambda cp: critic_apply(cp, o, a, action_insert_layer, mm_dtype)
    )(p)
    next_q = ensemble(target_critic_params, batch.next_obs, next_action)  # [2, B]
    y = jax.lax.stop_gradient(td_targets(batch, jnp.min(next_q, axis=0)))
    q = ensemble(critic_params, batch.obs, batch.action)  # [2, B]
    td = y[None, :] - q
    loss = jnp.mean(batch.weight[None, :] * jnp.square(td))
    if l2 > 0.0:
        loss = loss + l2 * sum(
            jnp.sum(jnp.square(layer["w"])) for layer in critic_params
        )
    return loss, jnp.mean(td, axis=0)


def td3_actor_loss(
    actor_params,
    critic_params,
    batch: Batch,
    action_scale,
    action_insert_layer: int = 1,
    action_offset=0.0,
    mm_dtype=None,
):
    """DPG loss through critic 0 only (the TD3 convention)."""
    action = actor_apply(actor_params, batch.obs, action_scale, action_offset, mm_dtype)
    q1 = critic_apply(
        jax.tree.map(lambda x: x[0], critic_params),
        batch.obs, action, action_insert_layer, mm_dtype,
    )
    return -jnp.mean(q1)


# ---------------------------------------------------------------------------
# SAC (arXiv 1801.01290 / 1812.05905)
# ---------------------------------------------------------------------------

_TANH_EPS = 1e-6


def sac_sample(mean, log_std, key, action_scale, action_offset=0.0):
    """Reparameterized tanh-Gaussian sample mapped onto the action box.

    Returns (action[B, A], log_prob[B]). log_prob folds the standard tanh
    change-of-variables correction PLUS the box scaling's -log(scale) per
    dim (the policy density lives in environment action units, so the
    entropy target -act_dim means "one nat below a unit-box uniform per
    dim" regardless of the env's scale). Gradients flow through `mean` and
    `log_std` (reparameterization); callers stop-gradient where the
    pathwise term is unwanted."""
    std = jnp.exp(log_std)
    u = mean + std * jax.random.normal(key, mean.shape)
    tanh_u = jnp.tanh(u)
    action = tanh_u * action_scale + action_offset
    # N(u; mean, std) log-density, summed over action dims.
    gauss_lp = -0.5 * (
        jnp.square((u - mean) / std) + 2.0 * log_std + jnp.log(2.0 * jnp.pi)
    )
    # d(action)/d(u) = scale * (1 - tanh(u)^2); log|det| subtracts.
    squash = jnp.log(action_scale * (1.0 - jnp.square(tanh_u)) + _TANH_EPS)
    log_prob = jnp.sum(gauss_lp - squash, axis=-1)
    return action, log_prob


def sac_critic_loss(
    critic_params,
    actor_params,
    target_critic_params,
    batch: Batch,
    action_scale,
    key,
    alpha,
    log_std_min: float,
    log_std_max: float,
    action_insert_layer: int = 1,
    l2: float = 0.0,
    action_offset=0.0,
    mm_dtype=None,
):
    """Entropy-regularized clipped double-Q TD loss:
    y = r + discount * (min_i Q'_i(s', a') - alpha * log pi(a'|s')),
    a' ~ pi(.|s') drawn from the CURRENT actor (SAC has no target actor).
    `critic_params` leaves carry the same leading ensemble axis of 2 as
    TD3's (learner.init_train_state). Returns (loss, td_proxy[B]) with the
    ensemble-mean TD error as the PER priority proxy."""
    from distributed_ddpg_tpu.models.mlp import actor_gaussian_apply

    mean, log_std = actor_gaussian_apply(
        actor_params, batch.next_obs, log_std_min, log_std_max, mm_dtype
    )
    next_action, next_lp = sac_sample(mean, log_std, key, action_scale, action_offset)
    ensemble = lambda p, o, a: jax.vmap(
        lambda cp: critic_apply(cp, o, a, action_insert_layer, mm_dtype)
    )(p)
    next_q = jnp.min(
        ensemble(target_critic_params, batch.next_obs, next_action), axis=0
    )
    y = jax.lax.stop_gradient(td_targets(batch, next_q - alpha * next_lp))
    q = ensemble(critic_params, batch.obs, batch.action)  # [2, B]
    td = y[None, :] - q
    loss = jnp.mean(batch.weight[None, :] * jnp.square(td))
    if l2 > 0.0:
        # Weight decay over both ensemble members (matching td3_critic_loss).
        loss = loss + l2 * sum(
            jnp.sum(jnp.square(layer["w"])) for layer in critic_params
        )
    return loss, jnp.mean(td, axis=0)


def sac_actor_loss(
    actor_params,
    critic_params,
    batch: Batch,
    action_scale,
    key,
    alpha,
    log_std_min: float,
    log_std_max: float,
    action_insert_layer: int = 1,
    action_offset=0.0,
    mm_dtype=None,
):
    """Reparameterized actor objective E[alpha * log pi(a|s) - min_i Q_i(s, a)].

    Unlike TD3 (critic 0 only), SAC minimizes against the ensemble MIN —
    the 1812.05905 convention. Returns (loss, mean_log_prob) — the aux
    feeds the alpha (temperature) update."""
    from distributed_ddpg_tpu.models.mlp import actor_gaussian_apply

    mean, log_std = actor_gaussian_apply(
        actor_params, batch.obs, log_std_min, log_std_max, mm_dtype
    )
    action, lp = sac_sample(mean, log_std, key, action_scale, action_offset)
    q = jnp.min(
        jax.vmap(
            lambda cp: critic_apply(cp, batch.obs, action, action_insert_layer, mm_dtype)
        )(critic_params),
        axis=0,
    )
    return jnp.mean(alpha * lp - q), jnp.mean(lp)


def sac_target_entropy(target_entropy: float, act_dim: int, action_scale):
    """Resolve the temperature target as a trace-time Python float (jnp
    here would yield a tracer under jit): an explicit `target_entropy`
    wins; nan (the config sentinel) means auto — the 1812.05905 -act_dim
    heuristic, which is stated for UNIT-box log-probs, shifted by
    +sum(log scale) because sac_sample's densities live in env action
    units (without the shift any env with scale > 1 gets a LOWER-entropy
    target than standard SAC and alpha collapses — measured on Pendulum,
    scale 2: alpha -> 0.017 and stuck). Shared by learner.sac_step and
    the fused kernel wrapper so the two paths cannot desync."""
    import math

    import numpy as np

    if not math.isnan(target_entropy):
        return float(target_entropy)
    return -float(act_dim) + float(
        np.sum(
            np.log(
                np.broadcast_to(
                    np.asarray(action_scale, np.float64), (act_dim,)
                )
            )
        )
    )


# ---------------------------------------------------------------------------
# Distributional critic (D4PG)
# ---------------------------------------------------------------------------


def categorical_support(v_min: float, v_max: float, num_atoms: int):
    return jnp.linspace(v_min, v_max, num_atoms)


def categorical_projection(support, target_probs, rewards, discounts):
    """Project the shifted/scaled target distribution back onto `support`.

    support: f32[A]; target_probs: f32[B, A]; rewards, discounts: f32[B].
    Returns f32[B, A]. Standard C51 projection (vectorized, no Python loops —
    traces to gathers/scatters XLA handles natively).
    """
    v_min, v_max = support[0], support[-1]
    num_atoms = support.shape[0]
    dz = (v_max - v_min) / (num_atoms - 1)
    # Bellman-updated atom positions, clipped to the support: f32[B, A]
    tz = jnp.clip(
        rewards[:, None] + discounts[:, None] * support[None, :], v_min, v_max
    )
    b = (tz - v_min) / dz                 # fractional index in [0, A-1]
    lower = jnp.floor(b)
    upper = jnp.ceil(b)
    # When b lands exactly on an atom, put all mass on it (lower == upper).
    eq = (upper == lower).astype(target_probs.dtype)
    w_lower = (upper - b) + eq            # mass to the lower atom
    w_upper = b - lower
    lo = lower.astype(jnp.int32)
    up = upper.astype(jnp.int32)
    onehot = jnp.eye(num_atoms, dtype=target_probs.dtype)
    proj = jnp.einsum("ba,ba,baj->bj", target_probs, w_lower, onehot[lo])
    proj = proj + jnp.einsum("ba,ba,baj->bj", target_probs, w_upper, onehot[up])
    return proj


def distributional_critic_loss(
    critic_params,
    target_actor_params,
    target_critic_params,
    batch: Batch,
    action_scale,
    support,
    action_insert_layer: int = 1,
    action_offset=0.0,
    mm_dtype=None,
):
    """Categorical TD loss (cross-entropy vs projected target distribution).

    Returns (loss, td_error_proxy[B]) where the proxy is |E[Z] - E[Z_target]|
    (used for PER priorities, as in D4PG follow-ups)."""
    next_action = actor_apply(
        target_actor_params, batch.next_obs, action_scale, action_offset, mm_dtype
    )
    target_logits = critic_apply(
        target_critic_params, batch.next_obs, next_action, action_insert_layer, mm_dtype
    )
    target_probs = jax.nn.softmax(target_logits, axis=-1)
    proj = jax.lax.stop_gradient(
        categorical_projection(support, target_probs, batch.reward, batch.discount)
    )
    logits = critic_apply(
        critic_params, batch.obs, batch.action, action_insert_layer, mm_dtype
    )
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.sum(proj * logprobs, axis=-1)
    loss = jnp.mean(batch.weight * ce)
    mean_q = jnp.sum(jax.nn.softmax(logits, axis=-1) * support[None, :], axis=-1)
    mean_target = jnp.sum(proj * support[None, :], axis=-1)
    return loss, mean_target - mean_q


def distributional_actor_loss(
    actor_params,
    critic_params,
    batch: Batch,
    action_scale,
    support,
    action_insert_layer: int = 1,
    action_offset=0.0,
    mm_dtype=None,
):
    action = actor_apply(actor_params, batch.obs, action_scale, action_offset, mm_dtype)
    logits = critic_apply(critic_params, batch.obs, action, action_insert_layer, mm_dtype)
    q = jnp.sum(jax.nn.softmax(logits, axis=-1) * support[None, :], axis=-1)
    return -jnp.mean(q)
