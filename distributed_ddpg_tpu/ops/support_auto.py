"""Auto-sizing for the C51/D4PG categorical support (VERDICT r4 Weak #4).

The distributional critic's value support [v_min, v_max] was a hand knob per
env: ±150 saturates HalfCheetah (Q grows past 600), LunarLander needed ±400,
Pendulum [-1600, 0] (docs/EVIDENCE.md §3, docs/OPERATIONS.md). Every new env
needed an operator who knew this. `--v_min=auto --v_max=auto` replaces the
knob with two rules:

1. **Initial sizing** (`initial_bounds`): once the replay holds warmup data,
   bound the discounted return from observed reward statistics. For a reward
   stream r with per-step discount γ (n-step: stored rewards are n-step sums
   with effective discount γ^n), a persistent reward r yields return
   r / (1 - γ^n); a one-off reward contributes at most r. Robust percentiles
   guard against single outliers, the raw extremes guard against sparse
   terminal rewards (LunarLander's ±100 land/crash), and a margin leaves
   headroom so the edge atoms aren't immediately saturated. This reproduces
   the hand-tuned Pendulum support ([-1600, 0]: r ∈ [-16.3, 0] dense) from
   data alone.

2. **Running expansion** (`maybe_expand`): warmup statistics cannot see a
   trained policy's returns (HalfCheetah random-policy rewards suggest ~±200;
   trained Q reaches 600+, which is exactly how the ±150 default saturated).
   The learner's mean_q metric rides the existing chunk-metrics sync; when it
   approaches an edge of the current support the support is re-derived with
   that edge pushed out. Expansions are EDGE-TRIGGERED and — when the caller
   supplies `data_bounds_fn` — **DATA-CORROBORATED**: the rule-1 bound over
   the replay's CURRENT rewards must exceed the current edge for the
   expansion to happen at all (else REFUSED), and the new edge is then the
   LARGER of that data bound and the geometric step — the data gates, the
   geometry sizes (the percentile bound lags achievable return; capping at
   it measurably throttled a healthy run — see the in-function comment).
   mean_q is a prediction and can diverge; rewards cannot. Observed failure
   (round 5, HalfCheetah seed 1, pre-guard): the critic diverged to
   mean_q ≈ +2400 while actual episode returns sat near -400, and the
   mean_q-only rule chased the fantasy from [-96, 639] to [-118, 5907] —
   each expansion granting the divergence more room. With the guard the
   trigger fires, the replay rewards say the data supports no more than the
   warmup-scale bound, and the expansion is refused (counted in
   `SupportController.refusals` for the metrics stream). Without
   `data_bounds_fn` the legacy geometric growth is kept (unit isolation).
   Each applied expansion costs one XLA recompile of the chunk program,
   which amortizes to nothing (seconds against minutes-long rungs).

Semantics under expansion: the critic's logits keep their per-atom meaning
while the atom VALUES stretch, so predicted Q momentarily stretches with
them and the critic relearns the mapping over the next few thousand steps.
Expansion-only (never shrink) keeps this transient one-directional and rare.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# Edge-proximity threshold, in units of |mean_q| (NOT support width): the
# high edge counts as "approached" when (v_max - mean_q) < PROXIMITY *
# max(|mean_q|, MIN_HALF_WIDTH), and symmetrically for the low edge. Scaling
# by mean_q instead of the span makes the trigger immune to an oversized
# support: with a width-relative rule, a support accidentally sized
# [-3731, 639] saw the PERFECTLY HEALTHY mean_q of -11.7 as "inside the top
# 30% of the span" and expanded v_max to 5010 (measured, round-5 LunarLander
# v1 run) — growing exactly the resolution problem it was meant to solve.
# The MIN_HALF_WIDTH floor keeps a near-zero edge (Pendulum's v_max ~ 0)
# expandable: mean_q -> 0 from below still closes within the floor. It
# fires BEFORE projection clipping fully saturates the edge atoms (mean_q
# can never exceed v_max, so waiting for equality would be waiting forever).
PROXIMITY = 0.3
# On expansion the approached edge moves to center ± GROWTH * half-range:
# geometric growth => O(log) recompiles over any true range.
GROWTH = 3.0
# Learner steps to HOLD after an expansion before re-checking. The stretch
# is affine and the logits are unchanged, so the reinterpreted mean_q lands
# near the NEW edge again (stretched by the same factor as the support) and
# an immediate re-check would re-fire regardless of need, cascading the
# support toward infinity at one recompile per check. Only SGD moves
# mean_q off the edge — TD targets pull the stretched predictions back
# toward the true (unstretched) Q over O(hundreds) of steps — so the
# controller must wait out that relearn horizon. Callers enforce this via
# the steps_since_expansion argument below.
COOLDOWN_STEPS = 2000
# Headroom multiplier on the initial warmup-derived range.
MARGIN = 1.2
# Corroboration strictness: the data bound must exceed the current edge
# by at least this fraction of the span for an expansion to pass the
# gate. This does NOT size the expansion (a corroborated trigger always
# gets at least the geometric step); it sets how far past the edge the
# replay rewards must reach before growth is believed — tightening it
# strengthens the diverged-critic guard, loosening it expands earlier on
# percentile jitter.
MIN_GROWTH = 0.1
# Floor on the support width: degenerate all-equal-reward warmups (e.g.
# zero-reward gridworlds) must still produce a usable support.
MIN_HALF_WIDTH = 1.0


def initial_bounds(
    rewards: np.ndarray,
    gamma: float,
    n_step: int = 1,
    discounts: Optional[np.ndarray] = None,
) -> Tuple[float, float]:
    """Derive [v_min, v_max] from observed (n-step) rewards.

    rewards: the replay's stored reward column — n-step accumulated sums
    when n_step > 1, matching what the Bellman target actually adds.
    discounts: the matching stored discount column, when available. A
    terminal transition (discount == 0) carries a ONE-OFF reward by
    definition — nothing bootstraps through it — so it must not enter the
    persistent-reward bound r/(1-gamma^n): LunarLander's random-policy
    warmup crashes (-100 terminal) are frequent enough to land inside the
    1st percentile, and multiplying them by the ~34-step horizon sized the
    support to [-3731, 639] where the hand value was ±400 (measured,
    round 5). With the terminal mask they only enter via the raw-extreme
    term (a -100 crash must still be inside the support, as ±100 itself).
    """
    r = np.asarray(rewards, np.float64)
    finite = np.isfinite(r)
    if discounts is not None:
        d = np.asarray(discounts, np.float64)
        nonterm = r[finite & (d > 0.0)]
    else:
        nonterm = r[finite]
    r = r[finite]
    if r.size == 0:
        raise ValueError("initial_bounds needs at least one finite reward")
    # Effective per-transition discount: stored n-step rewards bootstrap
    # through gamma^n, so the persistent-reward return bound is r/(1-gamma^n).
    g_eff = float(gamma) ** int(n_step)
    horizon = 1.0 / max(1.0 - g_eff, 1e-6)
    if nonterm.size == 0:
        # All-terminal warmup (bandit-style env): NOTHING bootstraps, true
        # returns ARE the raw rewards — the horizon term would oversize the
        # support ~100x and park the whole value function inside one atom.
        r_lo = r_hi = 0.0
    else:
        r_lo, r_hi = np.percentile(nonterm, [1.0, 99.0])
    # Each side: the persistent-reward bound from the robust percentile OR
    # the raw extreme (sparse terminal rewards are outliers the percentile
    # clips away, but a single +100 landing bonus must still be inside the
    # support). Zero stays inside: returns cross zero whenever rewards do,
    # and an all-negative stream (Pendulum) still has v_max ~ 0 ceilings.
    lo = min(r_lo * horizon if r_lo < 0 else 0.0, float(r.min()), 0.0)
    hi = max(r_hi * horizon if r_hi > 0 else 0.0, float(r.max()), 0.0)
    center = 0.5 * (lo + hi)
    half = max(0.5 * (hi - lo) * MARGIN, MIN_HALF_WIDTH)
    return center - half, center + half


def replay_data_bounds(replay, gamma: float, n_step: int):
    """The rule-1 bound over a replay's CURRENT reward column — the one
    derivation every call site must share (initial sizing in agent.py and
    train.py, and both expansion-corroboration closures): a drift between
    sites would make the two training paths corroborate against different
    statistics."""
    rewards, discounts = replay.reward_sample()
    return initial_bounds(rewards, gamma, n_step, discounts=discounts)


def _edge_triggered(v_min: float, v_max: float, mean_q: float) -> bool:
    """THE proximity predicate — shared by maybe_expand (the gate) and
    SupportController (refusal classification), so the refusals metric can
    never drift from what the gate actually refuses."""
    if not np.isfinite(mean_q):
        return False
    near = PROXIMITY * max(abs(mean_q), MIN_HALF_WIDTH)
    return v_max - mean_q < near or mean_q - v_min < near


def maybe_expand(
    v_min: float,
    v_max: float,
    mean_q: float,
    steps_since_expansion: Optional[int] = None,
    data_bounds_fn=None,
) -> Optional[Tuple[float, float]]:
    """Edge-triggered expansion. Returns new (v_min, v_max) when mean_q has
    closed to within PROXIMITY * max(|mean_q|, MIN_HALF_WIDTH) of either
    edge AND (when data_bounds_fn is given) the current replay data
    corroborates growth on that edge, else None (no change — the caller
    skips the recompile).

    data_bounds_fn: zero-arg callable returning `initial_bounds` over the
    replay's CURRENT reward column (called lazily, only after the proximity
    trigger fires — the column pull is ~100k rows). The data bound GATES:
    a trigger whose data bound does not meaningfully exceed the current
    edge is a diverging critic, not a grown return scale, and is refused
    (module docstring, seed-1 incident). A corroborated trigger grows to
    the LARGER of the data bound and the geometric step — the data is a
    lagging estimator and capping at it measurably throttles healthy runs
    (in-function comment). When None, the legacy uncorroborated geometric
    growth is used.

    steps_since_expansion: learner steps since the caller last applied an
    expansion (None = never). Checks inside COOLDOWN_STEPS are refused —
    see the COOLDOWN_STEPS note: the affine stretch re-places the
    reinterpreted mean_q near the new edge, so without the hold the check
    right after an expansion would re-fire and cascade."""
    if (
        steps_since_expansion is not None
        and steps_since_expansion < COOLDOWN_STEPS
    ):
        return None
    if not _edge_triggered(v_min, v_max, mean_q):
        return None
    center = 0.5 * (v_min + v_max)
    half = 0.5 * (v_max - v_min)
    near = PROXIMITY * max(abs(mean_q), MIN_HALF_WIDTH)
    hi_edge = v_max - mean_q < near
    lo_edge = mean_q - v_min < near
    if data_bounds_fn is None:
        if hi_edge:
            return v_min, center + GROWTH * half
        return center - GROWTH * half, v_max
    lo_d, hi_d = data_bounds_fn()
    min_step = MIN_GROWTH * (v_max - v_min)
    # The data bound GATES the expansion but does not CAP the new edge:
    # the percentile-derived bound is a lagging estimator of achievable
    # return (measured round 5, HalfCheetah seed 0 — capping the edge at
    # the data bound throttled healthy growth to 3672 where the
    # uncorroborated rule reached 5075), so a corroborated trigger gets
    # the full geometric headroom. Runaway stays bounded: the NEXT
    # expansion needs the data to corroborate again above the grown
    # edge, so a diverged critic buys at most one geometric step beyond
    # what the rewards ever supported (vs unbounded chasing pre-guard).
    if hi_edge and hi_d > v_max + min_step:
        return v_min, float(max(hi_d, center + GROWTH * half))
    if lo_edge and lo_d < v_min - min_step:
        return float(min(lo_d, center - GROWTH * half)), v_max
    return None  # trigger fired but the data does not corroborate: refuse


class SupportController:
    """Owns the one piece of expansion state — the learner step of the last
    applied expansion — so the cooldown bookkeeping lives in ONE place
    instead of being copied into every training loop (DDPGAgent.train_step
    and train.py's after_chunk are the two call sites)."""

    def __init__(self):
        self._last_expand_step: Optional[int] = None
        self._last_refusal_step: Optional[int] = None
        # Proximity triggers refused by the data-corroboration gate — a
        # nonzero, growing count in the metrics stream is the diverging-
        # critic signature (mean_q pinned at an edge the data won't let
        # grow), worth an operator's attention even though the support
        # is, correctly, not chasing it.
        self.refusals: int = 0

    def check(
        self,
        v_min: float,
        v_max: float,
        mean_q: float,
        step: int,
        data_bounds_fn=None,
    ) -> Optional[Tuple[float, float]]:
        """maybe_expand with the cooldown applied; records the step when an
        expansion fires. Returns the new bounds or None.

        Refusals are ALSO cooled down: a pinned diverged mean_q would
        otherwise re-fire the trigger on every check and re-pay the
        ~100k-row reward-column pull each time, for the rest of the run —
        the replay contents cannot change faster than COOLDOWN_STEPS
        anyway."""
        since_expand = (
            None
            if self._last_expand_step is None
            else step - self._last_expand_step
        )
        since_refusal = (
            None
            if self._last_refusal_step is None
            else step - self._last_refusal_step
        )
        if since_refusal is not None and since_refusal < COOLDOWN_STEPS:
            return None
        grown = maybe_expand(
            v_min, v_max, mean_q,
            steps_since_expansion=since_expand,
            data_bounds_fn=data_bounds_fn,
        )
        if grown is not None:
            self._last_expand_step = step
            return grown
        if (
            data_bounds_fn is not None
            and (since_expand is None or since_expand >= COOLDOWN_STEPS)
            and _edge_triggered(v_min, v_max, mean_q)
        ):
            self.refusals += 1
            self._last_refusal_step = step
        return None
