"""Multi-host (DCN) initialization (SURVEY.md §7 step 6; BASELINE.json:11-12,
the v5e-16 'cross-host AllReduce' rung).

The reference's cross-host story is distributed TF's gRPC parameter server
(SURVEY.md §2 #10). Here it is `jax.distributed.initialize`: after it runs,
`jax.devices()` spans all hosts, the SAME (data, model) mesh and learner jit
from parallel/ cover the pod, and XLA lowers the gradient AllReduce
hierarchically (ICI within a host, DCN across hosts). No framework code
changes between 1 host and N hosts — only this bootstrap.

Each host runs its own actors and replay shard and feeds its local devices.
Feeding works unchanged across processes: `jax.device_put` with a global
NamedSharding places each process's addressable shards (every process must
call it with the same global array — true here since learner inputs are
deterministic given the replay contents), and
`jax.make_array_from_process_local_data` remains the explicit per-host
alternative. Both paths (and full cross-process learner parity) are
exercised by tests/test_multihost.py over a 2-process Gloo CPU cluster.
"""

from __future__ import annotations

import os
from typing import Optional


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Idempotent jax.distributed bootstrap. Args fall back to the standard
    env vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID,
    or cloud-TPU auto-detection when none are set). Returns True if a
    multi-process runtime was initialized, False for single-process runs."""
    import jax

    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])

    if coordinator_address is None and num_processes is None:
        # Single process (or cloud-TPU metadata auto-detect, which
        # jax.distributed.initialize() handles with no args — only attempt it
        # when a TPU runtime is actually present).
        return False

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return jax.process_count() > 1
    except RuntimeError as e:
        msg = str(e)
        if "already initialized" in msg:
            return jax.process_count() > 1
        if "must be called before" in msg and jax.process_count() > 1:
            # Backend already live AND already multi-process: a legitimate
            # idempotent re-entry (the application bootstrapped distributed
            # before calling train). If the live backend is single-process,
            # the explicit multi-host request genuinely failed — re-raise
            # rather than silently training N independent copies.
            return True
        raise


def allgather_scalar(value, dtype=None):
    """All-gather one host scalar across processes; returns a numpy array
    of shape [process_count]. The ONE host-initiated DCN collective the
    ingest/budget machinery needs (replay/device.py sync_ship beats,
    train.py's global env-step budget). Centralized here so every caller
    — including the transfer scheduler's lockstep lane, which must be the
    only thread issuing host-initiated collectives when background
    sync_ship is active (docs/TRANSFER.md) — goes through one audited
    entry point."""
    import numpy as np
    from jax.experimental import multihost_utils

    arr = np.asarray(value, dtype) if dtype is not None else np.asarray(value)
    return np.asarray(multihost_utils.process_allgather(arr))


def process_info() -> dict:
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
