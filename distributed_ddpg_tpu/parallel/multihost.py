"""Multi-host (DCN) initialization + pod-resilience layer (SURVEY.md §7
step 6; BASELINE.json:11-12, the v5e-16 'cross-host AllReduce' rung;
docs/RESILIENCE.md pod rows).

The reference's cross-host story is distributed TF's gRPC parameter server
(SURVEY.md §2 #10). Here it is `jax.distributed.initialize`: after it runs,
`jax.devices()` spans all hosts, the SAME (data, model) mesh and learner jit
from parallel/ cover the pod, and XLA lowers the gradient AllReduce
hierarchically (ICI within a host, DCN across hosts). No framework code
changes between 1 host and N hosts — only this bootstrap.

Each host runs its own actors and replay shard and feeds its local devices.
Feeding works unchanged across processes: `jax.device_put` with a global
NamedSharding places each process's addressable shards (every process must
call it with the same global array — true here since learner inputs are
deterministic given the replay contents), and
`jax.make_array_from_process_local_data` remains the explicit per-host
alternative. Both paths (and full cross-process learner parity) are
exercised by tests/test_multihost.py over a 2-process Gloo CPU cluster.

Pod resilience (the PR-6 layer; docs/RESILIENCE.md):

Podracer-style deployments (PAPERS.md arXiv 2104.06272) run on preemptible
pods where single-process death is the COMMON failure — and a gloo/DCN
collective whose peer died blocks the survivors forever with no error.
This module therefore owns three defenses, all centralized at the single
audited entry point every host-initiated collective already goes through:

  1. **Collective deadlines.** `call_with_deadline` bounds any guarded
     collective by `pod_collective_timeout_s` (configure_pod; the transfer
     scheduler's lockstep lane wraps its beats through the same function).
     A hung collective surfaces as a typed `PodPeerLost` instead of an
     eternal block; single-process runs (deadline unconfigured) pay zero
     overhead — the wrapper short-circuits to a direct call. `grant()`
     extends the deadline across known-long windows (first-chunk XLA
     compile), mirroring the stall watchdog's grant.
  2. **Peer liveness.** `beat_allgather` piggybacks a heartbeat word (a
     per-process beat sequence number) on the existing sync_ship beat
     payload, so every successful beat refreshes a last-known-alive
     vector. When a collective dies, the PodPeerLost message carries that
     vector plus the peer id parsed (best-effort) from the transport
     error — survivors learn which process died within a bounded number
     of beats.
  3. **Coordinated resume.** `elect_resume_step` all-gathers each
     process's manifest-valid checkpoint steps and returns the greatest
     step present on EVERY process, so a pod restarting after a clean
     abort (train.py EXIT_POD_DEGRADED) never resumes forked.

`startup_barrier` is the one-time rendezvous with its own generous grace
(pod_startup_grace_s), distinct from the steady-state deadline: process
startup skew under box load (backend init, imports) must not eat into —
or false-fire — the much tighter collective deadline.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Iterable, Optional


class PodPeerLost(RuntimeError):
    """A pod-level host-initiated collective missed its deadline or failed
    mid-flight: some peer process is gone (crashed, preempted, or hung).
    Survivors must take the coordinated clean abort (train.py: drain the
    transfer scheduler, one emergency checkpoint, exit EXIT_POD_DEGRADED)
    — any further collective would block or fork the pod.

    `peer` is the lost process id when the transport error named one
    (best-effort; None for a silent timeout). `reason` is "timeout" or
    "error"."""

    def __init__(self, message: str, peer: Optional[int] = None,
                 reason: str = "timeout"):
        super().__init__(message)
        self.peer = peer
        self.reason = reason


# --- module pod state (configured once per train run by train_jax) --------
_pod_lock = threading.Lock()
_tls = threading.local()  # re-entrancy: nested guards must not double-arm
_pod_deadline_s = 0.0        # 0 = deadlines off (single-process default)
_pod_stats = None            # metrics.PodStats, when train.py wires one
_pod_grace_until = 0.0       # monotonic deadline extension (grant())
_beat_seq = 0                # this process's heartbeat word
_last_heartbeats = None      # last gathered per-process heartbeat vector


def configure_pod(timeout_s: float, stats=None) -> None:
    """Arm (or, with 0, disarm) the pod collective deadline and attach the
    PodStats sink. train_jax calls this only on multi-process runs, so
    single-process collectives keep the zero-overhead direct path."""
    global _pod_deadline_s, _pod_stats, _pod_grace_until, _beat_seq
    global _last_heartbeats
    with _pod_lock:
        _pod_deadline_s = max(0.0, float(timeout_s))
        _pod_stats = stats
        _pod_grace_until = 0.0
        if _pod_deadline_s == 0.0:
            _beat_seq = 0
            _last_heartbeats = None


def grant(extra_s: float) -> None:
    """Suppress deadline firing until `extra_s` seconds from NOW — the pod
    sibling of Watchdog.grant, for known-long lockstep windows (the first
    chunk dispatch's XLA compile can skew processes by more than the
    steady-state deadline; a compile-skewed peer is not a dead peer)."""
    global _pod_grace_until
    with _pod_lock:
        _pod_grace_until = max(
            _pod_grace_until, time.monotonic() + float(extra_s)
        )


def pod_deadline_s() -> float:
    """The currently-armed steady-state deadline (0 = off)."""
    return _pod_deadline_s


def beat_result_timeout_s(default_s: float = 600.0) -> float:
    """Outer wait bound for a background lockstep/shard_exchange beat
    ticket (replay/device.py sync_ship, train.py wait_beat). With the pod
    deadline armed, the lane's in-flight beat is already bounded by
    call_with_deadline — so the ticket wait only needs to cover at most
    one queued beat behind one in-flight beat, plus any active grant
    window (first-chunk compile) and dispatch slack. A wedge therefore
    surfaces as a typed failure within a small multiple of
    pod_collective_timeout_s instead of a hardcoded 10-minute stall;
    deadline unconfigured (single-process, or 0 = off) keeps the generous
    `default_s` — there is no peer to lose, only teardown stragglers."""
    t = _pod_deadline_s
    if t <= 0:
        return float(default_s)
    with _pod_lock:
        grace = max(0.0, _pod_grace_until - time.monotonic())
    return 2.0 * t + grace + 30.0


def wait_beat_ticket(ticket, label: str = "sync_ship beat"):
    """Resolve one background ordered-lane beat ticket under the derived
    deadline (beat_result_timeout_s), converting a TimeoutError into
    typed PodPeerLost — the ONE owner of the bounded-wait contract for
    both waiters (replay/device.py sync_ship's synchronous facade and
    train.py's wait_beat gate), so the timeout policy and the typed-abort
    message can never drift between them. Returns the beat's result;
    re-raises the beat's own exception (e.g. the lane deadline's
    PodPeerLost) unchanged."""
    timeout = beat_result_timeout_s()
    try:
        return ticket.result(timeout=timeout)
    except TimeoutError as e:
        _note_peer_lost(f"pod_peer_lost:{label}")
        raise PodPeerLost(
            f"background {label} unresolved after {timeout:.0f}s — the "
            "ordered beat lane is wedged (scheduler stalled or a peer "
            "process is gone)",
            reason="timeout",
        ) from e


def call_with_deadline(fn, timeout_s: Optional[float] = None,
                       label: str = "collective"):
    """Run `fn` bounded by the pod collective deadline. timeout_s=None
    uses the configured default; <= 0 (or an unconfigured default)
    SHORT-CIRCUITS to a direct call on the caller's thread — the
    single-process zero-overhead contract tests pin.

    A guarded call runs on a helper thread; if the deadline (plus any
    active grant) passes first, a `PodPeerLost(reason="timeout")` raises
    on the caller while the abandoned helper blocks on — the caller is
    aborting the process anyway, and a wedged gloo/DCN op has no cancel
    API. Successful calls record their elapsed time into PodStats (the
    collective_timeout near-miss / slack telemetry)."""
    t = _pod_deadline_s if timeout_s is None else float(timeout_s)
    if t <= 0 or getattr(_tls, "guarded", False):
        # Off, or already running under an outer guard (the scheduler's
        # lockstep wrap around a beat whose inner allgather is guarded
        # too): one deadline per collective, one helper thread, one
        # peer-lost count.
        return fn()
    with _pod_lock:
        grace_left = _pod_grace_until - time.monotonic()
    if grace_left > 0:
        # The grant EXTENDS the deadline by the remaining grace (the
        # documented worst-case detection latency is timeout + grace).
        t += grace_left
    box: dict = {}
    done = threading.Event()

    def _run():
        _tls.guarded = True
        try:
            box["result"] = fn()
        except BaseException as e:  # delivered to the waiting caller
            box["exc"] = e
        finally:
            done.set()

    t0 = time.monotonic()
    helper = threading.Thread(
        target=_run, daemon=True, name=f"pod-deadline-{label}"
    )
    helper.start()
    if not done.wait(t):
        stats = _pod_stats
        if stats is not None:
            stats.record_peer_lost()
        from distributed_ddpg_tpu import trace

        trace.instant("pod_peer_lost", label=label, deadline_s=t)
        _note_peer_lost(f"pod_peer_lost:{label}")
        raise PodPeerLost(
            f"pod collective {label!r} missed its {t:.1f}s deadline — a "
            f"peer process is gone or hung ({_liveness_note()})",
            reason="timeout",
        )
    elapsed = time.monotonic() - t0
    if "exc" in box:
        raise box["exc"]
    # Success only: failed collectives must not steer the near-miss /
    # slack telemetry the deadline is tuned from.
    stats = _pod_stats
    if stats is not None:
        stats.record_collective(elapsed, t)
    return box["result"]


def _note_peer_lost(reason: str) -> None:
    """Flip the process health state (obs/health.py) to degraded the
    moment a peer is declared lost — the /healthz endpoint must read
    degraded DURING the coordinated abort's teardown window (emergency
    checkpoint, election, linger), not only in the exit code after it.
    Lazy import + broad except: the typed-abort path must never gain a
    new failure mode from a diagnostics layer."""
    try:
        from distributed_ddpg_tpu.obs import health

        health.get().note(reason)
    except Exception:
        pass


def _parse_peer(message: str) -> Optional[int]:
    """Best-effort peer id from a transport/coordination error message
    (jax's coordination service and gloo both name the failed task/rank
    in most death reports)."""
    m = re.search(r"(?:task|process|peer|rank)[\s:#=]*(\d+)",
                  message, re.IGNORECASE)
    return int(m.group(1)) if m else None


def _liveness_note() -> str:
    """One-line last-known-alive summary for PodPeerLost messages: the
    heartbeat vector from the most recent successful beat."""
    with _pod_lock:
        beats = _last_heartbeats
        seq = _beat_seq
    if beats is None:
        return "no heartbeat beat completed yet"
    return (
        f"last heartbeats per process {list(int(b) for b in beats)} "
        f"at local beat {seq}"
    )


def last_heartbeats():
    """The most recent gathered per-process heartbeat vector (or None)."""
    with _pod_lock:
        return None if _last_heartbeats is None else _last_heartbeats.copy()


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    runtime_heartbeat_timeout_s: Optional[float] = None,
) -> bool:
    """Idempotent jax.distributed bootstrap. Args fall back to the standard
    env vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID,
    or cloud-TPU auto-detection when none are set). Returns True if a
    multi-process runtime was initialized, False for single-process runs.

    `runtime_heartbeat_timeout_s` stretches the JAX runtime's OWN death
    detection (see the comment at the call below); train_jax derives it
    from the pod deadline + grace so the clean-abort contract holds by
    default, and the POD_RUNTIME_HEARTBEAT_TIMEOUT_S env var remains the
    operator override."""
    import jax

    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])

    if coordinator_address is None and num_processes is None:
        # Single process (or cloud-TPU metadata auto-detect, which
        # jax.distributed.initialize() handles with no args — only attempt it
        # when a TPU runtime is actually present).
        return False

    try:
        # Stretch the JAX coordination service's OWN death detection
        # (default 10s x 10 missed = ~100s, after which the C++ client
        # LOG(FATAL)s the process — a SIGABRT with no emergency
        # checkpoint). The pod layer's collective deadline must WIN that
        # race so survivors abort cleanly with exit 76: train_jax passes
        # a value derived from pod_collective_timeout_s +
        # pod_startup_grace_s; POD_RUNTIME_HEARTBEAT_TIMEOUT_S overrides.
        # The knob rides the internal initializer (the public API does
        # not expose heartbeats in this jax version); any signature
        # drift falls back to the public path — detection then just
        # stays at the runtime's defaults.
        hb_env = os.environ.get("POD_RUNTIME_HEARTBEAT_TIMEOUT_S")
        hb = float(hb_env) if hb_env else runtime_heartbeat_timeout_s
        if hb and hb > 0:
            try:
                from jax._src.distributed import global_state as _gs

                interval = max(1, int(round(float(hb) / 10.0)))
                _gs.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    service_heartbeat_interval_seconds=interval,
                    service_max_missing_heartbeats=10,
                    client_heartbeat_interval_seconds=interval,
                    client_max_missing_heartbeats=10,
                )
                return jax.process_count() > 1
            except (ImportError, TypeError):
                pass  # private initializer moved: public path below
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return jax.process_count() > 1
    except RuntimeError as e:
        msg = str(e)
        # "already initialized": the public API's idempotent-re-entry
        # message; "only be called once": the internal initializer's
        # (POD_RUNTIME_HEARTBEAT_TIMEOUT_S path) wording for the same
        # condition.
        if "already initialized" in msg or "only be called once" in msg:
            return jax.process_count() > 1
        if "must be called before" in msg and jax.process_count() > 1:
            # Backend already live AND already multi-process: a legitimate
            # idempotent re-entry (the application bootstrapped distributed
            # before calling train). If the live backend is single-process,
            # the explicit multi-host request genuinely failed — re-raise
            # rather than silently training N independent copies.
            return True
        raise


# Every integer pod-layer gather (startup barrier, sync_ship beats, the
# env-budget gather, the resume election) is padded into one int64 vector
# of this many slots, so they ALL reuse a single compiled all-gather
# executable. One executable means one wire size for every host gather:
# even if the gloo CPU transport interleaves streams (its collective ops
# carry no type tag, only byte counts), the pod layer can never feed it
# mismatched op sizes. The election's newest-8-steps window is sized to
# this.
_UNIFORM_SLOTS = 8


def allgather_scalar(value, dtype=None, timeout_s: Optional[float] = None,
                     label: str = "allgather"):
    """All-gather one host scalar (or small fixed-shape vector) across
    processes; returns a numpy array of shape [process_count, ...]. The
    ONE host-initiated DCN collective the ingest/budget machinery needs
    (replay/device.py sync_ship beats, train.py's global env-step budget).
    Centralized here so every caller — including the transfer scheduler's
    lockstep lane, which must be the only thread issuing host-initiated
    collectives when background sync_ship is active (docs/TRANSFER.md) —
    goes through one audited, DEADLINE-GUARDED entry point: a hung gather
    raises PodPeerLost at the configured pod_collective_timeout_s instead
    of blocking forever, and a transport error on a multi-process run is
    typed the same way (a failed pod collective means a peer is gone —
    the pod must abort cleanly either way). Small integer payloads ride
    the uniform int64[_UNIFORM_SLOTS] transport (see above)."""
    import numpy as np

    arr = np.asarray(value, dtype) if dtype is not None else np.asarray(value)
    uniform = arr.dtype.kind in "iu" and arr.ndim <= 1 and arr.size <= _UNIFORM_SLOTS

    def _gather():
        from jax.experimental import multihost_utils

        if uniform:
            payload = np.zeros((_UNIFORM_SLOTS,), np.int64)
            payload[: arr.size] = arr.reshape(-1)
            out = np.asarray(multihost_utils.process_allgather(payload))
            out = out[:, : arr.size] if arr.ndim else out[:, 0]
            return out.astype(arr.dtype, copy=False)
        return np.asarray(multihost_utils.process_allgather(arr))

    try:
        return call_with_deadline(_gather, timeout_s=timeout_s, label=label)
    except PodPeerLost:
        raise
    except Exception as e:
        import jax

        if jax.process_count() > 1:
            stats = _pod_stats
            if stats is not None:
                stats.record_peer_lost()
            from distributed_ddpg_tpu import trace

            trace.instant("pod_peer_lost", label=label, error=repr(e)[:120])
            _note_peer_lost(f"pod_peer_lost:{label}")
            raise PodPeerLost(
                f"pod collective {label!r} failed mid-flight: {e!r} "
                f"({_liveness_note()})",
                peer=_parse_peer(str(e)),
                reason="error",
            ) from e
        raise


def beat_allgather(count, label: str = "sync_ship_beat"):
    """All-gather one int payload per process with a piggybacked heartbeat
    word (this process's beat sequence number) — the sync_ship beat path
    (replay/device.py). Every successful beat refreshes the last-known-
    alive vector `last_heartbeats()`, so when a later collective dies the
    PodPeerLost message reports how recently each peer was provably alive
    (bounded by the beat cadence: one per learner chunk in train_jax).
    Returns the gathered payload column, shape [process_count]."""
    import numpy as np

    global _beat_seq, _last_heartbeats
    with _pod_lock:
        _beat_seq += 1
        seq = _beat_seq
    gathered = allgather_scalar(
        np.asarray([int(count), seq], np.int64), label=label
    )
    with _pod_lock:
        _last_heartbeats = gathered[:, 1].copy()
    stats = _pod_stats
    if stats is not None:
        stats.note_beat()
    return gathered[:, 0]


def startup_barrier(grace_s: float, label: str = "pod_startup_barrier") -> None:
    """One-time pod rendezvous with its own GENEROUS grace, distinct from
    the steady-state collective deadline: under box load a peer's backend
    init / imports can lag by tens of seconds (the documented gloo child
    startup flake, CHANGES.md PR 5), and that skew must be absorbed once
    here — not false-fire the much tighter per-beat deadline, and not
    surface as a mid-test heartbeat timeout. No-op single-process."""
    import jax

    if jax.process_count() <= 1:
        return
    import sys

    import numpy as np

    t0 = time.monotonic()
    allgather_scalar(
        np.int32(jax.process_index()), timeout_s=float(grace_s), label=label
    )
    print(
        f"[pod] startup barrier: {jax.process_count()} processes "
        f"synchronized in {time.monotonic() - t0:.1f}s",
        file=sys.stderr, flush=True,
    )


def clock_handshake(label: str = "clock_handshake") -> Optional[dict]:
    """Startup monotonic<->wall offset handshake (docs/OBSERVABILITY.md
    §4): each process all-gathers its wall clock (int64 ms — the uniform
    transport, one more reuse of the single compiled gather executable)
    at ONE synchronized point, so every host learns every other host's
    wall-clock offset relative to rank 0. The per-host flight-recorder
    ring anchors timestamps to its own (wall_t0, perf_counter) pair;
    these offsets are the correction term `tools.runs merge-trace` uses
    to put N per-host timelines on one aligned clock — without them a
    skewed NTP host's spans land visibly out of order against the
    collectives they participated in. The gather itself bounds the skew
    measurement error at the collective's in-flight time. Returns
    {"wall_ms": [per-host], "offset_ms": [per-host, rank0-relative]};
    None single-process."""
    import jax
    import numpy as np

    if jax.process_count() <= 1:
        return None
    gathered = allgather_scalar(
        np.int64(int(time.time() * 1000.0)), label=label
    )
    wall_ms = [int(v) for v in np.asarray(gathered).reshape(-1)]
    return {
        "wall_ms": wall_ms,
        "offset_ms": [v - wall_ms[0] for v in wall_ms],
    }


def _common_step(gathered) -> int:
    """The greatest checkpoint step present on EVERY process, from the
    [process_count, k] gathered step matrix (-1 entries = padding). -1
    when no step is common. Pure so the election rule is unit-testable
    without a cluster; every process computes it from the identical
    gathered matrix, so the pod can never disagree."""
    import numpy as np

    rows = np.asarray(gathered, np.int64)
    common = None
    for row in rows:
        steps = {int(v) for v in row if int(v) >= 0}
        common = steps if common is None else (common & steps)
    return max(common) if common else -1


def elect_resume_step(local_steps: Iterable[int], limit: int = 8) -> int:
    """Coordinated resume election (docs/RESILIENCE.md): all-gather each
    process's newest `limit` manifest-valid checkpoint steps and return
    the greatest step available on EVERY process — restoring anything
    newer on some processes only would fork the pod. -1 = no common step
    (every process then starts fresh, which is also agreed). ALL
    processes must call this at the same point (train_jax resume)."""
    import numpy as np

    steps = sorted({int(s) for s in local_steps})[-max(1, int(limit)):]
    vec = np.full((max(1, int(limit)),), -1, np.int64)
    if steps:
        vec[: len(steps)] = np.asarray(steps, np.int64)
    gathered = allgather_scalar(vec, label="resume_step_election")
    return _common_step(gathered)


def elect_slice_step(local_step: Optional[int]) -> int:
    """Coordinated replay-slice adoption election (elastic pod;
    docs/REPLAY_SHARDING.md all-writer checkpoints): all-gather each
    process's newest complete slice step
    (checkpoint.latest_complete_slice_step) and adopt it only when EVERY
    process sees the SAME step — on a shared checkpoint filesystem that
    is the common case; under NFS visibility skew or per-host disks a
    disagreement must resolve to 'nobody adopts' (-1, every buffer
    resumes empty — also agreed), because a pod where some processes
    load rows and others don't has forked its data distribution. Rides
    the uniform int64 transport like every pod gather; single-process
    returns the local answer directly. ALL processes must call this at
    the same point (train_jax resume, right after the step election)."""
    import jax
    import numpy as np

    local = -1 if local_step is None else int(local_step)
    if jax.process_count() <= 1:
        return local
    gathered = allgather_scalar(
        np.int64(local), label="slice_step_election"
    )
    vals = {int(v) for v in np.asarray(gathered).reshape(-1)}
    return local if vals == {local} and local >= 0 else -1


def process_info() -> dict:
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
