"""Double-buffered host->HBM minibatch pipeline (SURVEY.md §7 step 5 and
'hard parts (a)': a >=20x-faster learner starves unless sampling + h2d leave
the step's critical path).

A daemon thread samples K minibatches from replay, stacks them into one
[K, B, ...] super-batch, and `jax.device_put`s it with the chunk sharding
(device_put is async — the transfer overlaps the learner's current chunk).
`depth` bounds the queue: depth=2 is classic double buffering (one chunk in
compute, one in flight). Sample indices stay host-side and ride along for
PER priority updates after the chunk's TD errors come back.
"""

from __future__ import annotations

import queue
import threading
import time
import warnings
from typing import Dict, Optional

import numpy as np

from distributed_ddpg_tpu import trace


# stop()-path drain bound: how long the worker grants an in-flight
# transfer ticket to land after stop is requested, before abandoning it
# to the scheduler (whose close() fails pending tickets loudly). A bound
# on shutdown courtesy, not a liveness deadline — liveness is next()'s
# PrefetchTimeout.
_STOP_DRAIN_S = 5.0


class PrefetchError(RuntimeError):
    """The prefetch worker thread died; the original exception rides along
    as __cause__ (the IngestError surfacing discipline). Subclasses
    RuntimeError so pre-existing blanket handlers keep working."""


class PrefetchTimeout(RuntimeError):
    """next() deadline expired with the worker thread still alive — replay
    starvation or a wedged device transfer, NOT a worker crash (a dead
    worker surfaces as 'prefetch thread died' with its real exception
    chained). Named so callers can distinguish a stall from the bare
    queue.Empty internals."""


class ChunkPrefetcher:
    def __init__(
        self,
        replay,
        put_chunk,                  # ShardedLearner.put_chunk (or any device placer)
        batch_size: int,
        chunk_size: int,
        depth: int = 2,
        lock: Optional[threading.Lock] = None,
        fault=None,                 # faults.FaultSite ticked per sample
        scheduler=None,             # transfer.TransferScheduler (optional)
    ):
        self._replay = replay
        self._put = put_chunk
        # Unified transfer scheduler (docs/TRANSFER.md): when attached,
        # the h2d device_put is submitted as a 'prefetch'-class work item
        # instead of running inline — the scheduler's fair queue then
        # rate-balances it against replay-ingest super-blocks (neither
        # stream can starve the other). Sampling stays on this worker
        # thread: it is CPU work, not bus work.
        self._sched = scheduler
        self._batch_size = batch_size
        self._chunk = chunk_size
        self._lock = lock or threading.Lock()
        # Chaos harness (faults.py): prefetch:sample:hang@k~s sleeps the
        # k-th chunk sample (PrefetchTimeout territory when s exceeds
        # next()'s deadline); prefetch:sample:crash@k kills the worker
        # thread, surfacing via next()'s 'prefetch thread died'.
        self._fault = fault
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True, name="prefetch")

    def start(self) -> "ChunkPrefetcher":
        self._thread.start()
        return self

    def _sample_chunk(self) -> Dict[str, np.ndarray]:
        # Flight-recorder span: host-replay sampling time on the prefetch
        # thread — when the learner's sample_wait phase grows, the
        # timeline shows whether THIS (lock contention, sample cost) or
        # the h2d below is the bottleneck.
        if self._fault is not None:
            self._fault.tick()
        with trace.span("prefetch_sample"):
            samples = []
            with self._lock:
                for _ in range(self._chunk):
                    samples.append(self._replay.sample(self._batch_size))
            return {
                k: np.stack([s[k] for s in samples]) for k in samples[0]
            }

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                chunk = self._sample_chunk()
                indices = chunk.pop("indices")
                # Re-check stop BEFORE committing to the device transfer:
                # put_chunk blocks on h2d (unboundedly, on a wedged
                # tunnel), and a stop() issued while we sampled must not
                # strand the join behind a transfer nobody will consume.
                if self._stop.is_set():
                    return
                if self._sched is not None:
                    nbytes = sum(
                        getattr(v, "nbytes", 0) for v in chunk.values()
                    )
                    ticket = self._sched.submit(
                        "prefetch", lambda: self._put(chunk),
                        nbytes=nbytes, label="prefetch_h2d",
                    )
                    # Bounded waits so a stop() during a scheduler stall
                    # still joins; a dead scheduler surfaces through the
                    # ticket as TransferError -> next()'s 'prefetch
                    # thread died'.
                    while not ticket.done():
                        if self._stop.is_set():
                            ticket.wait(_STOP_DRAIN_S)
                            break
                        ticket.wait(0.1)
                    if not ticket.done():
                        return
                    device_chunk = ticket.result(timeout=0.0)
                else:
                    with trace.span("prefetch_h2d"):
                        device_chunk = self._put(chunk)
                # Block here (not in get()) when the queue is full — this is
                # the backpressure that makes `depth` the buffer bound.
                while not self._stop.is_set():
                    try:
                        self._q.put((device_chunk, indices), timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surface in next()
            self._exc = e

    def next(self, timeout: float = 60.0):
        """Returns (device_chunk, host_indices[K, B]). Re-checks for a dead
        worker while waiting so its real exception surfaces promptly instead
        of an unrelated queue timeout; a deadline with the worker ALIVE
        raises PrefetchTimeout (named), never a bare queue.Empty."""
        deadline = time.monotonic() + timeout
        while True:
            if self._exc is not None:
                raise PrefetchError("prefetch thread died") from self._exc
            try:
                return self._q.get(timeout=min(0.5, max(0.0, deadline - time.monotonic())))
            except queue.Empty:
                if time.monotonic() >= deadline:
                    raise PrefetchTimeout(
                        f"no prefetched chunk within {timeout:.1f}s with the "
                        "worker alive — replay starvation or a wedged "
                        "device transfer"
                    ) from None

    def stop(self, timeout: float = 5.0) -> bool:
        """Stop the worker and join it. Drains the queue REPEATEDLY while
        joining: a worker blocked in q.put refills the single slot a
        one-shot drain frees, and a worker blocked inside put_chunk's
        device transfer may surface one more chunk before seeing the stop
        flag. Returns False (with a warning) if the worker is still alive
        at the deadline — it can only be wedged inside an uninterruptible
        device transfer; the daemon thread is leaked rather than hanging
        teardown forever."""
        self._stop.set()
        deadline = time.monotonic() + timeout
        while self._thread.is_alive() and time.monotonic() < deadline:
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.1)
        if self._thread.is_alive():
            warnings.warn(
                "prefetch worker did not exit within "
                f"{timeout:.1f}s (blocked in a device transfer?); leaking "
                "the daemon thread"
            )
            return False
        return True
