"""Device mesh + sharding specs (replaces the reference's parameter-server
variable placement, SURVEY.md §2 #10 / §5 'Distributed communication backend').

The reference pins variables to /job:ps and replicates worker graphs over
gRPC. Here the topology is a `jax.sharding.Mesh` with two named axes:

- `data`: the data-parallel axis. Replay minibatches shard their leading
  (batch) dim here; XLA turns the per-shard gradient contributions into one
  AllReduce over ICI (the `psum` the north star names, BASELINE.json:5).
  Sharded device replay partitions its HBM ring over this axis too
  (docs/REPLAY_SHARDING.md).
- `model`: tensor parallelism. Params shard over this axis according to
  the regex partition-rule tables in `parallel/partition.py` (Megatron
  column-/row-parallel alternation by default; per-net tables for
  anything else — docs/MESH.md has the grammar and the add-a-rule
  recipe). model_axis > 1 composes with sharded replay, device actors,
  the serve jax backend, and the fused megastep: per-device param +
  optimizer HBM divides by the model-axis size.

This module owns the MESH (make_mesh, shard_map, to_named) and the
batch-side specs; the param-side spec construction (net_pspec,
state_pspec) lives in partition.py and is re-exported here so existing
callers keep their import path.

Multi-host (DCN) uses the SAME mesh/specs: jax.distributed.initialize makes
jax.devices() span hosts, and XLA routes the collective hierarchically
(ICI within host, DCN across; SURVEY.md §5 row 'Distributed comm backend').
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu.parallel.partition import (  # noqa: F401 (re-export)
    PartitionRuleError,
    match_partition_rules,
    mlp_rules,
    net_pspec,
    state_pspec,
)
from distributed_ddpg_tpu.types import Batch

# Placement-invariant PRNG (the future jax default): with the legacy
# non-partitionable threefry, the VALUES jax.random produces inside a
# jitted program depend on the mesh's model-axis size — measured: the
# same key draws different normals under (4, 1) vs (4, 2) meshes — which
# would make every sampled minibatch and OU-noise stream a function of
# the TP degree and break the model_axis parity oracle
# (tests/test_partition.py). Set at import of THIS module — every
# device-program owner imports it before building programs, so all
# programs in a process trace under one consistent scheme regardless of
# which entry point (train/bench/proganalyze/multihost child) started
# it. An explicit JAX_THREEFRY_PARTITIONABLE in the environment wins:
# that is the embedder's escape hatch back to the legacy scheme.
import os as _os

if _os.environ.get("JAX_THREEFRY_PARTITIONABLE", "") == "":
    jax.config.update("jax_threefry_partitionable", True)


def shard_map(f, mesh: Mesh, in_specs, out_specs, check: bool = False):
    """Version-portable shard_map: jax >= 0.6 exposes `jax.shard_map` with
    `check_vma`; older jaxes (0.4.x here) only have
    `jax.experimental.shard_map.shard_map` with the equivalent flag spelled
    `check_rep`. Same semantics either way — per-shard body, explicit
    collectives, specs name this module's (data, model) axes."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check
    )


def make_mesh(
    data_axis: int = -1,
    model_axis: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, model) mesh. data_axis=-1 means 'all remaining devices'."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if model_axis < 1 or n % model_axis:
        raise ValueError(f"model_axis={model_axis} must divide device count {n}")
    if data_axis == -1:
        data_axis = n // model_axis
    if data_axis * model_axis != n:
        raise ValueError(
            f"mesh {data_axis}x{model_axis} != {n} devices"
        )
    arr = np.asarray(devices).reshape(data_axis, model_axis)
    return Mesh(arr, ("data", "model"))


def batch_pspec() -> Batch:
    """Minibatches shard their batch dim over 'data' (fields are [B, ...])."""
    return Batch(
        obs=P("data", None),
        action=P("data", None),
        reward=P("data"),
        discount=P("data"),
        next_obs=P("data", None),
        weight=P("data"),
    )


def to_named(mesh: Mesh, spec_tree):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
