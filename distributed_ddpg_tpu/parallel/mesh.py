"""Device mesh + sharding specs (replaces the reference's parameter-server
variable placement, SURVEY.md §2 #10 / §5 'Distributed communication backend').

The reference pins variables to /job:ps and replicates worker graphs over
gRPC. Here the topology is a `jax.sharding.Mesh` with two named axes:

- `data`: the data-parallel axis. Replay minibatches shard their leading
  (batch) dim here; XLA turns the per-shard gradient contributions into one
  AllReduce over ICI (the `psum` the north star names, BASELINE.json:5).
- `model`: optional tensor parallelism. DDPG's MLPs are far too small to
  NEED TP (SURVEY.md §2 'Parallelism-strategy inventory' marks it N/A in the
  reference), but params are plain pytrees so the spec tree below shards
  hidden dims Megatron-style (alternating column-/row-parallel) when
  model_axis > 1 — proving the design scales to nets where TP matters.

Multi-host (DCN) uses the SAME mesh/specs: jax.distributed.initialize makes
jax.devices() span hosts, and XLA routes the collective hierarchically
(ICI within host, DCN across; SURVEY.md §5 row 'Distributed comm backend').
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu.types import Batch, OptState, TrainState


def shard_map(f, mesh: Mesh, in_specs, out_specs, check: bool = False):
    """Version-portable shard_map: jax >= 0.6 exposes `jax.shard_map` with
    `check_vma`; older jaxes (0.4.x here) only have
    `jax.experimental.shard_map.shard_map` with the equivalent flag spelled
    `check_rep`. Same semantics either way — per-shard body, explicit
    collectives, specs name this module's (data, model) axes."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check
    )


def make_mesh(
    data_axis: int = -1,
    model_axis: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, model) mesh. data_axis=-1 means 'all remaining devices'."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if model_axis < 1 or n % model_axis:
        raise ValueError(f"model_axis={model_axis} must divide device count {n}")
    if data_axis == -1:
        data_axis = n // model_axis
    if data_axis * model_axis != n:
        raise ValueError(
            f"mesh {data_axis}x{model_axis} != {n} devices"
        )
    arr = np.asarray(devices).reshape(data_axis, model_axis)
    return Mesh(arr, ("data", "model"))


def _layer_pspec(layer_index: int, num_layers: int, kernel_shape, model_size: int):
    """Megatron-style alternation: even layers column-parallel (shard the
    output dim), odd layers row-parallel (shard the input dim). The final
    layer stays replicated (its output dim is act_dim / 1 / num_atoms —
    tiny and indivisible). Dims that don't divide the model axis stay
    replicated rather than erroring — XLA would pad, we'd rather not."""
    if len(kernel_shape) == 3:
        # Ensemble-stacked critic (TD3 twin, learner.init_train_state):
        # leading [2] axis replicated, TP alternation applied to the inner
        # (in, out) dims exactly as for a plain critic.
        inner = _layer_pspec(layer_index, num_layers, kernel_shape[1:], model_size)
        return {"w": P(None, *inner["w"]), "b": P(None, *inner["b"])}
    in_dim, out_dim = kernel_shape
    if model_size == 1 or layer_index == num_layers - 1:
        return {"w": P(None, None), "b": P(None)}
    if layer_index % 2 == 0:
        if out_dim % model_size == 0:
            return {"w": P(None, "model"), "b": P("model")}
    else:
        if in_dim % model_size == 0:
            return {"w": P("model", None), "b": P(None)}
    return {"w": P(None, None), "b": P(None)}


def net_pspec(params, model_size: int):
    n = len(params)
    return tuple(
        _layer_pspec(i, n, params[i]["w"].shape, model_size) for i in range(n)
    )


def state_pspec(state: TrainState, mesh: Mesh) -> TrainState:
    """PartitionSpec tree mirroring TrainState 1:1. Params (and their Adam
    moments, which must shard identically) follow net_pspec; scalars
    replicate."""
    m = mesh.shape["model"]
    actor = net_pspec(state.actor_params, m)
    critic = net_pspec(state.critic_params, m)
    return TrainState(
        actor_params=actor,
        critic_params=critic,
        target_actor_params=actor,
        target_critic_params=critic,
        actor_opt=OptState(mu=actor, nu=actor, count=P()),
        critic_opt=OptState(mu=critic, nu=critic, count=P()),
        step=P(),
        # SAC temperature scalars replicate; None (non-SAC) is an empty
        # pytree node and needs no spec.
        log_alpha=None if state.log_alpha is None else P(),
        alpha_opt=(
            None
            if state.alpha_opt is None
            else OptState(mu=P(), nu=P(), count=P())
        ),
    )


def batch_pspec() -> Batch:
    """Minibatches shard their batch dim over 'data' (fields are [B, ...])."""
    return Batch(
        obs=P("data", None),
        action=P("data", None),
        reward=P("data"),
        discount=P("data"),
        next_obs=P("data", None),
        weight=P("data"),
    )


def to_named(mesh: Mesh, spec_tree):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
