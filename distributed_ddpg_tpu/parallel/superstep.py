"""Compile-once multi-beat superstep: B fused beats in ONE lax.fori_loop
program per dispatch (`config.superstep_beats`; docs/FUSED_BEAT.md).

PR 13's fused megastep made one steady-state iteration a single jitted
program, but the host still returns to Python once per beat — dispatch
latency, the stats device_get, and JSONL bookkeeping pace the loop
instead of the hardware (exactly the host-orchestration overhead arXiv
2012.04210 measures dominating accelerator RL loops). Going all the way
Anakin (Podracer, PAPERS.md arXiv 2104.06272) means the training EPOCH
is one dispatch: this module wraps B copies of the megastep's pure beat
body (megastep.build_beat_body — the identical composition, not a
re-implementation) inside one donated-carry `jax.lax.fori_loop`, so
B x (sample + K updates, rollout, ring scatter, guardrail probe) runs
with zero host round-trips and the host returns to Python once per
SUPERSTEP.

Structure: ALL B beats run inside `fori_loop(0, B, body, carry)` — the
carry's StepOutput slots are zero-initialized at trace time (eval_shape;
only out.state, seeded with the real incoming TrainState, feeds
arithmetic). Keeping every beat in the loop body is load-bearing for
bit-identity: the body compiles as its own isolated HLO computation and
gets the same codegen as the standalone jitted beat program, whereas a
beat inlined into the main computation gets cross-optimized with its
surroundings (reassociation/fusion, ULP-level divergence that even an
optimization_barrier does not stop). The traced loop body is jit-free
(the recompile-hazard lint asserts this shape stays jit-free: a nested
jit inside the traced body would re-trace per recomposition and defeat
the compile-once contract).

Stats stop being a per-beat host sync:

- **guarded**: the per-beat cumulative int32 health words stack into a
  device-side `[B, 5]` carry (`.at[i].set` in the loop body) and the
  bad-row index captures into `[B, GUARD_BAD_IDX]`;
  `ShardedLearner.note_fused_health` takes the stacked vectors and
  `poll_health()` pays ONE device_get per superstep — the final row is
  the chunk-end cumulative counters the host monitor differences, and
  the per-row deltas yield the first-bad-beat index the guardrail event
  log surfaces. Quarantine stays per-beat ON DEVICE (the tree-select in
  the probe body is unchanged); host rollback/LR-backoff decisions move
  to superstep granularity.
- **unguarded**: metrics/td_errors of the FINAL beat come out (the only
  ones the cadence ever reads — identical to what B sequential beats
  leave in `out`), so nothing syncs until the JSONL cadence asks.

PER beta anneal: the host precomputes the B per-beat betas as a
float32[B] vector reproducing the sequential schedule (beat b anneals
from `budget + b * rows_per_beat`) and the loop body indexes `betas[i]`
— computing the anneal in f32 on device could round differently and
break the bit-identity oracle.

Multi-host: the superstep is one global SPMD program dispatched at the
SAME lockstep site run_beat occupied; host-row `sync_ship`/ingest beats
still ride the transfer scheduler's ordered lanes BETWEEN supersteps
(folding sync_ship into the loop is explicitly out of scope — it is a
host-mediated transfer and would couple the loop to host scheduling).

Bit-identity oracle: `superstep_beats=B` produces bit-identical
TrainState, ring contents, rollout carry, sampling key, and PER
priorities to B sequential fused beats (tests/test_superstep.py pins
B=1 vs unfused and B=4 vs 4 sequential beats across uniform/PER x
replicated/sharded x guarded/unguarded). Rebuild contract matches the
megastep: a learner `programs_version` bump recomposes the loop body on
the next dispatch (one XLA recompile, same allowance discipline).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import StepOutput
from distributed_ddpg_tpu.metrics import FusedBeatStats
from distributed_ddpg_tpu.parallel.megastep import build_beat_body


def per_beat_betas(config: DDPGConfig, budget_now: int, beats: int,
                   rows_per_beat: int) -> np.ndarray:
    """The float32[B] PER beta-anneal vector a B-beat superstep consumes:
    entry b is exactly the beta the sequential loop would compute before
    its b-th beat (device rows advance rows_per_beat per beat). Host-side
    numpy on purpose — the train loop's anneal runs in Python floats, and
    replicating it bit-for-bit is part of the superstep oracle."""
    betas = np.empty((beats,), np.float32)
    for b in range(beats):
        frac = min(
            1.0, (budget_now + b * rows_per_beat) / config.total_env_steps
        )
        betas[b] = np.float32(
            config.per_beta + frac * (config.per_beta_final - config.per_beta)
        )
    return betas


class FusedSuperstep:
    """B fused beats in one donated-carry fori_loop program — see module
    docstring. Drop-in sibling of FusedMegastep (train.py constructs one
    or the other from config.superstep_beats); drives the live
    learner/pool/replay state exactly as B sequential run_beat calls
    would, with one dispatch and one host sync point."""

    def __init__(self, config: DDPGConfig, learner, pool, replay,
                 beats: Optional[int] = None):
        self.config = config
        self.learner = learner
        self.pool = pool
        self.replay = replay
        self.per = bool(config.prioritized)
        self.guard = bool(learner.guard_enabled)
        self.beats = int(
            beats if beats is not None else config.superstep_beats
        )
        if self.beats < 1:
            raise ValueError(f"superstep beats must be >= 1, got {beats}")
        self.chunk_size = int(learner.chunk_size)   # learner steps / beat
        self.rows_per_beat = int(pool.rows_per_chunk)
        self._stats = FusedBeatStats(seed=config.seed)
        self._build()

    def _build(self) -> None:
        beat, in_sh, out_sh, donate = build_beat_body(
            self.learner, self.pool, self.replay, self.per, self.guard,
            self.rows_per_beat,
        )
        B = self.beats

        # One composition per (per, guard) variant. EVERY beat runs inside
        # the fori_loop body (range 0..B): the loop body compiles as its
        # own isolated HLO computation, so XLA gives it the same codegen
        # as the standalone jitted beat program — that is what makes the
        # superstep BIT-identical to B sequential run_beat dispatches.
        # (Inlining the first beat into the main computation instead was
        # measurably NOT bit-identical: XLA cross-optimizes an inlined
        # beat with its surroundings — reassociation/fusion at ULP level —
        # and an optimization_barrier does not stop the divergence.)
        # Shape discipline: the pre-loop StepOutput carry slots are
        # zero-initialized from eval_shape (trace-time only, no FLOPs);
        # the body overwrites them every iteration and only out.state —
        # seeded with the REAL incoming state — feeds arithmetic.

        def init_out(shapes, state):
            out0 = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), shapes[0]
            )
            return out0._replace(state=state)

        if not self.per and not self.guard:

            def superstep(state, key, storage, ptr, size, carry):
                shapes = jax.eval_shape(
                    beat, state, key, storage, ptr, size, carry
                )
                out0 = init_out(shapes, state)

                def body(i, acc):
                    out, key, storage, ptr, size, carry = acc
                    return beat(out.state, key, storage, ptr, size, carry)

                return jax.lax.fori_loop(
                    0, B, body, (out0, key, storage, ptr, size, carry)
                )

        elif not self.per and self.guard:

            def superstep(state, key, storage, ptr, size, carry, g):
                shapes = jax.eval_shape(
                    beat, state, key, storage, ptr, size, carry, g
                )
                out0 = init_out(shapes, state)
                # Stacked per-beat stats carry: health rows land at [i],
                # bad-row captures pre-filled with the device's "no bad
                # row" sentinel (-1) — one device_get reads the lot.
                hs = jnp.zeros((B,) + shapes[7].shape, shapes[7].dtype)
                bs = jnp.full((B,) + shapes[8].shape, -1, shapes[8].dtype)

                def body(i, acc):
                    out, key, storage, ptr, size, carry, g, hs, bs = acc
                    (out, key, storage, ptr, size, carry, g, h,
                     b) = beat(out.state, key, storage, ptr, size, carry, g)
                    return (out, key, storage, ptr, size, carry, g,
                            hs.at[i].set(h), bs.at[i].set(b))

                return jax.lax.fori_loop(
                    0, B, body,
                    (out0, key, storage, ptr, size, carry, g, hs, bs),
                )

        elif self.per and not self.guard:

            def superstep(state, key, storage, ptr, size, carry,
                          priorities, maxp, betas, alpha, eps):
                shapes = jax.eval_shape(
                    beat, state, key, storage, ptr, size, carry,
                    priorities, maxp, betas[0], alpha, eps,
                )
                out0 = init_out(shapes, state)

                def body(i, acc):
                    (out, key, storage, ptr, size, carry, priorities,
                     maxp) = acc
                    return beat(out.state, key, storage, ptr, size, carry,
                                priorities, maxp, betas[i], alpha, eps)

                return jax.lax.fori_loop(
                    0, B, body,
                    (out0, key, storage, ptr, size, carry, priorities,
                     maxp),
                )

        else:

            def superstep(state, key, storage, ptr, size, carry,
                          priorities, maxp, betas, alpha, eps, g):
                shapes = jax.eval_shape(
                    beat, state, key, storage, ptr, size, carry,
                    priorities, maxp, betas[0], alpha, eps, g,
                )
                out0 = init_out(shapes, state)
                hs = jnp.zeros((B,) + shapes[9].shape, shapes[9].dtype)
                bs = jnp.full((B,) + shapes[10].shape, -1, shapes[10].dtype)

                def body(i, acc):
                    (out, key, storage, ptr, size, carry, priorities, maxp,
                     g, hs, bs) = acc
                    (out, key, storage, ptr, size, carry, priorities, maxp,
                     g, h, b) = beat(
                        out.state, key, storage, ptr, size, carry,
                        priorities, maxp, betas[i], alpha, eps, g,
                    )
                    return (out, key, storage, ptr, size, carry, priorities,
                            maxp, g, hs.at[i].set(h), bs.at[i].set(b))

                return jax.lax.fori_loop(
                    0, B, body,
                    (out0, key, storage, ptr, size, carry, priorities,
                     maxp, g, hs, bs),
                )

        # The jit contract is the megastep's own per-variant tuple: same
        # argument order, same donation indices; the guarded health/bad
        # outputs simply grow a leading [B] axis (still replicated).
        sup_in = in_sh
        sup_out = out_sh

        self._superstep = jax.jit(
            superstep,
            in_shardings=sup_in,
            out_shardings=sup_out,
            donate_argnums=donate,
        )
        self._donate = donate
        self._learner_version = self.learner.programs_version

    # --- driving ---

    def run_superstep(self, betas: Optional[np.ndarray] = None) -> StepOutput:
        """Dispatch B fused beats as one program and install every
        returned carry piece back on the live objects, exactly where B
        sequential run_beat calls would have left them. `betas` is the
        float32[B] PER anneal vector (per_beat_betas); None for uniform.
        Returns the FINAL beat's StepOutput — the one a sequential run's
        last after_chunk would consume."""
        L, pool, replay = self.learner, self.pool, self.replay
        if self._learner_version != L.programs_version:
            # The learner rebuilt its chunk bodies (LR backoff, support
            # expansion): recompose the whole loop body against the fresh
            # bodies — one XLA recompile, the megastep's rebuild contract.
            self._build()
        B = self.beats
        t0 = time.perf_counter()
        with replay.dispatch_lock:
            with trace.span(
                "superstep", beats=B, rows=B * self.rows_per_beat,
                steps=B * self.chunk_size,
            ):
                if self.per:
                    bvec = jnp.asarray(
                        np.broadcast_to(
                            np.asarray(betas, np.float32), (B,)
                        ).copy()
                    )
                    scalars = (
                        bvec, np.float32(replay.alpha),
                        np.float32(replay.eps),
                    )
                    if self.guard:
                        (out, key, storage, ptr, size, carry, prios, maxp,
                         g, health, bad_idx) = self._superstep(
                            L.state, L._key, replay.storage, replay.ptr,
                            replay.size, pool._carry, replay.priorities,
                            replay.max_priority, *scalars, L._guard,
                        )
                        L.note_fused_health(g, health, bad_idx)
                    else:
                        (out, key, storage, ptr, size, carry, prios,
                         maxp) = self._superstep(
                            L.state, L._key, replay.storage, replay.ptr,
                            replay.size, pool._carry, replay.priorities,
                            replay.max_priority, *scalars,
                        )
                    replay.set_per_state(prios, maxp)
                else:
                    if self.guard:
                        (out, key, storage, ptr, size, carry, g, health,
                         bad_idx) = self._superstep(
                            L.state, L._key, replay.storage, replay.ptr,
                            replay.size, pool._carry, L._guard,
                        )
                        L.note_fused_health(g, health, bad_idx)
                    else:
                        (out, key, storage, ptr, size,
                         carry) = self._superstep(
                            L.state, L._key, replay.storage, replay.ptr,
                            replay.size, pool._carry,
                        )
                L.state = out.state
                L._key = key
                replay.storage, replay.ptr, replay.size = storage, ptr, size
                replay.note_device_rows(B * self.rows_per_beat)
            dt = time.perf_counter() - t0
        pool.absorb_fused_chunk(carry, dt, beats=B)
        self._stats.record_beat(
            B * self.chunk_size, B * self.rows_per_beat, dt, beats=B,
        )
        return out

    # --- host-side views ---

    def snapshot(self) -> dict:
        """fused_* observability fields (metrics.FusedBeatStats;
        docs/OBSERVABILITY.md) — the superstep reuses the fused family,
        with fused_supersteps/fused_superstep_beats marking the dispatch
        amortization."""
        return self._stats.snapshot()

    def example_args(self, beta: float = 1.0):
        """The live argument tuple the superstep program traces over —
        the program-contract analyzer hook below feeds it to
        BuiltProgram (donation indices match run_superstep's dispatch)."""
        L, pool, replay = self.learner, self.pool, self.replay
        args = [L.state, L._key, replay.storage, replay.ptr, replay.size,
                pool._carry]
        if self.per:
            args += [replay.priorities, replay.max_priority,
                     np.full((self.beats,), beta, np.float32),
                     np.float32(replay.alpha), np.float32(replay.eps)]
        if self.guard:
            args.append(L._guard)
        return tuple(args)


# ---------------------------------------------------------------------------
# program-contract analyzer hook (analysis/programs.py; docs/ANALYSIS.md
# "Layer 2")
# ---------------------------------------------------------------------------


def program_specs():
    """The superstep family at B=2 (the smallest loop that actually
    iterates), built tiny under the 2-device CPU probe mesh: uniform +
    PER x replicated + sharded x guarded + unguarded, plus the TP
    composition. The donated carry is the megastep's ENLARGED by the
    loop (same donated tuple — the stacked health words are outputs, not
    inputs) and must still alias through the lowered artifact; the
    guarded/unguarded pair of each shape dispatches at the same lockstep
    site, so they share a beat_group exactly like the megastep variants
    (the superstep's collective order is the beat's order twice: once
    for the inline first beat, once for the traced loop body)."""
    from distributed_ddpg_tpu.analysis.programs import (
        BuiltProgram,
        ProgramSpec,
        probe_config,
        probe_mesh,
    )
    from distributed_ddpg_tpu.actors.device_pool import DeviceActorPool
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import (
        DevicePrioritizedReplay,
        DeviceReplay,
    )

    OWNER = "parallel/superstep.py"
    cache = {}

    def superstep(
        guard: bool, per: bool, sharded: bool, tp: bool = False
    ) -> FusedSuperstep:
        key = (guard, per, sharded, tp)
        if key not in cache:
            placement = "sharded" if sharded else "replicated"
            config = probe_config(
                actor_backend="device",
                num_actors=0,
                device_actor_envs=4,
                device_actor_chunk=2,
                guardrails=guard,
                prioritized=per,
                replay_sharding=placement,
                fused_chunk="off",
                fused_beat="on",
                superstep_beats=2,
                model_axis=2 if tp else 1,
            )
            mesh = probe_mesh(2 if tp else 1)
            pool = DeviceActorPool(config, mesh=mesh)
            learner = ShardedLearner(
                config,
                pool.obs_dim,
                pool.act_dim,
                pool.action_scale,
                action_offset=pool.action_offset,
                mesh=mesh,
                chunk_size=2,
                replay_sharding=placement,
            )
            replay_cls = DevicePrioritizedReplay if per else DeviceReplay
            replay = replay_cls(
                64, pool.obs_dim, pool.act_dim, mesh=mesh, block_size=8,
                async_ship=False, replay_sharding=placement,
            )
            cache[key] = FusedSuperstep(
                config, learner, pool, replay, beats=2
            )
        return cache[key]

    def build(guard: bool, per: bool, sharded: bool, tp: bool = False):
        def _build():
            ss = superstep(guard, per, sharded, tp)
            return BuiltProgram(
                ss._superstep, ss.example_args(), ss._donate
            )
        return _build

    specs = []
    for per, kind in ((False, "uniform"), (True, "per")):
        for sharded in (False, True):
            shard_tag = ".sharded" if sharded else ""
            for guard in (False, True):
                tag = ".guarded" if guard else ""
                specs.append(ProgramSpec(
                    f"superstep.loop.{kind}{shard_tag}{tag}",
                    OWNER,
                    build(guard, per, sharded),
                    beat_group=f"superstep-loop-{kind}{shard_tag}",
                ))
        # TP variant (docs/MESH.md): the carry pspecs — TP-sharded params
        # + 'data'-sharded ring — must survive the fori_loop composition
        # under the (2, 2) probe mesh; shares the 1D sharded loop's
        # beat_group so the staged exchange order cannot fork a pod
        # mixing TP degrees.
        specs.append(ProgramSpec(
            f"superstep.loop.{kind}.sharded.tp",
            OWNER,
            build(False, per, True, tp=True),
            beat_group=f"superstep-loop-{kind}.sharded",
        ))
    return specs
