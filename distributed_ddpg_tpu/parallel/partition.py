"""Regex partition-rule engine: param-tree paths -> PartitionSpecs
(ROADMAP '2D (data, model) named mesh with regex partition rules';
SNIPPETS.md [2] `match_partition_rules`, [3] DreamZero's ('data','model')
rule tables).

The old `mesh._layer_pspec` hardcoded one network shape: an MLP whose
layers alternate Megatron column-/row-parallel by index parity. That
worked for the seed's two MLPs and nothing else — a pixel encoder's conv
kernels, a distributional critic's wide value head, or any future net
would each need another bespoke if-ladder. This module replaces it with
the idiom large-model JAX codebases converged on: an ORDERED rule table
mapping regex patterns over '/'-joined tree paths to PartitionSpecs,
first match wins.

Semantics (each one a contract tests/test_partition.py pins):

- **paths** — a leaf's path is its pytree key path '/'-joined: the actor
  tuple's layer-2 kernel is `2/w`. Rules are matched with `re.search`,
  so tables may anchor (`^...$`) or float.
- **first match wins** — the table is ordered; put specific overrides
  (the final-layer replication rule) ahead of generic parity rules.
- **rank alignment** — a spec shorter than the leaf's rank aligns to the
  TRAILING dims and the extra leading dims replicate. This is what makes
  one rule cover both a plain critic kernel `[in, out]` and the TD3
  twin-ensemble kernel `[2, in, out]` (learner.init_train_state stacks
  the pair on a leading axis).
- **indivisible -> replicated** — a leaf whose 'model'-sharded dim does
  not divide the model-axis size replicates instead of erroring (XLA
  would pad; we'd rather not). This is a per-leaf decision and exactly
  reproduces the old per-layer fallback: the seed critic's
  action-insert layer (in_dim = hidden + act_dim, usually odd) stays
  replicated while its neighbors shard.
- **scalars replicate** — rank-0 leaves get P() without consulting the
  table (the SNIPPETS.md [2] rule).
- **unmatched -> hard error** — a path no rule covers raises
  PartitionRuleError naming the path. A silently-replicated new layer
  is exactly the drift this engine exists to prevent: add a rule, on
  purpose, in review.

The default tables reproduce the old alternation bit-for-bit
(tests/test_partition.py pins the equality at the seed shapes):
even-index layers column-parallel (shard the output dim), odd-index
row-parallel (shard the input dim), final layer replicated (its output
dim is act_dim / 1 / num_atoms — tiny and indivisible). Even/odd is a
plain regex fact of decimal strings (last digit [02468] / [13579]); only
the final-layer override depends on the net's depth, so `mlp_rules(n)`
prepends it per net.

`state_pspec` derives the Adam-moment specs from the SAME tables the
params use — params and optimizer state can never shard differently,
which is the invariant that makes checkpoint restore and the
pointer-swap param refresh placement-oblivious.

Add-a-rule recipe and the data x model composition decision table:
docs/MESH.md.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_ddpg_tpu.types import OptState, TrainState

# One rule: (regex over the '/'-joined tree path, PartitionSpec). The
# spec names mesh axes ('model' here; 'data' stays a batch-dim axis and
# never appears in param tables).
Rule = Tuple[str, P]


class PartitionRuleError(ValueError):
    """A param-tree path matched no rule in the table. Every leaf must be
    placed ON PURPOSE — extend the table (docs/MESH.md 'add a rule')
    rather than letting a new layer silently replicate."""


# Megatron alternation for a {w, b} MLP layer list, index-parity encoded
# as a regex over the layer index's last decimal digit. Final-layer
# replication is depth-dependent and prepended by mlp_rules().
DEFAULT_MLP_RULES: Tuple[Rule, ...] = (
    # even layers: column-parallel (shard the output dim; bias shards too)
    (r"(^|/)\d*[02468]/w$", P(None, "model")),
    (r"(^|/)\d*[02468]/b$", P("model")),
    # odd layers: row-parallel (shard the input dim; bias replicated —
    # it adds after the partial-sum reduction)
    (r"(^|/)\d*[13579]/w$", P("model", None)),
    (r"(^|/)\d*[13579]/b$", P(None)),
)


def mlp_rules(num_layers: int) -> Tuple[Rule, ...]:
    """The default table for an MLP of `num_layers` {w, b} layers: the
    final layer replicates (override first), everything else follows the
    parity alternation."""
    last = num_layers - 1
    return (
        (rf"(^|/){last}/w$", P(None, None)),
        (rf"(^|/){last}/b$", P(None)),
    ) + DEFAULT_MLP_RULES


def _path_str(path) -> str:
    """'/'-joined pytree key path: SequenceKey(2)/DictKey('w') -> '2/w'."""
    parts = []
    for k in path:
        if hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:  # pragma: no cover - future key kinds
            parts.append(str(k))
    return "/".join(parts)


def _fit(spec: P, shape: Tuple[int, ...], model_size: int) -> P:
    """Align `spec` to a leaf of `shape` under a model axis of
    `model_size`: trailing-dim alignment (extra leading dims replicate),
    whole-leaf replication when model_size == 1 or when any sharded dim
    does not divide it (module docstring 'indivisible -> replicated')."""
    if len(spec) > len(shape):
        raise PartitionRuleError(
            f"rule spec {spec} has rank {len(spec)} but the leaf has "
            f"shape {shape} — a spec must not outrank its leaf"
        )
    full = (None,) * (len(shape) - len(spec)) + tuple(spec)
    replicated = P(*(None,) * len(shape))
    if model_size == 1:
        return replicated
    for dim, ax in zip(shape, full):
        if ax is not None and dim % model_size != 0:
            return replicated
    return P(*full)


def match_partition_rules(rules: Sequence[Rule], tree, model_size: int):
    """PartitionSpec tree for `tree` under the ordered rule table
    (SNIPPETS.md [2]): scalars replicate, the first matching rule's spec
    is rank-aligned and divisibility-gated by _fit, and an unmatched
    path is a hard PartitionRuleError."""

    def place(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0:
            return P()
        name = _path_str(path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                return _fit(spec, shape, model_size)
        raise PartitionRuleError(
            f"no partition rule matches param path {name!r} (shape "
            f"{shape}) — extend the rule table (docs/MESH.md 'add a "
            "rule'); every leaf must be placed on purpose"
        )

    return jax.tree_util.tree_map_with_path(place, tree)


def net_pspec(params, model_size: int, rules: Optional[Sequence[Rule]] = None):
    """Spec tree for one {w, b}-layer param list. Default rules are the
    per-depth MLP table (mlp_rules); pass `rules` for non-MLP nets."""
    return match_partition_rules(
        mlp_rules(len(params)) if rules is None else rules,
        params,
        model_size,
    )


def state_pspec(
    state: TrainState,
    mesh: Mesh,
    actor_rules: Optional[Sequence[Rule]] = None,
    critic_rules: Optional[Sequence[Rule]] = None,
) -> TrainState:
    """PartitionSpec tree mirroring TrainState 1:1. Actor/critic params,
    their targets, AND their Adam moments all derive from the same rule
    table per net — params and optimizer state can never shard
    differently. Scalars (step, SAC temperature machinery, Adam counts)
    replicate."""
    m = mesh.shape["model"]
    actor = net_pspec(state.actor_params, m, rules=actor_rules)
    critic = net_pspec(state.critic_params, m, rules=critic_rules)
    return TrainState(
        actor_params=actor,
        critic_params=critic,
        target_actor_params=actor,
        target_critic_params=critic,
        actor_opt=OptState(mu=actor, nu=actor, count=P()),
        critic_opt=OptState(mu=critic, nu=critic, count=P()),
        step=P(),
        # SAC temperature scalars replicate; None (non-SAC) is an empty
        # pytree node and needs no spec.
        log_alpha=None if state.log_alpha is None else P(),
        alpha_opt=(
            None
            if state.alpha_opt is None
            else OptState(mu=P(), nu=P(), count=P())
        ),
    )
