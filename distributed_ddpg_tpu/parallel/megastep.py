"""Anakin-style fused training megastep: rollout + ring scatter + sample
+ K learner updates as ONE jitted program per beat (config.fused_beat;
docs/FUSED_BEAT.md; PAPERS.md arXiv 2104.06272, with the device-resident
sample path per the in-network experience-sampling line, arXiv
2110.13506).

The dispatch-per-phase loop (train.py) issues three device programs per
steady-state iteration — the learner chunk, the device-actor rollout, and
the ring insert — with the host's Python between each enqueue. Every
piece already lives in HBM (device actors PR 9, sharded/replicated device
replay PR 10, the scanned learner chunk), so the host round-trips buy
nothing: this module composes the SAME pure bodies those subsystems
expose into one donated-carry program, reducing the host to a metronome
that dispatches beats and reads the one int32 health word.

One fused beat IS one steady-state loop iteration, in the loop's own
order:

  1. **sample + learn** — the learner's XLA-scan sampling chunk
     (`ShardedLearner.pure_scan_sample_fn`: uniform or PER, replicated or
     sharded storage, guarded or unguarded) draws K minibatches from the
     current ring and applies K updates;
  2. **rollout** — the device-actor scan (`DeviceActorPool.rollout_fn`)
     advances E envs for K_env steps with the FRESHLY-UPDATED actor
     params (exactly what the unfused loop's pointer-swap refresh +
     devactor_step does after each chunk);
  3. **scatter** — the rows land in the ring via the replay's pure insert
     body (`DeviceReplay.pure_insert_device_rows_fn`; PER additionally
     max-priority-stamps the landed run, `pure_stamp_fn`).

Because each leg is the IDENTICAL pure function the standalone dispatch
paths jit — same keys, same op order — a fused beat sequence is
bit-identical to the equivalent separate-dispatch sequence for fixed
seeds (tests/test_megastep.py pins uniform + PER, replicated + sharded).

Guardrails ride INSIDE the fused program: the PR-7 GuardState probe
(finite checks, EWMA z-score, tree-select quarantine, bad-row capture)
threads through the composed scan, the beat returns the per-chunk health
word, and `ShardedLearner.note_fused_health` hands it to the existing
host monitor — so `guardrails=True` no longer forces the unfused path;
the fast path is the safe path. (The bad-rollout caveat: a beat whose
learner leg gets quarantined still lands its rollout rows — they were
produced by the pre-rollback policy, which is ordinary replay data and
subject to the same row screen as everything else.)

Multi-host: the beat is one global SPMD program every process dispatches
at the same lockstep point (train.py drives it exactly where the chunk
dispatch sat), so per-process device-op order cannot fork; the lockstep /
shard_exchange ingest beats for HOST rows still ride the transfer
scheduler's ordered lane BETWEEN fused beats (ingest_once is unchanged).

Failure contract: the beat donates its whole carry (TrainState, sampling
key, ring storage/ptr/size, rollout carry, PER priorities, GuardState) at
dispatch, so there is no bounded-restart retry — a dispatch failure
surfaces immediately (the run_sample_chunk fallback's
donation-discipline, without the kernel's degrade leg: every composed
body is the already-proven XLA scan path). Rebuilds are automatic: the
learner's LR-backoff / support-expansion program rebuilds bump
`programs_version`, and the next run_beat recomposes against the fresh
bodies (one XLA recompile, same allowance discipline as the learner's
own rebuild).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import METRIC_KEYS, StepOutput
from distributed_ddpg_tpu.metrics import FusedBeatStats


def build_beat_body(learner, pool, replay, per: bool, guard: bool,
                    rows_per_beat: int):
    """The pure fused-beat body and its jit contract for one
    (per, guard) variant: `(beat, in_shardings, out_shardings,
    donate_argnums)`. FusedMegastep jits it directly (one beat per
    dispatch); parallel/superstep.py composes the SAME body B times
    inside one lax.fori_loop — sharing the construction is what makes
    superstep-vs-sequential bit-identity structural rather than
    coincidental."""
    L = learner
    mesh = L.mesh
    m = int(rows_per_beat)
    insert_fn = replay.pure_insert_device_rows_fn(m)
    stamp_fn = replay.pure_stamp_fn(m) if per else None
    rollout_fn = pool.rollout_fn
    sample_fn = L.pure_scan_sample_fn(per)

    replicated = NamedSharding(mesh, P())
    storage_sharding = NamedSharding(
        mesh, P("data", None) if replay.sharded else P(None, None)
    )
    prio_sharding = NamedSharding(
        mesh, P("data") if replay.sharded else P(None)
    )
    carry_sharding = pool._carry_sharding
    out_step = StepOutput(
        state=L._state_sharding,
        td_errors=NamedSharding(mesh, P(None, "data")),
        metrics={k: replicated for k in METRIC_KEYS},
    )

    # The beat bodies below are the loop iteration verbatim: learn on
    # the current ring, roll out with the updated params, scatter.
    # `ptr` is threaded through untouched by the learner leg; PER
    # stamps from the PRE-insert pointer (the insert_device_rows
    # ordering).
    if not per and not guard:

        def beat(state, key, storage, ptr, size, carry):
            out, key = sample_fn(state, key, storage, size)
            carry, rows = rollout_fn(out.state.actor_params, carry)
            storage, ptr, size = insert_fn(storage, rows, ptr, size)
            return out, key, storage, ptr, size, carry

        in_sh = (L._state_sharding, replicated, storage_sharding,
                 replicated, replicated, carry_sharding)
        out_sh = (out_step, replicated, storage_sharding,
                  replicated, replicated, carry_sharding)
        donate = (0, 1, 2, 3, 4, 5)
    elif not per and guard:

        def beat(state, key, storage, ptr, size, carry, g):
            out, key, g, health, bad_idx = sample_fn(
                state, key, storage, size, g
            )
            carry, rows = rollout_fn(out.state.actor_params, carry)
            storage, ptr, size = insert_fn(storage, rows, ptr, size)
            return (out, key, storage, ptr, size, carry, g, health,
                    bad_idx)

        in_sh = (L._state_sharding, replicated, storage_sharding,
                 replicated, replicated, carry_sharding, replicated)
        out_sh = (out_step, replicated, storage_sharding, replicated,
                  replicated, carry_sharding, replicated, replicated,
                  replicated)
        donate = (0, 1, 2, 3, 4, 5, 6)
    elif per and not guard:

        def beat(state, key, storage, ptr, size, carry, priorities,
                 maxp, beta, alpha, eps):
            out, key, priorities, maxp = sample_fn(
                state, key, storage, size, priorities, maxp, beta,
                alpha, eps,
            )
            carry, rows = rollout_fn(out.state.actor_params, carry)
            old_ptr = ptr
            storage, ptr, size = insert_fn(storage, rows, ptr, size)
            priorities = stamp_fn(priorities, maxp, old_ptr)
            return (out, key, storage, ptr, size, carry, priorities,
                    maxp)

        in_sh = (L._state_sharding, replicated, storage_sharding,
                 replicated, replicated, carry_sharding, prio_sharding,
                 replicated, replicated, replicated, replicated)
        out_sh = (out_step, replicated, storage_sharding, replicated,
                  replicated, carry_sharding, prio_sharding,
                  replicated)
        donate = (0, 1, 2, 3, 4, 5, 6)
    else:

        def beat(state, key, storage, ptr, size, carry, priorities,
                 maxp, beta, alpha, eps, g):
            out, key, priorities, maxp, g, health, bad_idx = sample_fn(
                state, key, storage, size, priorities, maxp, beta,
                alpha, eps, g,
            )
            carry, rows = rollout_fn(out.state.actor_params, carry)
            old_ptr = ptr
            storage, ptr, size = insert_fn(storage, rows, ptr, size)
            priorities = stamp_fn(priorities, maxp, old_ptr)
            return (out, key, storage, ptr, size, carry, priorities,
                    maxp, g, health, bad_idx)

        in_sh = (L._state_sharding, replicated, storage_sharding,
                 replicated, replicated, carry_sharding, prio_sharding,
                 replicated, replicated, replicated, replicated,
                 replicated)
        out_sh = (out_step, replicated, storage_sharding, replicated,
                  replicated, carry_sharding, prio_sharding,
                  replicated, replicated, replicated, replicated)
        donate = (0, 1, 2, 3, 4, 5, 6, 11)

    return beat, in_sh, out_sh, donate


class FusedMegastep:
    """One jitted beat program over (learner, device-actor pool, device
    replay) — see module docstring. Constructed by train.py when
    config.fused_beat resolves active; drives the live objects' state
    (learner.state/_key/_guard, pool carry, replay ring) exactly as the
    separate dispatches would."""

    def __init__(self, config: DDPGConfig, learner, pool, replay):
        self.config = config
        self.learner = learner
        self.pool = pool
        self.replay = replay
        self.per = bool(config.prioritized)
        self.guard = bool(learner.guard_enabled)
        self.chunk_size = int(learner.chunk_size)   # learner steps / beat
        self.rows_per_beat = int(pool.rows_per_chunk)
        self._stats = FusedBeatStats(seed=config.seed)
        self._build()

    def _build(self) -> None:
        beat, in_sh, out_sh, donate = build_beat_body(
            self.learner, self.pool, self.replay, self.per, self.guard,
            self.rows_per_beat,
        )
        self._beat = jax.jit(
            beat,
            in_shardings=in_sh,
            out_shardings=out_sh,
            donate_argnums=donate,
        )
        self._donate = donate
        self._learner_version = self.learner.programs_version

    # --- driving ---

    def run_beat(self, beta: Optional[float] = None) -> StepOutput:
        """Dispatch one fused beat against the live learner/pool/replay
        state and install every returned carry piece back where the
        separate dispatches would have left it. Returns the learner
        StepOutput (train.py's after_chunk consumes it unchanged)."""
        L, pool, replay = self.learner, self.pool, self.replay
        if self._learner_version != L.programs_version:
            # The learner rebuilt its chunk bodies (LR backoff, support
            # expansion): recompose the beat against the fresh bodies so
            # fused and unfused always run the same effective config.
            self._build()
        t0 = time.perf_counter()
        with replay.dispatch_lock:
            with trace.span(
                "fused_beat", rows=self.rows_per_beat,
                steps=self.chunk_size,
            ):
                if self.per:
                    scalars = (
                        np.float32(beta), np.float32(replay.alpha),
                        np.float32(replay.eps),
                    )
                    if self.guard:
                        (out, key, storage, ptr, size, carry, prios, maxp,
                         g, health, bad_idx) = self._beat(
                            L.state, L._key, replay.storage, replay.ptr,
                            replay.size, pool._carry, replay.priorities,
                            replay.max_priority, *scalars, L._guard,
                        )
                        L.note_fused_health(g, health, bad_idx)
                    else:
                        (out, key, storage, ptr, size, carry, prios,
                         maxp) = self._beat(
                            L.state, L._key, replay.storage, replay.ptr,
                            replay.size, pool._carry, replay.priorities,
                            replay.max_priority, *scalars,
                        )
                    replay.set_per_state(prios, maxp)
                else:
                    if self.guard:
                        (out, key, storage, ptr, size, carry, g, health,
                         bad_idx) = self._beat(
                            L.state, L._key, replay.storage, replay.ptr,
                            replay.size, pool._carry, L._guard,
                        )
                        L.note_fused_health(g, health, bad_idx)
                    else:
                        out, key, storage, ptr, size, carry = self._beat(
                            L.state, L._key, replay.storage, replay.ptr,
                            replay.size, pool._carry,
                        )
                L.state = out.state
                L._key = key
                replay.storage, replay.ptr, replay.size = storage, ptr, size
                replay.note_device_rows(self.rows_per_beat)
            dt = time.perf_counter() - t0
        pool.absorb_fused_chunk(carry, dt)
        self._stats.record_beat(self.chunk_size, self.rows_per_beat, dt)
        return out

    # --- host-side views ---

    def snapshot(self) -> dict:
        """fused_* observability fields (metrics.FusedBeatStats;
        docs/OBSERVABILITY.md) for the train/final records."""
        return self._stats.snapshot()

    def example_args(self, beta: float = 1.0):
        """The live argument tuple the beat program traces over — the
        program-contract analyzer hook below feeds it to BuiltProgram."""
        L, pool, replay = self.learner, self.pool, self.replay
        args = [L.state, L._key, replay.storage, replay.ptr, replay.size,
                pool._carry]
        if self.per:
            args += [replay.priorities, replay.max_priority,
                     np.float32(beta), np.float32(replay.alpha),
                     np.float32(replay.eps)]
        if self.guard:
            args.append(L._guard)
        return tuple(args)


# ---------------------------------------------------------------------------
# program-contract analyzer hook (analysis/programs.py; docs/ANALYSIS.md
# "Layer 2")
# ---------------------------------------------------------------------------


def program_specs():
    """The fused beat family, built tiny (4 probe envs x rollout chunk 2,
    learner chunk 2, 64-row ring) under the 2-device CPU probe mesh:
    uniform + PER x replicated + sharded x guarded + unguarded. The
    guarded/unguarded pair of each shape dispatches at the SAME lockstep
    site (train.py picks per config), so they share a beat_group; the
    donated carry (TrainState + key + ring + rollout carry + priorities +
    GuardState) must alias through the lowered artifact — the whole point
    of a fused beat is NOT paying 2x HBM on its carry."""
    from distributed_ddpg_tpu.analysis.programs import (
        BuiltProgram,
        ProgramSpec,
        probe_config,
        probe_mesh,
    )
    from distributed_ddpg_tpu.actors.device_pool import DeviceActorPool
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import (
        DevicePrioritizedReplay,
        DeviceReplay,
    )

    OWNER = "parallel/megastep.py"
    cache = {}

    def megastep(
        guard: bool, per: bool, sharded: bool, tp: bool = False
    ) -> FusedMegastep:
        key = (guard, per, sharded, tp)
        if key not in cache:
            placement = "sharded" if sharded else "replicated"
            config = probe_config(
                actor_backend="device",
                num_actors=0,
                device_actor_envs=4,
                device_actor_chunk=2,
                guardrails=guard,
                prioritized=per,
                replay_sharding=placement,
                fused_chunk="off",
                fused_beat="on",
                model_axis=2 if tp else 1,
            )
            mesh = probe_mesh(2 if tp else 1)
            pool = DeviceActorPool(config, mesh=mesh)
            learner = ShardedLearner(
                config,
                pool.obs_dim,
                pool.act_dim,
                pool.action_scale,
                action_offset=pool.action_offset,
                mesh=mesh,
                chunk_size=2,
                replay_sharding=placement,
            )
            replay_cls = DevicePrioritizedReplay if per else DeviceReplay
            replay = replay_cls(
                64, pool.obs_dim, pool.act_dim, mesh=mesh, block_size=8,
                async_ship=False, replay_sharding=placement,
            )
            cache[key] = FusedMegastep(config, learner, pool, replay)
        return cache[key]

    def build(guard: bool, per: bool, sharded: bool, tp: bool = False):
        def _build():
            ms = megastep(guard, per, sharded, tp)
            return BuiltProgram(ms._beat, ms.example_args(), ms._donate)
        return _build

    specs = []
    for per, kind in ((False, "uniform"), (True, "per")):
        for sharded in (False, True):
            shard_tag = ".sharded" if sharded else ""
            for guard in (False, True):
                tag = ".guarded" if guard else ""
                specs.append(ProgramSpec(
                    f"megastep.beat.{kind}{shard_tag}{tag}",
                    OWNER,
                    build(guard, per, sharded),
                    beat_group=f"megastep-beat-{kind}{shard_tag}",
                ))
        # TP variant (docs/MESH.md): the full fused composition — sharded
        # ring on 'data' x params on 'model' — under the (2, 2) probe
        # mesh. It SHARES the 1D sharded beat's beat_group: the
        # explicitly-staged exchange must match that beat's order (a pod
        # mixing TP degrees would fork), and the group check enforces the
        # cross-variant equality a lone golden diff could quietly drop.
        specs.append(ProgramSpec(
            f"megastep.beat.{kind}.sharded.tp",
            OWNER,
            build(False, per, True, tp=True),
            beat_group=f"megastep-beat-{kind}.sharded",
        ))
    return specs
