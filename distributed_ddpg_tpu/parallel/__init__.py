from distributed_ddpg_tpu.parallel.mesh import (
    batch_pspec,
    make_mesh,
    state_pspec,
)
from distributed_ddpg_tpu.parallel.learner import ShardedLearner

__all__ = ["make_mesh", "state_pspec", "batch_pspec", "ShardedLearner"]
